"""Live telemetry exposition + host resource telemetry (ISSUE 19).

Two exports over the existing MetricsRegistry fabric, both stdlib-only:

* ``render_exposition`` — a Prometheus-style text rendering of one
  registry snapshot.  Counters and gauges map 1:1; histograms render as
  summaries (p50/p90/p99 quantile lines + ``_count``/``_sum``/``_max``).
  The snapshot IS the lock-safety: ``MetricsRegistry.snapshot(
  reset=False)`` copies every structure under the registry lock, so a
  scrape can never observe a half-written histogram ring.  Served at
  ``GET /metrics`` on ServeTier (serve/server.py) and by the standalone
  ``MetricsExporter`` below for training/stream runs
  (``Config.obs_export_port``).

* ``ResourceSampler`` — process resource telemetry (RSS, CPU seconds,
  thread count, open fds, GC collections) as ``resource`` JSONL rows
  plus ``obs.resource.*`` registry gauges, so a leak or a CPU-bound
  straggler lands in the same stream as the metrics it distorts.
  Inline (``sample()`` from the serve CLI stats tick) or threaded
  (``start()``/``close()`` from the Trainer, XF006 lifecycle).

docs/OBSERVABILITY.md "Operating a live fleet" documents the format.
"""

from __future__ import annotations

import gc
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from xflow_tpu.obs.registry import Snapshot
from xflow_tpu.obs.schema import resource_row

# quantile label in the exposition -> key in Histogram.summary()
_QUANTILES = (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99"))

# per-connection socket deadline on the standalone exporter (XF017
# discipline even though obs/ is outside the rule's static domain: a
# scraper that stalls mid-request must not pin a handler thread)
EXPORTER_TIMEOUT_S = 10.0


def metric_name(name: str, prefix: str = "xflow") -> str:
    """Registry name -> exposition name: ``serve.e2e.b8`` ->
    ``xflow_serve_e2e_b8`` ([a-zA-Z0-9_] only, prefixed)."""
    safe = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if safe and safe[0].isdigit():
        safe = "_" + safe
    return f"{prefix}_{safe}" if prefix else safe


def _fmt(v: float) -> str:
    # repr keeps full float precision (round-trip exactness is what the
    # scrape-vs-snapshot parity gate checks); integers render bare
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_exposition(snap: Snapshot, prefix: str = "xflow") -> str:
    """One registry snapshot as Prometheus text exposition format.

    Rendered names sort deterministically; every histogram becomes a
    summary family: quantile lines over the percentile ring, ``_count``
    and ``_sum`` (= count * mean) over all time, ``_max`` as a
    companion gauge (not part of the summary spec, but the watermark is
    too diagnostic to drop)."""
    out: list[str] = []
    for name in sorted(snap.counters):
        m = metric_name(name, prefix)
        out.append(f"# TYPE {m} counter")
        out.append(f"{m} {_fmt(snap.counters[name])}")
    for name in sorted(snap.gauges):
        m = metric_name(name, prefix)
        out.append(f"# TYPE {m} gauge")
        out.append(f"{m} {_fmt(snap.gauges[name])}")
    for name in sorted(snap.hists):
        m = metric_name(name, prefix)
        h = snap.hists[name]
        out.append(f"# TYPE {m} summary")
        for label, key in _QUANTILES:
            out.append(f'{m}{{quantile="{label}"}} {_fmt(h[key])}')
        count = h.get("count", 0)
        out.append(f"{m}_count {_fmt(count)}")
        out.append(f"{m}_sum {_fmt(h.get('mean', 0.0) * count)}")
        out.append(f"# TYPE {m}_max gauge")
        out.append(f"{m}_max {_fmt(h.get('max', 0.0))}")
    return "\n".join(out) + "\n"


def parse_exposition(text: str) -> dict[str, dict]:
    """Inverse of ``render_exposition`` (tests + the check_live_obs
    gate): ``{"counter": {name: v}, "gauge": {...}, "summary":
    {name: {"0.5": v, "0.9": v, "0.99": v, "count": n, "sum": s,
    "max": m}}}`` keyed by EXPOSITION names."""
    types: dict[str, str] = {}
    out: dict[str, dict] = {"counter": {}, "gauge": {}, "summary": {}}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        name, value = line.rsplit(None, 1)
        v = float(value)
        if "{" in name:
            base, label = name.split("{", 1)
            q = label.split('"')[1]
            out["summary"].setdefault(base, {})[q] = v
        elif types.get(name) == "counter":
            out["counter"][name] = v
        elif types.get(name) == "gauge":
            base = name[: -len("_max")] if name.endswith("_max") else ""
            if types.get(base) == "summary":
                out["summary"].setdefault(base, {})["max"] = v
            else:
                out["gauge"][name] = v
        else:
            for suffix in ("_count", "_sum"):
                if name.endswith(suffix):
                    base = name[: -len(suffix)]
                    out["summary"].setdefault(base, {})[suffix[1:]] = v
                    break
    return out


# -- host resource telemetry ----------------------------------------------


def sample_resources() -> dict:
    """One stdlib-only ``resource`` row body for this process."""
    rss = 0
    try:
        with open("/proc/self/statm") as f:
            rss = int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        try:  # portable fallback: peak RSS, in KiB on Linux
            import resource as _resource

            rss = _resource.getrusage(
                _resource.RUSAGE_SELF
            ).ru_maxrss * 1024
        except (ImportError, OSError):
            rss = 0
    times = os.times()
    cpu = times.user + times.system
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        fds = 0
    collections = sum(s.get("collections", 0) for s in gc.get_stats())
    return resource_row(
        rss_bytes=rss,
        cpu_seconds=cpu,
        threads=threading.active_count(),
        open_fds=fds,
        gc_collections=collections,
    )


class ResourceSampler:
    """Periodic (or caller-paced) host resource sampling.

    ``sample()`` emits one ``resource`` JSONL row through the metrics
    logger and mirrors the values into ``obs.resource.*`` gauges so
    the live ``/metrics`` exposition carries them too.  ``start()``
    spawns a sampling thread for runs whose main thread is busy
    training; the serve CLI instead calls ``sample()`` from its stats
    tick — same row, no extra thread.  The thread emits one row
    immediately (short runs still carry data) and one final row at
    ``close()``, and is joined with a timeout (XF006)."""

    def __init__(self, metrics_logger=None, registry=None,
                 interval_s: float = 30.0):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.metrics_logger = metrics_logger
        self.registry = registry
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sample(self) -> dict:
        body = sample_resources()
        if self.metrics_logger is not None:
            self.metrics_logger.log("resource", body)
        if self.registry is not None:
            for key in ("rss_bytes", "cpu_seconds", "threads",
                        "open_fds", "gc_collections"):
                self.registry.gauge_set(
                    "obs.resource." + key, float(body[key])
                )
        return body

    def _pulse(self) -> None:
        # liveness watermark for the loop below (XF009 heartbeat
        # surface): a wedged sampler shows as a stale beat gauge in
        # the very exposition it feeds
        if self.registry is not None:
            self.registry.gauge_set(
                "obs.resource.beat_unix", time.time()
            )

    def _run(self) -> None:
        self.sample()
        while not self._stop.wait(self.interval_s):
            self._pulse()
            self.sample()

    def start(self) -> "ResourceSampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="resource-sampler", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        """Idempotent: stop the thread (joined with a timeout), then
        emit one final sample while the metrics logger is still open."""
        first = not self._stop.is_set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if first:
            self.sample()

    def __enter__(self) -> "ResourceSampler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- standalone exporter (training/stream runs) ---------------------------


class _ExporterHandler(BaseHTTPRequestHandler):
    server_version = "xflow-exporter/1"
    protocol_version = "HTTP/1.1"

    def setup(self) -> None:
        # same rationale as serve/server.py _Handler.setup: the class
        # attribute `timeout` is None, so a scraper that stalls
        # mid-request would pin this handler thread indefinitely
        self.timeout = self.server.exporter.timeout_s  # type: ignore[attr-defined]
        super().setup()

    def log_message(self, fmt, *args) -> None:
        pass  # a scrape is not stderr chatter

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        exporter = self.server.exporter  # type: ignore[attr-defined]
        try:
            if self.path == "/metrics":
                payload = exporter.render().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                code = 200
            elif self.path == "/healthz":
                payload = json.dumps({"status": "exporting"}).encode()
                ctype = "application/json"
                code = 200
            else:
                payload = b"not found: try /metrics\n"
                ctype = "text/plain"
                code = 404
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except (ConnectionError, TimeoutError, OSError):
            pass  # scraper went away mid-response: its loss, not ours


class _ExporterServer(ThreadingHTTPServer):
    daemon_threads = True
    # bounded accept backlog; a scraper is never latency-critical
    request_queue_size = 8


class MetricsExporter:
    """Standalone ``GET /metrics`` endpoint for runs that have no HTTP
    surface of their own (training, stream driver).  One accept thread
    (stdlib ``serve_forever``), per-connection socket deadlines
    (``EXPORTER_TIMEOUT_S``), reaped via ``close()`` with a timed join
    (XF006) — the Trainer owns the lifecycle when
    ``Config.obs_export_port`` is set."""

    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = EXPORTER_TIMEOUT_S, extra=None):
        if timeout_s <= 0:
            raise ValueError("timeout_s must be > 0")
        self.registry = registry
        self.timeout_s = timeout_s
        # optional () -> str appended to the exposition (e.g. a serve
        # tier pooling several registries)
        self.extra = extra
        self._httpd = _ExporterServer((host, port), _ExporterHandler)
        self._httpd.exporter = self  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def render(self) -> str:
        text = render_exposition(self.registry.snapshot(reset=False))
        if self.extra is not None:
            text += self.extra()
        return text

    def _serve(self) -> None:
        # stdlib accept loop; poll_interval bounds shutdown latency
        self._httpd.serve_forever(poll_interval=0.2)

    def start(self) -> "MetricsExporter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._serve, name="metrics-exporter", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
