"""Counters / gauges / histograms for pipeline-health metrics.

The registry is epoch-scoped by convention: the trainer resets it at
epoch start and snapshots it at epoch end, so every ``train_epoch`` /
``eval`` JSONL record carries exactly that window's phase seconds,
stall time, and step-time percentiles (docs/OBSERVABILITY.md).

Thread-safety: loader parse/pack and transfer-ahead h2d phases run on
worker threads, so every mutation takes a (cheap, uncontended) lock.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


class Histogram:
    """Sliding-window value recorder with percentile summaries.

    Keeps the newest ``capacity`` observations in a ring (plus exact
    running count/sum/max), so percentiles reflect the recent window
    and memory stays bounded on arbitrarily long runs.  Step-time p50/
    p90/p99 are the intended use; 4096 samples cover several epochs of
    toy runs and a representative window of production ones.

    ``summary()`` windows: ``count``/``sum``/``mean``/``max`` are exact
    ALL-TIME aggregates; the percentiles and ``window_max`` cover only
    the retained ring.  (``max`` used to silently switch to the window
    once the ring wrapped — a one-off spike older than ``capacity``
    observations vanished from the summary.)
    """

    __slots__ = ("capacity", "count", "sum", "max", "_vals")

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self.count = 0
        self.sum = 0.0
        self.max = float("-inf")
        self._vals: list[float] = []

    def observe(self, v: float) -> None:
        if self.count < self.capacity:
            self._vals.append(v)
        else:
            self._vals[self.count % self.capacity] = v
        self.count += 1
        self.sum += v
        if v > self.max:
            self.max = v

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the retained window (p in
        [0, 100]); 0.0 when empty."""
        if not self._vals:
            return 0.0
        s = sorted(self._vals)
        idx = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
        return s[idx]

    def summary(self) -> dict[str, float]:
        return {
            "count": self.count,
            "mean": self.sum / self.count if self.count else 0.0,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
            "max": self.max if self.count else 0.0,
            "window_max": max(self._vals) if self._vals else 0.0,
        }


@dataclass
class Snapshot:
    """One reset-window's worth of metrics, as plain dicts."""

    counters: dict[str, float] = field(default_factory=dict)
    gauges: dict[str, float] = field(default_factory=dict)
    hists: dict[str, dict[str, float]] = field(default_factory=dict)

    def phase_seconds(self) -> dict[str, float]:
        """Counters under the ``phase.`` namespace, name-stripped —
        the per-phase wall-second accounting."""
        pre = "phase."
        return {
            k[len(pre):]: v for k, v in self.counters.items()
            if k.startswith(pre)
        }


class MetricsRegistry:
    enabled = True

    def __init__(self, hist_capacity: int = 4096):
        self._lock = threading.Lock()
        self._hist_capacity = hist_capacity
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Histogram] = {}

    def counter_add(self, name: str, v: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + v

    def gauge_set(self, name: str, v: float) -> None:
        with self._lock:
            self._gauges[name] = v

    def observe(self, name: str, v: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(self._hist_capacity)
            h.observe(v)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    def snapshot(self, reset: bool = False) -> Snapshot:
        with self._lock:
            snap = Snapshot(
                counters=dict(self._counters),
                gauges=dict(self._gauges),
                hists={k: h.summary() for k, h in self._hists.items()},
            )
            if reset:
                self._counters.clear()
                self._gauges.clear()
                self._hists.clear()
        return snap


class NullRegistry:
    """Disabled registry: no-ops, empty snapshots, nothing retained."""

    __slots__ = ()
    enabled = False

    def counter_add(self, name: str, v: float = 1.0) -> None:
        pass

    def gauge_set(self, name: str, v: float) -> None:
        pass

    def observe(self, name: str, v: float) -> None:
        pass

    def reset(self) -> None:
        pass

    def snapshot(self, reset: bool = False) -> Snapshot:
        return Snapshot()


NULL_REGISTRY = NullRegistry()
