"""``obs merge`` + ``obs doctor`` — first-responder forensics.

``merge`` combines per-host metrics JSONL files into one rank-tagged,
time-aligned stream: every row gains ``rank`` (from its file's
``run_start`` header) and ``time_unix`` (the header's wall-clock epoch
plus the row's relative ``t``), and rows sort globally by that clock.
Extra fields are schema-compatible (validators check required fields
only), so a merged file still passes ``obs validate``.

``doctor`` reads one run stream (merged or single-host), optionally a
flight dump (obs/flight.py) and a bench artifact, and prints a RANKED
diagnosis instead of raw JSONL:

* watchdog ``health`` rows → dominant stall cause with trip counts and
  worst silence;
* SLO ``alert`` rows (obs/live.py burn-rate evaluator) → rules still
  firing at end of stream (warn) vs fired-and-resolved (info);
* flight dump → why the run died and what every thread was doing;
* phase accounting → the dominant wall-clock phase, with an
  input-bound callout when stalls dominate;
* per-rank step-time skew → straggler host callout (merged streams);
* per-stream input fan-out skew (``stream`` rows, io/fanout.py) →
  stream-straggler callout, same 1.3x rule on active throughput;
* step-time shape → bimodality (p99 ≫ p50 while p90 stays near p50)
  as recompile suspicion;
* serving tier → shed-storm windows (``serve_shed`` rows where
  admission control rejected most offered traffic — blamed on
  capacity, explicitly NOT on the queue) and canary-stuck rollouts
  (a ``rollout`` stream that ends on ``begin``/``canary``);
* retrieval→ranking cascade → candidate starvation (``cascade`` rows
  where the retrieval stage answered with fewer than the requested k)
  and per-stage p99 attribution (a slow cascade blames the right
  fleet by name);
* continuous training → servable-stale streams (``freshness`` rows,
  docs/CONTINUOUS.md): last newest-event-age over its SLO, rollouts
  repeatedly aborting, or begins that never commit;
* chaos fabric → ``chaos`` rows correlated with the self-healing
  ``health`` causes: fault storm vs isolated recovery, with
  ``quarantine_budget_exceeded`` (data corruption, not an input
  stall) and unrevived ``replica_evicted`` blamed by name
  (docs/ROBUSTNESS.md);
* bench artifact → degraded-bench detection (``degraded: true``).

Severity ranks ``crit`` > ``warn`` > ``info``; the CLI exits 0 only
when nothing at ``warn`` or above surfaced — "run one command, get a
verdict" (scripts/check_doctor_smoke.py gates the healthy-run path).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from xflow_tpu.obs.schema import load_jsonl, load_jsonl_tolerant
from xflow_tpu.obs.summary import split_runs

# straggler: slowest rank's mean step-time p50 vs the fleet median
STRAGGLER_RATIO = 1.3
# input-bound: TIME-WEIGHTED input_stall fraction of total wall-clock
# (0.44 steady-state stall is normal for a CPU toy run; 0.5+ of the
# whole run means the device mostly waited)
INPUT_BOUND_FRAC = 0.5
# bimodality: p99 >= BIMODAL_P99 * p50 while p90 <= BIMODAL_P90 * p50
# (a fat smooth tail raises p90 too; a recompile spike does not).
# Each run's FIRST epoch row is exempt — it legitimately contains the
# process's one-time XLA compile, which IS a giant outlier step.
BIMODAL_P99 = 3.0
BIMODAL_P90 = 1.5
# ... and the spike must be material in ABSOLUTE terms: on
# millisecond-scale toy steps, OS scheduler noise on a loaded CI box
# alone produces 3x-p50 tails (observed flaking the tier-1 doctor
# smoke at a 10ms floor too — a 5.5ms-p50 toy run under full-suite
# load showed a 19.9ms p99, pure scheduler noise), while a real XLA
# recompile costs tens of ms at minimum.
BIMODAL_MIN_EXCESS_S = 0.025
# store-thrash: a tiered run (``store`` rows, docs/STORE.md) whose hot
# tier still serves under this occurrence share AFTER the warmup epoch
# while promotions/demotions keep churning — the working set does not
# fit the configured hot capacity.
STORE_THRASH_HIT_RATE = 0.5
# shed storm: a ``serve_shed`` window (serve/fleet.py admission
# control) where rejections dominate offered traffic.  The floor on
# absolute sheds keeps a 3-request toy window from reading as a storm.
SHED_STORM_FRAC = 0.5
SHED_STORM_MIN_TOTAL = 20

# tail attribution over reqtrace request spans (obs/reqtrace.py): the
# slowest-k exemplars must be BOTH a multiple of the all-request p50
# and a hard absolute excess above it before the tail is called
# anomalous — the ratio alone trips on millisecond scheduler noise in
# toy runs, the floor alone trips on any genuinely slow tier.  The
# row floor keeps a handful of warmup requests from electing a
# dominant phase.
REQTRACE_MIN_REQUESTS = 20
REQTRACE_TAIL_RATIO = 3.0
REQTRACE_TAIL_MIN_EXCESS_S = 0.05
REQTRACE_SLOW_K = 3

# health causes owned by the self-healing fabric (xflow_tpu/chaos/,
# docs/ROBUSTNESS.md): routed to _check_chaos for a named diagnosis —
# _check_health must NOT read them as watchdog stall trips (a
# quarantine abort is data corruption, not an input stall; an evicted
# replica is a capacity event, not a queue bug).
_SELF_HEAL_CAUSES = {
    "record_quarantined",
    "quarantine_budget_exceeded",
    "checkpoint_fallback",
    "checkpoint_save_failed",
    "replica_evicted",
    "replica_revived",
    "replica_revive_failed",
    "store_promote_restarted",
    "store_promote_dead",
}
# the subset that means "the fault was absorbed and service recovered"
_SELF_HEAL_RECOVERIES = {
    "replica_revived",
    "store_promote_restarted",
    "checkpoint_fallback",
}
# fault storm: this many injected/absorbed faults in one stream stops
# reading as "isolated recovery"
CHAOS_STORM_MIN = 10

_SEV_ORDER = {"crit": 0, "warn": 1, "info": 2}


@dataclass
class Diagnosis:
    severity: str  # "crit" | "warn" | "info"
    code: str  # short machine-greppable tag
    message: str


# -- merge ------------------------------------------------------------------


def merge_rows(paths: list[str]) -> list[dict]:
    """Rank-tagged, time-aligned union of per-host metrics files."""
    return merge_rows_tolerant(paths)[0]


def merge_rows_tolerant(paths: list[str]) -> tuple[list[dict], int]:
    """``merge_rows`` plus the count of torn final lines skipped: a
    file that is still being APPENDED to legitimately ends mid-line,
    and merging live files is exactly what `obs live` and a mid-run
    `obs merge` do.  Torn middles still raise (corruption)."""
    merged: list[dict] = []
    skipped = 0
    for path in paths:
        rows, torn = load_jsonl_tolerant(path)
        skipped += torn
        for run in split_runs(rows):
            header = run.header or {}
            rank = int(header.get("rank", 0))
            t0 = float(header.get("time_unix", 0.0))
            run_id = str(header.get("run_id", ""))
            rows = ([header] if run.header else []) + run.rows
            for row in rows:
                out = dict(row)
                out.setdefault("rank", rank)
                # run_id tag: time-sorting interleaves the per-host
                # streams, so split_runs no longer recovers run
                # membership — the explicit tag does
                out.setdefault("run_id", run_id)
                out.setdefault(
                    "time_unix", round(t0 + float(row.get("t", 0.0)), 3)
                )
                merged.append(out)
    merged.sort(key=lambda r: r.get("time_unix", 0.0))
    return merged, skipped


def write_jsonl(rows: list[dict], f) -> None:
    for row in rows:
        f.write(json.dumps(row, sort_keys=True) + "\n")


# -- doctor checks ----------------------------------------------------------


def _rank_of(row: dict, header_rank: int) -> int:
    return int(row.get("rank", header_rank))


def _epoch_rows(rows: list[dict]) -> list[tuple[int, dict]]:
    """(rank, train_epoch row) pairs across every run in the stream."""
    out = []
    for run in split_runs(rows):
        hr = int((run.header or {}).get("rank", 0))
        for e in run.epochs:
            out.append((_rank_of(e, hr), e))
    return out


def _warm_epoch_rows(rows: list[dict]) -> list[dict]:
    """train_epoch rows EXCLUDING each (rank, run)'s first one: every
    fresh process (initial or resumed — a resume is a new run_start)
    pays the one-time XLA compile in its first epoch, which is a
    legitimate giant step-time outlier, not a recompile bug.

    Grouping is by the rows' rank/run_id tags when present (merged
    streams interleave hosts by wall-clock, so split_runs alone puts
    every row in the LAST header's run and would exempt only one
    host's warmup); unmerged files fall back to split_runs order."""
    groups: dict = {}
    for i, run in enumerate(split_runs(rows)):
        header = run.header or {}
        hr = header.get("rank", 0)
        hid = header.get("run_id", i)
        for e in run.epochs:
            key = (e.get("rank", hr), e.get("run_id", hid))
            groups.setdefault(key, []).append(e)
    out = []
    for epochs in groups.values():
        # stream order is time order (merge sorts; single files append)
        out.extend(epochs[1:])
    return out


def _check_health(rows: list[dict]) -> list[Diagnosis]:
    trips: dict[str, list[dict]] = {}
    recovered: dict[str, float] = {}
    for r in rows:
        if r.get("kind") != "health":
            continue
        cause = r.get("cause", "?")
        if cause in _SELF_HEAL_CAUSES:
            continue  # _check_chaos owns the named diagnosis
        if cause.startswith("recovered:"):
            orig = cause.split(":", 1)[1]
            recovered[orig] = max(
                recovered.get(orig, 0.0), float(r.get("silence_seconds", 0))
            )
        else:
            trips.setdefault(cause, []).append(r)
    out = []
    for cause, events in sorted(
        trips.items(), key=lambda kv: -len(kv[1])
    ):
        worst = max(
            [float(e.get("silence_seconds", 0)) for e in events]
            + [recovered.get(cause, 0.0)]
        )
        ranks = sorted({_rank_of(e, 0) for e in events})
        out.append(Diagnosis(
            "crit",
            cause,
            f"watchdog tripped {len(events)}x: {cause} on channel "
            f"{events[-1].get('channel', '?')!r} (worst silence "
            f"{worst:.1f}s over threshold "
            f"{events[-1].get('threshold_seconds', 0)}s, rank(s) "
            f"{ranks})",
        ))
    dumps = [r for r in rows if r.get("kind") == "flight_dump"]
    for d in dumps:
        out.append(Diagnosis(
            "info",
            "flight_dump_row",
            f"flight dump recorded at {d.get('path', '?')} (reason "
            f"{d.get('reason', '?')!r}, active phase "
            f"{d.get('active_phase', '?')!r}) — pass it via --flight "
            "for thread stacks",
        ))
    return out


def _check_alerts(rows: list[dict]) -> list[Diagnosis]:
    """``alert`` rows (obs/live.py AlertEvaluator) as first-class
    evidence: a rule whose LAST transition is still ``firing`` is an
    open problem; a fire→resolve pair is context worth naming (the
    SLO was breached mid-run even though it recovered)."""
    last: dict[str, dict] = {}
    fired: dict[str, int] = {}
    for r in rows:
        if r.get("kind") != "alert":
            continue
        rule = str(r.get("rule", "?"))
        last[rule] = r
        if r.get("state") == "firing":
            fired[rule] = fired.get(rule, 0) + 1
    out = []
    for rule, r in sorted(last.items()):
        n = fired.get(rule, 0)
        if r.get("state") == "firing":
            out.append(Diagnosis(
                "warn",
                "alert_firing",
                f"alert {rule} is FIRING (fired {n}x, last value "
                f"{r.get('value')} vs threshold {r.get('threshold')} "
                f"over {r.get('short_s')}s/{r.get('long_s')}s "
                f"windows): {r.get('detail', '')}",
            ))
        elif n:
            out.append(Diagnosis(
                "info",
                "alert_resolved",
                f"alert {rule} fired {n}x and resolved (last value "
                f"{r.get('value')} vs threshold "
                f"{r.get('threshold')}) — the SLO was breached "
                "mid-run even though it recovered",
            ))
    return out


def _check_phases(rows: list[dict]) -> list[Diagnosis]:
    epochs = [e for _, e in _epoch_rows(rows)]
    if not epochs:
        return []
    totals: dict[str, float] = {}
    wall = 0.0
    for e in epochs:
        wall += float(e.get("seconds", 0.0))
        for k, v in (e.get("phases") or {}).items():
            totals[k] = totals.get(k, 0.0) + float(v)
    if not totals or wall <= 0:
        return []
    name, secs = max(totals.items(), key=lambda kv: kv[1])
    out = [Diagnosis(
        "info",
        "dominant_phase",
        f"dominant phase: {name} ({secs:.2f}s, {100 * secs / wall:.0f}% "
        f"of {wall:.2f}s wall over {len(epochs)} epoch row(s))",
    )]
    stall = totals.get("input_stall", 0.0) / wall  # time-weighted
    if stall >= INPUT_BOUND_FRAC:
        out.append(Diagnosis(
            "warn",
            "input_bound",
            f"input-bound: input_stall is {100 * stall:.0f}% of total "
            "wall-clock — the device is waiting on data (check loader "
            "throughput in the shard rows, parse workers, prefetch "
            "depth)",
        ))
    return out


def _check_stragglers(rows: list[dict]) -> list[Diagnosis]:
    per_rank: dict[int, list[float]] = {}
    for rank, e in _epoch_rows(rows):
        p50 = float(e.get("step_time_p50", 0.0))
        if p50 > 0:
            per_rank.setdefault(rank, []).append(p50)
    if len(per_rank) < 2:
        return []
    means = {
        rank: sum(v) / len(v) for rank, v in per_rank.items()
    }
    # lower-middle median: with an even rank count (2 hosts being the
    # common case) the candidate straggler must compare against the
    # FASTER half, not against itself
    ordered = sorted(means.values())
    median = ordered[(len(ordered) - 1) // 2]
    if median <= 0:
        return []
    worst_rank, worst = max(means.items(), key=lambda kv: kv[1])
    if worst < median * STRAGGLER_RATIO:
        return [Diagnosis(
            "info",
            "rank_skew",
            f"step-time skew across {len(means)} ranks is "
            f"{worst / median:.2f}x (max/median) — within the "
            f"{STRAGGLER_RATIO}x straggler threshold",
        )]
    return [Diagnosis(
        "warn",
        "straggler",
        f"straggler: rank {worst_rank} mean step-time p50 "
        f"{1e3 * worst:.2f}ms is {worst / median:.2f}x the fleet "
        f"median ({1e3 * median:.2f}ms) across {len(means)} ranks — "
        "every synced step waits for it (slow host, shard skew, or "
        "thermal throttling)",
    )]


def _check_streams(rows: list[dict]) -> list[Diagnosis]:
    """Input fan-out stream skew (``stream`` rows, io/fanout.py) —
    the per-rank straggler rule applied to reader streams: a stream
    whose ACTIVE throughput (examples over its measured
    read+parse+compact seconds — a stream parked behind a saturated
    consumer is not slow) lags the stream median by the straggler
    ratio holds the whole serial-order merge back, because every
    later shard it owns gates the consumer."""
    per_stream: dict[int, list[float]] = {}
    for r in rows:
        if r.get("kind") != "stream":
            continue
        eps = float(r.get("examples_per_sec", 0.0))
        if eps > 0:
            per_stream.setdefault(int(r.get("stream", 0)), []).append(eps)
    if len(per_stream) < 2:
        return []
    means = {s: sum(v) / len(v) for s, v in per_stream.items()}
    # upper-middle median: the candidate straggler (SLOWEST stream)
    # must compare against the faster half, mirroring _check_stragglers
    ordered = sorted(means.values())
    median = ordered[len(ordered) // 2]
    worst_stream, worst = min(means.items(), key=lambda kv: kv[1])
    if worst <= 0 or median <= 0:
        return []
    ratio = median / worst
    if ratio < STRAGGLER_RATIO:
        return [Diagnosis(
            "info",
            "stream_skew",
            f"input-stream throughput skew across {len(means)} streams "
            f"is {ratio:.2f}x (median/min) — within the "
            f"{STRAGGLER_RATIO}x straggler threshold",
        )]
    return [Diagnosis(
        "warn",
        "stream_straggler",
        f"input-stream straggler: stream {worst_stream} mean "
        f"{worst:.0f} ex/s is {ratio:.2f}x slower than the stream "
        f"median ({median:.0f} ex/s) across {len(means)} streams — "
        "the serial-order merge waits on every shard it owns (shard "
        "size skew, a slow disk, or parse contention; stall_seconds "
        "in its stream rows says whether it was actually consumer-"
        "bound)",
    )]


def _check_bimodality(rows: list[dict]) -> list[Diagnosis]:
    suspect = []
    for e in _warm_epoch_rows(rows):
        p50 = float(e.get("step_time_p50", 0.0))
        p90 = float(e.get("step_time_p90", 0.0))
        p99 = float(e.get("step_time_p99", 0.0))
        if (
            p50 > 0
            and p99 >= BIMODAL_P99 * p50
            and p90 <= BIMODAL_P90 * p50
            and p99 - p50 >= BIMODAL_MIN_EXCESS_S
        ):
            suspect.append(e)
    if not suspect:
        return []
    e = suspect[-1]
    return [Diagnosis(
        "warn",
        "recompile_suspicion",
        f"step-time bimodality in {len(suspect)} epoch row(s): p99 "
        f"{1e3 * float(e['step_time_p99']):.1f}ms is "
        f"{float(e['step_time_p99']) / float(e['step_time_p50']):.1f}x "
        f"p50 while p90 stays near p50 — a few steps are wildly slower "
        "than the rest, the signature of silent recompiles (new batch "
        "shape?) or periodic interference; check XF001 and the span "
        "trace around the slow steps",
    )]


def _check_store(rows: list[dict]) -> list[Diagnosis]:
    """Tiered-store health from the ``store`` epoch rows.  Each run's
    FIRST store row is exempt: a cold start legitimately misses on
    everything while promotion fills the tier — thrash is a LOW hit
    rate that persists while the tier keeps churning."""
    warm: list[dict] = []
    for run in split_runs(rows):
        srows = [r for r in run.rows if r.get("kind") == "store"]
        warm.extend(srows[1:])
    bad = [
        r for r in warm
        if float(r.get("hot_hit_rate", 1.0)) < STORE_THRASH_HIT_RATE
        and (
            (int(r.get("promotions", 0)) + int(r.get("demotions", 0)))
            > 0
            # a SATURATED tier serving a too-large working set may
            # show zero churn (swap hysteresis blocks near-tie
            # evictions) — that is still the raise-hot-capacity
            # condition, not health
            or float(r.get("hot_occupancy", 0.0)) >= 0.99
        )
    ]
    if not bad:
        return []
    r = bad[-1]
    return [Diagnosis(
        "warn",
        "store_thrash",
        f"store-thrash in {len(bad)} epoch row(s): hot_hit_rate "
        f"{float(r['hot_hit_rate']):.2f} stayed below "
        f"{STORE_THRASH_HIT_RATE} after warmup while the tier churned "
        f"({r.get('promotions')} promotions / {r.get('demotions')} "
        f"demotions, occupancy {float(r.get('hot_occupancy', 0)):.2f} "
        f"in epoch {r.get('epoch')}) — the working set exceeds the hot "
        "tier; raise --hot-capacity-log2 or accept cold-fetch latency "
        "(docs/STORE.md)",
    )]


def _check_serve(
    rows: list[dict], queue_stall_tripped: bool = False
) -> list[Diagnosis]:
    """Serving-tier health from the fleet's ``serve_shed`` and
    ``rollout`` rows (serve/fleet.py, docs/SERVING.md).

    * **shed_storm** — a stats window where admission control rejected
      the majority of offered traffic: the tier is past capacity and
      the deadline budget is being defended at the door.  When the
      watchdog ALSO tripped serve_queue_stall in the same stream, the
      storm is named as the primary cause — the backlog is past its
      deadline budget *because* offered load exceeds capacity, so the
      fix is fleet size / offered QPS, not the queue.
    * **canary_stuck** — a run whose LAST ``rollout`` row is ``begin``
      or ``canary`` (the open-rollout heartbeat): the rollout never
      resolved to commit/abort — the process died or wedged
      mid-canary, and a fraction of traffic is still pinned to an
      uncommitted artifact."""
    out = []
    storms = [
        r for r in rows
        if r.get("kind") == "serve_shed"
        and float(r.get("shed_frac", 0.0)) >= SHED_STORM_FRAC
        and int(r.get("shed_total", 0)) >= SHED_STORM_MIN_TOTAL
    ]
    if storms:
        r = storms[-1]
        causes = ", ".join(
            f"{k}={v}" for k, v in sorted(
                (r.get("by_cause") or {}).items()
            )
        ) or "?"
        msg = (
            f"shed storm in {len(storms)} stats window(s): admission "
            f"control rejected {100 * float(r['shed_frac']):.0f}% of "
            f"offered traffic ({r.get('shed_total')} sheds vs "
            f"{r.get('admitted')} admitted; {causes}) — the tier is "
            "past capacity and defended the deadline budget at the "
            "door; add replicas or lower offered QPS (docs/SERVING.md)"
        )
        if queue_stall_tripped:
            msg += (
                "; the serve_queue_stall trip(s) above are this same "
                "capacity condition, not an independent queue bug"
            )
        out.append(Diagnosis("warn", "shed_storm", msg))
    for run in split_runs(rows):
        rrows = [r for r in run.rows if r.get("kind") == "rollout"]
        if rrows and rrows[-1].get("event") in ("begin", "canary"):
            r = rrows[-1]
            out.append(Diagnosis(
                "warn",
                "canary_stuck",
                f"canary-stuck rollout: the stream's last rollout row "
                f"is {r.get('event')!r} ({r.get('from_digest')} → "
                f"{r.get('to_digest')}, canary_frac "
                f"{r.get('canary_frac')}, {r.get('canary_requests')} "
                f"canary request(s), {r.get('canary_errors')} "
                "error(s)) with no commit/abort after it — the run "
                "ended mid-rollout; commit, abort, or redeploy so "
                "traffic converges on one artifact",
            ))
    return out


def _check_qos(rows: list[dict]) -> list[Diagnosis]:
    """QoS admission ordering from ``serve_shed`` rows that carry the
    per-class ``by_class`` split (serve/fleet.py QOS_CLASSES).

    * **qos_inversion** — a window where the BIDDING class shed
      traffic while best_effort shed nothing: the per-class budgets
      are supposed to make best_effort absorb pressure first and the
      bidding path shed last, so this ordering is inverted — the
      class budget fractions are misconfigured (best_effort's budget
      is not strictly tighter) or requests are mislabeled."""
    inverted = []
    for r in rows:
        if r.get("kind") != "serve_shed":
            continue
        by_class = r.get("by_class") or {}
        bid = by_class.get("bidding") or {}
        be = by_class.get("best_effort") or {}
        if (
            int(bid.get("shed", 0)) > 0
            and int(be.get("shed", 0)) == 0
            # only meaningful when best_effort traffic was offered at
            # all: an all-bidding workload shedding is plain overload
            and int(be.get("admitted", 0)) + int(be.get("shed", 0)) > 0
        ):
            inverted.append(r)
    if not inverted:
        return []
    r = inverted[-1]
    bid = (r.get("by_class") or {}).get("bidding") or {}
    return [Diagnosis(
        "warn",
        "qos_inversion",
        f"QoS inversion in {len(inverted)} stats window(s): the "
        f"bidding class shed {bid.get('shed')} request(s) while "
        "best_effort shed none despite carrying traffic — class "
        "shedding order is inverted (best_effort must absorb pressure "
        "first, bidding last); check serve_qos_best_effort_frac < "
        "serve_qos_normal_frac and client class labels "
        "(docs/SERVING.md)",
    )]


# scache_thrash gates (serve/scache.py windows in serve_stats rows)
SCACHE_THRASH_HIT_RATE = 0.1
SCACHE_MIN_TRAFFIC = 100
SCACHE_INVALIDATION_WINDOWS = 3


def _check_scache(rows: list[dict]) -> list[Diagnosis]:
    """Hot-key score-cache health from the cache_* fields the fleet
    folds into ``serve_stats`` windows (serve/scache.py).  Each run's
    FIRST cache window is exempt (a cold cache legitimately misses on
    everything — same warmup discipline as ``_check_store``).

    * **scache_thrash** — the hit rate stayed under
      ``SCACHE_THRASH_HIT_RATE`` with non-trivial traffic after
      warmup (the working set exceeds capacity, or traffic is not
      zipf-shaped enough to cache), or invalidations landed in
      ``SCACHE_INVALIDATION_WINDOWS``+ windows (rollouts churn the
      cache faster than it can warm) — either way the cache is
      costing memory without returning throughput."""
    warm: list[dict] = []
    invalidating = 0
    for run in split_runs(rows):
        crows = [
            r for r in run.rows
            if r.get("kind") == "serve_stats" and "cache_hits" in r
        ]
        warm.extend(crows[1:])
        invalidating += sum(
            1 for r in crows
            if int(r.get("cache_invalidations", 0)) > 0
        )
    cold = [
        r for r in warm
        if float(r.get("cache_hit_rate", 1.0)) < SCACHE_THRASH_HIT_RATE
        and (
            int(r.get("cache_hits", 0)) + int(r.get("cache_misses", 0))
        ) >= SCACHE_MIN_TRAFFIC
    ]
    out = []
    if cold:
        r = cold[-1]
        out.append(Diagnosis(
            "warn",
            "scache_thrash",
            f"score-cache thrash in {len(cold)} stats window(s): hit "
            f"rate {float(r.get('cache_hit_rate', 0.0)):.2f} stayed "
            f"below {SCACHE_THRASH_HIT_RATE} after warmup over "
            f"{int(r.get('cache_hits', 0)) + int(r.get('cache_misses', 0))} "
            f"lookups ({r.get('cache_entries')} entries, "
            f"{r.get('cache_evictions')} evictions) — the hot set "
            "exceeds serve_cache_capacity or the traffic is not "
            "skewed enough to cache; raise capacity or disable the "
            "cache (docs/SERVING.md)",
        ))
    elif invalidating >= SCACHE_INVALIDATION_WINDOWS:
        out.append(Diagnosis(
            "warn",
            "scache_thrash",
            f"score-cache churn: cache invalidations landed in "
            f"{invalidating} stats window(s) — rollouts are evicting "
            "the cache faster than it can warm, so it costs memory "
            "without returning throughput; batch the rollouts or "
            "disable the cache (docs/SERVING.md)",
        ))
    return out


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    return s[len(s) // 2] if s else 0.0


def _check_reqtrace(
    rows: list[dict],
    shed_storm: bool = False,
    queue_stall: bool = False,
) -> list[Diagnosis]:
    """Tail-latency attribution from ``reqtrace`` request spans
    (obs/reqtrace.py, docs/OBSERVABILITY.md).

    * **reqtrace_tail** — the slowest-k requests' mean e2e sits far
      above the all-request p50 AND one phase explains the excess:
      per-phase, take the slow-k mean minus the all-request median
      (clamped at zero) and name the argmax.  The dominant phase is
      cross-checked against the capacity findings already made from
      serve_shed/watchdog rows: a queue-side phase (admission_wait,
      coalesce_wait) dominating alongside a shed storm or queue stall
      is the same capacity condition seen from inside a request; a
      device-dominated tail alongside those findings means the queue
      symptoms are downstream of a slow device call, so fixing
      admission or fleet size would treat the symptom.
    * **reqtrace_tail_ok** (info) — enough traced requests and the
      tail is within normal spread of the median: decomposition
      reported, nothing to fix."""
    reqs = [
        r for r in rows
        if r.get("kind") == "reqtrace" and r.get("span") == "request"
        and isinstance(r.get("phases"), dict) and "e2e" in r
    ]
    if len(reqs) < REQTRACE_MIN_REQUESTS:
        return []
    e2es = [float(r["e2e"]) for r in reqs]
    p50 = _median(e2es)
    slow = sorted(reqs, key=lambda r: float(r["e2e"]), reverse=True)
    slow = slow[:REQTRACE_SLOW_K]
    slow_mean = sum(float(r["e2e"]) for r in slow) / len(slow)
    phases = sorted({p for r in reqs for p in r["phases"]})
    med = {
        p: _median([float(r["phases"].get(p, 0.0)) for r in reqs])
        for p in phases
    }
    excess = {
        p: max(
            0.0,
            sum(float(r["phases"].get(p, 0.0)) for r in slow)
            / len(slow) - med[p],
        )
        for p in phases
    }
    if (
        slow_mean < REQTRACE_TAIL_RATIO * p50
        or slow_mean - p50 < REQTRACE_TAIL_MIN_EXCESS_S
        or not any(excess.values())
    ):
        return [Diagnosis(
            "info", "reqtrace_tail_ok",
            f"reqtrace: {len(reqs)} request span(s), p50 "
            f"{1e3 * p50:.1f}ms, slowest-{len(slow)} mean "
            f"{1e3 * slow_mean:.1f}ms — tail within normal spread; "
            "no phase attribution needed",
        )]
    dominant = max(excess, key=lambda p: excess[p])
    ids = ", ".join(r.get("trace_id", "?") for r in slow)
    decomp = ", ".join(
        f"{p}+{1e3 * excess[p]:.1f}ms" for p in phases if excess[p]
    )
    msg = (
        f"tail attribution: slowest-{len(slow)} requests average "
        f"{1e3 * slow_mean:.1f}ms vs p50 {1e3 * p50:.1f}ms over "
        f"{len(reqs)} traced request(s); the excess is dominated by "
        f"the {dominant} phase ({decomp}; exemplar trace(s) {ids})"
    )
    if dominant in ("admission_wait", "coalesce_wait"):
        if shed_storm or queue_stall:
            msg += (
                " — consistent with the shed/queue findings above: "
                "the tier is past capacity and requests pay for it "
                "in queue time; add replicas or lower offered QPS"
            )
        else:
            msg += (
                " — requests queue before reaching a device; raise "
                "fleet size or max_batch before blaming the model"
            )
    elif dominant == "device":
        if shed_storm or queue_stall:
            msg += (
                " — the shed/queue findings above are a symptom, not "
                "the cause: the device call itself slowed and the "
                "backlog followed; profile the engine, not admission"
            )
        else:
            msg += (
                " — the device call itself is slow for these "
                "requests; check bucket sizes and engine digests "
                "(docs/SERVING.md)"
            )
    elif dominant == "swap_stall":
        msg += (
            " — batches stalled waiting on the rollout swap lock; "
            "an artifact swap ran during the window (docs/SERVING.md)"
        )
    return [Diagnosis("warn", "reqtrace_tail", msg)]


def _check_cascade(rows: list[dict]) -> list[Diagnosis]:
    """Retrieval→ranking cascade health from the ``cascade`` stats
    windows (serve/cascade.py; docs/SERVING.md):

    * **candidate_starvation** — the retrieval stage answered requests
      with fewer candidates than the requested k (an index smaller
      than k, or a retrieval rollout that shrank it): the ranker is
      scoring a thinner slate than the caller asked for.
    * **cascade_errors** — requests failed inside a stage (warn; the
      per-fleet serve rows name the replica).
    * **cascade_stage_p99** — per-stage p99 attribution (info): which
      stage dominates the e2e tail, so a slow cascade blames the
      right fleet instead of "serving is slow"."""
    crows = [
        r for r in rows
        if r.get("kind") == "cascade" and int(r.get("requests", 0)) > 0
    ]
    if not crows:
        return []
    out: list[Diagnosis] = []
    starved = sum(int(r.get("starved", 0)) for r in crows)
    if starved:
        r = next(r for r in crows if int(r.get("starved", 0)))
        out.append(Diagnosis(
            "warn",
            "candidate_starvation",
            f"candidate starvation: {starved} request(s) got fewer "
            f"candidates than requested (k={r.get('k')}, mean "
            f"returned {r.get('k_returned_mean')}) — the retrieval "
            "index holds fewer items than k (or a rollout shrank "
            "it); re-export the item index or lower the cascade k "
            "(docs/SERVING.md)",
        ))
    errors = sum(int(r.get("errors", 0)) for r in crows)
    if errors:
        out.append(Diagnosis(
            "warn",
            "cascade_errors",
            f"{errors} cascade request(s) failed inside a stage — "
            "check the per-fleet serve_shed/health rows to see which "
            "stage's replicas raised",
        ))
    last = crows[-1]
    rp99 = float(last.get("retrieval_p99", 0.0))
    kp99 = float(last.get("rank_p99", 0.0))
    e2e = float(last.get("e2e_p99", 0.0))
    if e2e > 0:
        stage, worst = (
            ("retrieval", rp99) if rp99 >= kp99 else ("ranking", kp99)
        )
        # per-stage and e2e percentiles come from different request
        # populations (a stage-2 shed books retrieval but not e2e), so
        # the share is capped at 100% rather than reported as an
        # impossible 200%
        share = min(100.0, 100 * worst / e2e)
        out.append(Diagnosis(
            "info",
            "cascade_stage_p99",
            f"cascade p99 attribution: e2e {1e3 * e2e:.1f}ms ≈ "
            f"retrieval {1e3 * rp99:.1f}ms + ranking "
            f"{1e3 * kp99:.1f}ms — the {stage} stage dominates "
            f"({share:.0f}%); scale THAT fleet first",
        ))
    return out


def _check_freshness(rows: list[dict]) -> list[Diagnosis]:
    """Continuous-training freshness (stream/driver.py ``freshness``
    rows; docs/CONTINUOUS.md).  A stream run must not read as clean
    when its servable is stale:

    * the LAST freshness row's newest-event-age exceeds its SLO — the
      fleet is serving a model older than the decay budget;
    * rollouts repeatedly abort (>= 2 aborts after the last commit) —
      exports keep failing the canary gate, so freshness can only
      decay from here;
    * a rollout BEGAN and never committed in a stream run (the
      begin-with-no-commit case): the run produced servables it never
      shipped — _check_serve's canary_stuck names the wedged rollout,
      this names the freshness consequence."""
    out: list[Diagnosis] = []
    for run in split_runs(rows):
        fresh = [r for r in run.rows if r.get("kind") == "freshness"]
        if not fresh:
            continue
        last = fresh[-1]
        age = float(last.get("newest_event_age_s", 0.0))
        slo = float(last.get("slo_s", 0.0))
        if slo > 0 and age > slo:
            out.append(Diagnosis(
                "warn",
                "servable_stale",
                f"stale servable: the stream's last freshness row "
                f"({last.get('event')!r} at step {last.get('step')}) "
                f"reports newest-event-age {age:.1f}s over the "
                f"{slo:.0f}s SLO — ingested events are not reaching "
                "the serving fleet; check rollout aborts and export "
                "cadence (docs/CONTINUOUS.md)",
            ))
        aborts_since_commit = 0
        for r in fresh:
            if r.get("event") == "commit":
                aborts_since_commit = 0
            elif r.get("event") == "abort":
                aborts_since_commit += 1
        if aborts_since_commit >= 2:
            out.append(Diagnosis(
                "warn",
                "servable_stale",
                f"rollouts repeatedly aborting: "
                f"{aborts_since_commit} consecutive abort(s) since "
                "the last committed swap — every refresh is failing "
                "the canary health gate, so the serving fleet keeps "
                "aging; inspect the rollout rows' gate verdicts "
                "(docs/CONTINUOUS.md)",
            ))
        rrows = [r for r in run.rows if r.get("kind") == "rollout"]
        began = any(r.get("event") == "begin" for r in rrows)
        committed = any(r.get("event") == "commit" for r in rrows)
        if began and not committed:
            out.append(Diagnosis(
                "warn",
                "servable_stale",
                "stream run began rollout(s) but never committed one: "
                "exports were cut and canaried but no swap ever "
                "landed — the fleet still serves the original base "
                "while the model trains ahead (see the canary_stuck "
                "finding for the wedged rollout itself)",
            ))
    return out


def _check_chaos(rows: list[dict]) -> list[Diagnosis]:
    """Chaos-fabric forensics (xflow_tpu/chaos/, docs/ROBUSTNESS.md):
    correlate ``chaos`` rows (injected faults) with the self-healing
    ``health`` causes and rank what the run absorbed vs what stuck.

    * **fault storm vs isolated recovery** — a handful of injected
      faults all matched by recoveries is the chaos gate's healthy
      shape (info); many faults, or faults without recoveries, rank as
      a storm (warn).
    * **quarantine_budget_exceeded** — named crit: the stream was
      corrupt past the skip budget and the run aborted deliberately.
      This is DATA corruption, not an input stall — without this
      check its silence would misread as input_bound/input_stall.
    * **replica_evicted** — evictions matched by revivals are absorbed
      capacity events (info); unrevived evictions mean the fleet is
      still running short (warn)."""
    chaos_rows = [r for r in rows if r.get("kind") == "chaos"]
    causes: dict[str, int] = {}
    for r in rows:
        if r.get("kind") == "health":
            c = r.get("cause", "?")
            causes[c] = causes.get(c, 0) + 1
    out: list[Diagnosis] = []
    if causes.get("quarantine_budget_exceeded"):
        out.append(Diagnosis(
            "crit",
            "quarantine_budget_exceeded",
            f"input corruption exceeded the quarantine budget "
            f"({causes.get('record_quarantined', 0)} quarantined "
            "block(s)/record(s) before the abort): the run stopped "
            "deliberately rather than train on a corrupt stream — "
            "this is data corruption, NOT an input stall; check the "
            "shard files and the loader retry health rows "
            "(docs/ROBUSTNESS.md)",
        ))
    quarantined = causes.get("record_quarantined", 0)
    if quarantined and not causes.get("quarantine_budget_exceeded"):
        out.append(Diagnosis(
            "warn",
            "record_quarantined",
            f"{quarantined} block(s)/record(s) quarantined (under the "
            "abort budget): input corruption is being skipped — those "
            "samples never reached the model; check the shard files "
            "and the loader retry health rows (docs/ROBUSTNESS.md)",
        ))
    fallbacks = causes.get("checkpoint_fallback", 0)
    saves_failed = causes.get("checkpoint_save_failed", 0)
    if fallbacks and not saves_failed:
        out.append(Diagnosis(
            "warn",
            "checkpoint_fallback",
            f"restore fell back past {fallbacks} unusable "
            "generation(s) to an older complete one: training REWOUND "
            "— deliberate under `--resume auto`, but inspect the "
            "skipped generations (external corruption?) before the "
            "keep-last-N GC ages the survivors out "
            "(docs/ROBUSTNESS.md)",
        ))
    if saves_failed:
        out.append(Diagnosis(
            "warn",
            "checkpoint_save_failed",
            f"{saves_failed} checkpoint save(s) FAILED "
            f"({causes.get('checkpoint_fallback', 0)} restore "
            "fallback(s) seen): the run remains restorable from the "
            "newest complete generation (`--resume auto`), but fix "
            "the storage path before the retained generations age out "
            "(docs/ROBUSTNESS.md)",
        ))
    evicted = causes.get("replica_evicted", 0)
    revived = causes.get("replica_revived", 0)
    if evicted:
        if revived >= evicted and not causes.get("replica_revive_failed"):
            out.append(Diagnosis(
                "info",
                "replica_evicted",
                f"{evicted} replica eviction(s), all revived from the "
                "shared artifact — scoring errors were absorbed as "
                "capacity events (sheds during the gap are admission "
                "control doing its job, not a queue bug)",
            ))
        else:
            out.append(Diagnosis(
                "warn",
                "replica_evicted",
                f"replica(s) evicted and NOT fully revived "
                f"({evicted} evicted, {revived} revived, "
                f"{causes.get('replica_revive_failed', 0)} revive "
                "failure(s)) — the fleet is serving at reduced "
                "capacity; expect sheds until replicas return "
                "(docs/ROBUSTNESS.md)",
            ))
    if causes.get("store_promote_dead"):
        out.append(Diagnosis(
            "warn",
            "store_promote_dead",
            "the promotion worker died twice (one restart spent): "
            "tier placement is frozen — training stays correct but "
            "every new key rides the cold miss path; expect the hot "
            "hit rate to decay (docs/ROBUSTNESS.md, docs/STORE.md)",
        ))
    if chaos_rows:
        sites: dict[str, int] = {}
        for r in chaos_rows:
            s = r.get("site", "?")
            sites[s] = sites.get(s, 0) + 1
        n = len(chaos_rows)
        recoveries = sum(causes.get(c, 0) for c in _SELF_HEAL_RECOVERIES)
        recoveries += causes.get("recovered:io_retry", 0)
        per_site = ", ".join(
            f"{s}={c}" for s, c in sorted(sites.items())
        )
        unrecovered = any(d.severity in ("crit", "warn") for d in out)
        if n >= CHAOS_STORM_MIN or unrecovered:
            out.append(Diagnosis(
                "warn",
                "fault_storm",
                f"fault storm: {n} injected fault(s) across "
                f"{len(sites)} site(s) ({per_site}) with "
                f"{recoveries} recovery row(s) — the findings above "
                "name what did not heal",
            ))
        else:
            out.append(Diagnosis(
                "info",
                "chaos_absorbed",
                f"chaos fabric armed: {n} injected fault(s) "
                f"({per_site}) absorbed by self-healing "
                f"({recoveries} recovery row(s)) — isolated "
                "recovery, not a storm",
            ))
    return out


def _check_flight(flight: dict) -> list[Diagnosis]:
    reason = flight.get("reason", "?")
    phase = flight.get("active_phase", "")
    threads = flight.get("threads", [])
    record = flight.get("record", {})
    sev = "crit" if reason in ("exception", "watchdog") else "warn"
    msg = (
        f"flight dump: run ended by {reason!r} while in phase "
        f"{phase or '?'} at step {record.get('last_step', '?')} "
        f"(last checkpoint step: {record.get('last_checkpoint_step')}, "
        f"{len(threads)} thread stacks captured)"
    )
    exc = flight.get("exception")
    if exc:
        msg += f"; exception {exc.get('type')}: {exc.get('message')}"
    out = [Diagnosis(sev, f"flight_{reason}", msg)]
    chans = record.get("channels", {})
    if chans:
        ages = ", ".join(
            f"{ch} {info.get('detail', '?')!r} {info.get('age_seconds', 0):.1f}s ago"
            for ch, info in sorted(chans.items())
        )
        out.append(Diagnosis(
            "info", "flight_channels", f"last heartbeats at dump: {ages}"
        ))
    return out


def _check_bench(bench: dict) -> list[Diagnosis]:
    parsed = bench.get("parsed") if isinstance(bench, dict) else None
    row = parsed if isinstance(parsed, dict) else bench
    if not isinstance(row, dict) or "value" not in row:
        return [Diagnosis(
            "info", "bench_unreadable",
            "bench artifact has no parsed result row — run bench.py "
            "to completion first",
        )]
    if row.get("degraded"):
        return [Diagnosis(
            "warn",
            "degraded_bench",
            f"degraded bench: {row.get('metric', '?')} = "
            f"{row.get('value')} measured on backend "
            f"{row.get('backend', '?')!r} (degraded environment — not "
            "comparable to the committed trajectory; last good: "
            f"{row.get('last_good_artifact', '?')})",
        )]
    return [Diagnosis(
        "info", "bench_ok",
        f"bench: {row.get('metric', '?')} = {row.get('value')} on "
        f"{row.get('backend', '?')} (not degraded)",
    )]


def diagnose(
    rows: list[dict],
    flight: dict | None = None,
    bench: dict | None = None,
) -> list[Diagnosis]:
    """Every check, ranked most-severe-first (stable within rank)."""
    findings: list[Diagnosis] = []
    findings.extend(_check_health(rows))
    findings.extend(_check_alerts(rows))
    findings.extend(_check_chaos(rows))
    findings.extend(_check_serve(
        rows,
        queue_stall_tripped=any(
            d.code == "serve_queue_stall" for d in findings
        ),
    ))
    findings.extend(_check_qos(rows))
    findings.extend(_check_scache(rows))
    findings.extend(_check_reqtrace(
        rows,
        shed_storm=any(d.code == "shed_storm" for d in findings),
        queue_stall=any(
            d.code == "serve_queue_stall" for d in findings
        ),
    ))
    findings.extend(_check_cascade(rows))
    findings.extend(_check_freshness(rows))
    if flight is not None:
        findings.extend(_check_flight(flight))
    findings.extend(_check_phases(rows))
    findings.extend(_check_stragglers(rows))
    findings.extend(_check_streams(rows))
    findings.extend(_check_bimodality(rows))
    findings.extend(_check_store(rows))
    if bench is not None:
        findings.extend(_check_bench(bench))
    preempted = sum(
        1 for _, e in _epoch_rows(rows) if e.get("preempted")
    )
    if preempted:
        findings.append(Diagnosis(
            "info", "preempted",
            f"{preempted} epoch row(s) ended by graceful preemption "
            "(resume with --resume)",
        ))
    findings.sort(key=lambda d: _SEV_ORDER.get(d.severity, 3))
    return findings


def format_diagnosis(
    path: str, rows: list[dict], findings: list[Diagnosis]
) -> str:
    ranks = sorted({
        int(r.get("rank", h.get("rank", 0)))
        for run in split_runs(rows)
        for h in [run.header or {}]
        for r in ([run.header] if run.header else []) + run.rows
    })
    out = [
        f"obs doctor — {path}: {len(rows)} rows, "
        f"{len(split_runs(rows))} run(s), rank(s) {ranks}"
    ]
    for d in findings:
        out.append(f"  [{d.severity.upper():4s}] {d.code}: {d.message}")
    problems = sum(1 for d in findings if d.severity in ("crit", "warn"))
    out.append(
        "diagnosis: clean (no crit/warn findings)"
        if not problems
        else f"diagnosis: {problems} problem(s) — ranked above"
    )
    return "\n".join(out)


def doctor(
    path: str,
    flight_path: str | None = None,
    bench_path: str | None = None,
) -> tuple[str, int]:
    """(report text, exit code): 0 clean, 1 when anything at warn or
    above surfaced."""
    from xflow_tpu.obs.flight import load_dump

    rows = load_jsonl(path)
    flight = load_dump(flight_path) if flight_path else None
    bench = None
    if bench_path:
        with open(bench_path) as f:
            bench = json.load(f)
    findings = diagnose(rows, flight=flight, bench=bench)
    text = format_diagnosis(path, rows, findings)
    bad = any(d.severity in ("crit", "warn") for d in findings)
    return text, 1 if bad else 0
