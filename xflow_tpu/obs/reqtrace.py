"""Request-scoped tracing across the serve path (ISSUE 16).

The serve tier's aggregate histograms (``serve_stats`` p99s, per-bucket
e2e, ``cascade`` stage rows) say THAT the tail is slow, never WHY one
request was slow.  This module is the per-request spine: a
``TraceContext`` (64-bit trace id + parent span id + sampling decision)
is minted at the HTTP front door — or accepted from an
``X-XFlow-Trace`` header / packed-wire field so the loadgen and
external clients correlate — and rides submit() through
AdmissionPolicy → ReplicaFleet routing → MicroBatcher coalescing →
the PredictEngine device call → both CascadeEngine stages.  Each
request materialises one ``RequestSpan`` stamping the five phase
boundaries:

    admission_wait  arrival → enqueued (admission check + routing)
    coalesce_wait   enqueued → batch sealed (micro-batch wait)
    swap_stall      batch sealed → engine captured (_swap_lock wait)
    featurize       rows → prepared Batch
    device          h2d + execute + fetch

and the batcher emits ONE batch span fanning in its N request spans
(same engine digest for every member by construction — the engine is
captured once under the swap lock, so a batch can never mix trace ids
across a rollout swap).

Sampling is head+tail: errors, sheds, and the window's slowest-k
exemplars are ALWAYS kept; the rest keep at ``Config.obs_reqtrace_sample``
via a deterministic splitmix64 hash of the trace id, so client and
server make the same decision without coordination.  Kept spans land as
``reqtrace`` JSONL rows (obs/schema.py) on every ``flush()`` — wired
into ``ReplicaFleet.emit_stats`` so trace windows align with
``serve_stats`` windows.  ``obs doctor`` attributes the tail to its
dominant phase; ``obs summarize`` prints the per-phase decomposition.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

__all__ = [
    "TraceContext",
    "RequestSpan",
    "ReqTraceSink",
    "PHASES",
    "format_header",
    "parse_header",
    "head_keep",
]

_MASK64 = (1 << 64) - 1

# phase vocabulary, in causal order — every request row's ``phases``
# dict carries exactly these keys (0.0 when a stage was never reached,
# e.g. a shed collapses everything into admission_wait)
PHASES = (
    "admission_wait",
    "coalesce_wait",
    "swap_stall",
    "featurize",
    "device",
)


def _mix64(x: int) -> int:
    """splitmix64 finalizer (same construction as chaos/registry.py) —
    turns sequential ids into uniform 64-bit words, so the sampling
    decision below is unbiased even for counter-minted trace ids."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def head_keep(trace_id: int, sample: float) -> bool:
    """Deterministic head-sampling decision for one trace id.

    Hash-based rather than random so every hop (client, front door,
    both cascade stages) agrees without carrying the verdict — and so
    replays are reproducible.  ``sample`` is a keep fraction in [0, 1].
    """
    if sample <= 0.0:
        return False
    if sample >= 1.0:
        return True
    # top 53 bits → uniform in [0, 1) without float rounding surprises
    return (_mix64(trace_id) >> 11) * 2.0**-53 < sample


class TraceContext:
    """The wire-portable triple: who is this request (trace_id), who
    asked (parent_span_id), and did the head-sampler keep it."""

    __slots__ = ("trace_id", "parent_span_id", "sampled")

    def __init__(
        self, trace_id: int, parent_span_id: int = 0, sampled: bool = False
    ):
        self.trace_id = trace_id & _MASK64
        self.parent_span_id = parent_span_id & _MASK64
        self.sampled = bool(sampled)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"TraceContext({self.trace_id:016x}, "
            f"parent={self.parent_span_id:016x}, sampled={self.sampled})"
        )


def format_header(ctx: TraceContext) -> str:
    """``X-XFlow-Trace`` header value: ``<trace>-<parent>-<0|1>``
    (16 lowercase hex digits each)."""
    return (
        f"{ctx.trace_id:016x}-{ctx.parent_span_id:016x}-"
        f"{1 if ctx.sampled else 0}"
    )


def parse_header(value: str | None) -> TraceContext | None:
    """Parse an ``X-XFlow-Trace`` header; None for absent/malformed —
    a bad trace header must never fail the request it annotates."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 3:
        return None
    try:
        tid = int(parts[0], 16)
        pid = int(parts[1], 16)
        flag = int(parts[2], 10)
    except ValueError:
        return None
    if not 0 < tid <= _MASK64 or not 0 <= pid <= _MASK64 or flag not in (0, 1):
        return None
    return TraceContext(tid, pid, bool(flag))


class RequestSpan:
    """One request's passage through one fleet stage.

    Mutable scratch object stamped in place by the fleet (arrival,
    shed) and the batcher worker (enqueue/seal/dequeue/featurize/
    device) — each field is written by exactly one thread at one point
    in the request's life, so no lock is needed until ``ReqTraceSink.
    complete`` freezes it into a record under the sink lock."""

    __slots__ = (
        "trace_id",
        "span_id",
        "parent_span_id",
        "sampled",
        "stage",
        "replica",
        "t_arrival",
        "t_enq",
        "t_seal",
        "t_deq",
        "t_feat",
        "t_done",
        "batch_id",
        "bucket",
        "digest",
        "sink",
    )

    def __init__(
        self,
        sink: "ReqTraceSink",
        ctx: TraceContext,
        span_id: int,
        stage: str,
    ):
        self.sink = sink
        self.trace_id = ctx.trace_id
        self.span_id = span_id
        self.parent_span_id = ctx.parent_span_id
        self.sampled = ctx.sampled
        self.stage = stage
        self.replica: int | None = None
        self.t_arrival = time.perf_counter()
        self.t_enq: float | None = None
        self.t_seal: float | None = None
        self.t_deq: float | None = None
        self.t_feat: float | None = None
        self.t_done: float | None = None
        self.batch_id: int | None = None
        self.bucket: int | None = None
        self.digest: str | None = None

    def context(self) -> TraceContext:
        """A child context: downstream spans parent onto THIS span."""
        return TraceContext(self.trace_id, self.span_id, self.sampled)


class ReqTraceSink:
    """Collects completed spans, applies head+tail sampling on flush,
    emits ``reqtrace`` JSONL rows.

    One sink per serving process is the intended shape (a cascade's two
    fleets share one, so retrieval and ranking spans of one trace land
    in the same window).  Thread-safe throughout: submit paths mint and
    complete from handler/worker threads while ``flush`` runs on the
    stats-window thread."""

    def __init__(
        self,
        metrics_logger=None,
        sample: float = 0.0,
        slow_k: int = 3,
        capacity: int = 65536,
    ):
        if not 0.0 <= sample <= 1.0:
            raise ValueError("reqtrace sample must be in [0, 1]")
        if slow_k < 0:
            raise ValueError("slow_k must be >= 0")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.metrics_logger = metrics_logger
        self.sample = float(sample)
        self.slow_k = int(slow_k)
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # fresh random base per process so ids don't collide across
        # restarts writing to one JSONL; sequential offsets are mixed
        # through splitmix64 at mint time
        base = int.from_bytes(os.urandom(8), "big")
        self._id_seq = itertools.count(base)
        self._batch_seq = itertools.count(1)
        self._done: list[dict] = []  # completed request records
        self._batches: dict[int, dict] = {}  # batch_id -> batch record
        self._last_kept: list[dict] = []  # request rows of last flush
        self.dropped = 0  # records lost to the capacity cap

    # -- minting -----------------------------------------------------------

    def mint(self) -> TraceContext:
        """A fresh root context (front door / loadgen), head-sampling
        decision baked in."""
        tid = _mix64(next(self._id_seq)) or 1  # trace id 0 is reserved
        return TraceContext(tid, 0, head_keep(tid, self.sample))

    def start(
        self,
        trace: TraceContext | None,
        stage: str,
        replica: int | None = None,
    ) -> RequestSpan:
        """Open one request span (mints a root context when the caller
        carried none).  Stamps t_arrival = now."""
        if trace is None:
            trace = self.mint()
        span = RequestSpan(self, trace, _mix64(next(self._id_seq)), stage)
        span.replica = replica
        return span

    def next_batch_id(self) -> int:
        return next(self._batch_seq)

    # -- completion --------------------------------------------------------

    def complete(
        self, span: RequestSpan, status: str = "ok", detail: str | None = None
    ) -> None:
        """Freeze one span into a record.  Missing stamps chain-fill
        forward from the last one reached, so the phase dict always
        sums to e2e exactly — a shed books its whole life as
        admission_wait, a featurize error books zero device, etc."""
        now = time.perf_counter()
        span.t_done = now
        t0 = span.t_arrival
        enq = span.t_enq if span.t_enq is not None else now
        seal = span.t_seal if span.t_seal is not None else enq
        deq = span.t_deq if span.t_deq is not None else seal
        feat = span.t_feat if span.t_feat is not None else deq
        phases = {
            "admission_wait": max(0.0, enq - t0),
            "coalesce_wait": max(0.0, seal - enq),
            "swap_stall": max(0.0, deq - seal),
            "featurize": max(0.0, feat - deq),
            "device": max(0.0, now - feat),
        }
        rec = {
            "span": "request",
            "trace_id": f"{span.trace_id:016x}",
            "span_id": f"{span.span_id:016x}",
            "parent_span_id": f"{span.parent_span_id:016x}",
            "stage": span.stage,
            "status": status,
            "sampled": span.sampled,
            "e2e": round(now - t0, 6),
            "phases": {k: round(v, 6) for k, v in phases.items()},
        }
        if span.replica is not None:
            rec["replica"] = span.replica
        if span.batch_id is not None:
            rec["batch"] = f"b{span.batch_id}"
        if span.bucket is not None:
            rec["bucket"] = span.bucket
        if span.digest is not None:
            rec["digest"] = span.digest
        if detail:
            rec["detail"] = str(detail)[:200]
        with self._lock:
            if len(self._done) >= self.capacity:
                self.dropped += 1
            else:
                self._done.append(rec)

    def note_batch(
        self,
        batch_id: int,
        trace_ids: list[int],
        digest: str,
        bucket: int,
        phases: dict,
        status: str = "ok",
    ) -> None:
        """Record one coalesced batch span fanning in its members.
        Exactly one engine digest per batch — the batcher captures the
        engine once under its swap lock."""
        rec = {
            "span": "batch",
            "batch": f"b{batch_id}",
            "n": len(trace_ids),
            "trace_ids": [f"{t:016x}" for t in trace_ids],
            "digest": digest,
            "bucket": bucket,
            "status": status,
            "phases": {k: round(v, 6) for k, v in phases.items()},
        }
        with self._lock:
            self._batches[batch_id] = rec

    # -- emission ----------------------------------------------------------

    def _keep_reason(self, rec: dict, slow_ids: set) -> str | None:
        if rec["status"] == "error":
            return "error"
        if rec["status"] == "shed":
            return "shed"
        if id(rec) in slow_ids:
            return "slow"
        if rec["sampled"]:
            return "head"
        return None

    def flush(self) -> list[dict]:
        """Drain the window: emit errors + sheds + slowest-k + the
        head-sampled remainder (whole trace trees — if ANY span of a
        trace is kept, its sibling spans and referenced batch spans are
        kept too, so every emitted trace id has a complete tree).
        Returns the emitted rows; idempotent on an empty window."""
        with self._lock:
            done, self._done = self._done, []
            batches, self._batches = self._batches, {}
        if not done and not batches:
            return []
        slow_ids = {
            id(r)
            for r in sorted(done, key=lambda r: r["e2e"], reverse=True)[
                : self.slow_k
            ]
        }
        kept_traces: set[str] = set()
        for rec in done:
            reason = self._keep_reason(rec, slow_ids)
            if reason is not None:
                rec["keep"] = reason
                kept_traces.add(rec["trace_id"])
        rows: list[dict] = []
        kept_batches: set[str] = set()
        for rec in done:
            if rec["trace_id"] not in kept_traces:
                continue
            rec.setdefault("keep", "tree")  # sibling of a kept span
            rows.append(rec)
            if "batch" in rec:
                kept_batches.add(rec["batch"])
        for _bid, b in sorted(batches.items()):
            if b["batch"] in kept_batches:
                b["keep"] = "batch"  # kept by member reference
                rows.append(b)
        if self.metrics_logger is not None:
            for row in rows:
                self.metrics_logger.log("reqtrace", row)
        with self._lock:
            self._last_kept = [r for r in rows if r["span"] == "request"]
        return rows

    # -- exemplar access (serve_bench / doctor cross-checks) ---------------

    def exemplars(self, k: int = 3) -> list[dict]:
        """Top-k slowest request rows of the LAST flush as serve_bench
        ``slowest_exemplars`` entries (trace id + phase breakdown)."""
        with self._lock:
            kept = list(self._last_kept)
        kept.sort(key=lambda r: r["e2e"], reverse=True)
        return [
            {
                "trace_id": r["trace_id"],
                "stage": r["stage"],
                "e2e_ms": round(r["e2e"] * 1e3, 3),
                "phases_ms": {
                    p: round(v * 1e3, 3) for p, v in r["phases"].items()
                },
            }
            for r in kept[:k]
        ]

    def phases_of(self, trace_id_hex: str) -> dict | None:
        """Phase breakdown (ms) for one kept trace id of the last
        flush — the loadgen's client-recorded exemplar lookup."""
        with self._lock:
            for r in self._last_kept:
                if r["trace_id"] == trace_id_hex:
                    return {
                        p: round(v * 1e3, 3) for p, v in r["phases"].items()
                    }
        return None
