"""Stall watchdog — classifies hot-path silence and escalates.

A wedged input pipeline, a hung device dispatch, and a backed-up
MicroBatcher all look identical from outside: the process is alive and
nothing moves.  The watchdog turns that silence into a *named* cause
within a bounded delay, with zero work on the hot path itself: the hot
paths already heartbeat the FlightRecorder (obs/flight.py ``note_*`` —
a clock read and a locked dict store), and a single monitor thread
polls those beats.

Classification (separate thresholds, Config ``obs_watchdog_*``):

* ``input_stall`` — the trainer's last phase note is ``input_stall``
  and it has been silent past ``input_s``: the loop is starved.  The
  health row carries the loader channel's age too, so a starving
  trainer with a *beating* loader (transfer/backpressure problem) is
  distinguishable from a dead input pipeline.
* ``device_hang`` — last phase note is ``dispatch``/``device_block``/
  ``h2d``/``checkpoint`` and silent past ``device_s``: the device (or
  its dispatch queue, or the checkpoint write) is wedged.
* ``serve_queue_stall`` — the serve channel is silent past ``serve_s``
  WHILE work is pending (``set_pending`` callable); an idle batcher
  never trips.
* ``serve_accept_stall`` — the ``http`` channel (the front end's
  accept loop beats it unconditionally every ``serve_forever`` poll,
  serve/server.py) is silent past ``serve_s`` while its pending probe
  (``tier.running``) says the server should be alive.  Separate from
  ``serve_queue_stall`` on purpose: a wedged accept loop with a
  healthy scoring path and a wedged scoring path behind a healthy
  front door are different pages.

Escalation per incident: trip → log line + ``health`` JSONL row +
instant trace event; silence reaching ``ESCALATE_FACTOR`` × threshold →
one flight dump (``<flight_out>`` with reason ``watchdog``).  Recovery
(a fresh beat) emits a closing ``health`` row with cause
``recovered:<original>`` so the stream records the stall's duration.

Thread-safety (XF003): all incident state is mutated under
``self._lock``; the monitor thread never touches device state or JAX
at all (XF002 — no host syncs anywhere on this path).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from xflow_tpu.obs.flight import FlightRecorder

# phase-note detail -> (cause name, threshold key)
_TRAIN_CAUSES = {
    "input_stall": ("input_stall", "input"),
    "dispatch": ("device_hang", "device"),
    "device_block": ("device_hang", "device"),
    "h2d": ("device_hang", "device"),
    "checkpoint": ("checkpoint_stall", "device"),
}

ESCALATE_FACTOR = 2.0


class Watchdog:
    def __init__(
        self,
        flight: FlightRecorder,
        input_s: float = 30.0,
        device_s: float = 120.0,  # keep in sync with Config defaults
        serve_s: float = 10.0,
        poll_s: float = 0.0,
        flight_out: str = "",
        metrics_logger=None,
        tracer=None,
        log: Callable[[str], None] | None = None,
    ):
        if min(input_s, device_s, serve_s) <= 0:
            raise ValueError("watchdog thresholds must be > 0")
        self.flight = flight
        self.thresholds = {
            "input": input_s,
            "device": device_s,
            "serve": serve_s,
        }
        # poll fast enough to trip "within its threshold": a quarter of
        # the tightest threshold, floored so a sub-ms test threshold
        # doesn't spin the monitor
        self.poll_s = poll_s if poll_s > 0 else max(
            min(input_s, device_s, serve_s) / 4.0, 0.01
        )
        self.flight_out = flight_out
        self.metrics_logger = metrics_logger
        self.tracer = tracer
        self._log = log if log is not None else (lambda s: None)
        self._lock = threading.Lock()
        # channel -> open incident {cause, threshold, t_trip, dumped}
        self._incidents: dict[str, dict[str, Any]] = {}
        self._pending: dict[str, Callable[[], bool]] = {}
        self.trip_count = 0
        self.dump_count = 0
        self._last_row: dict[str, Any] | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def set_pending(self, channel: str, fn: Callable[[], bool]) -> None:
        """Register a 'work is pending' probe: ``channel`` silence only
        trips while ``fn()`` is True (an idle server is healthy)."""
        with self._lock:
            self._pending[channel] = fn

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="xflow-obs-watchdog", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> "Watchdog":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- monitor ------------------------------------------------------------

    def _run(self) -> None:
        # the watchdog IS the monitor: beating the recorder from here
        # would make every silent channel look alive, and its own
        # liveness is observable through the health rows it emits
        # (xf: ignore[XF009])
        while not self._stop.wait(self.poll_s):
            self.check()

    def check(self, now: float | None = None) -> list[dict]:
        """One monitor pass (the thread calls this every ``poll_s``;
        tests call it directly).  Returns the health rows emitted."""
        if now is None:
            now = time.perf_counter()
        rows = []
        for channel in ("train", "serve", "http"):
            row = self._check_channel(channel, now)
            if row is not None:
                rows.append(row)
        return rows

    def _classify(self, channel: str, detail: str) -> tuple[str, float] | None:
        """(cause, threshold seconds) for the channel's activity
        ``detail``, or None when its silence is benign."""
        if channel == "train":
            if detail == "idle":
                # the trainer parked itself between epochs/evals —
                # silence here is the caller's time, not a stall
                return None
            cause, key = _TRAIN_CAUSES.get(
                detail, (f"stall:{detail}", "device")
            )
            return cause, self.thresholds[key]
        with self._lock:
            pending = self._pending.get(channel)
        if pending is not None and not pending():
            return None  # idle, not stalled
        if channel == "http":
            return "serve_accept_stall", self.thresholds["serve"]
        return "serve_queue_stall", self.thresholds["serve"]

    def _check_channel(self, channel: str, now: float) -> dict | None:
        # age + detail read atomically: classifying a stale age against
        # a just-transitioned phase's (tighter) threshold would trip
        # spuriously
        state = self.flight.channel_state(channel, now)
        if state is None:
            return None  # channel never started — nothing to watch
        age, detail = state
        with self._lock:
            incident = self._incidents.get(channel)
        verdict = self._classify(channel, detail)
        if verdict is None or age < verdict[1]:
            if incident is not None:
                return self._recover(channel, incident, age)
            return None
        cause, threshold = verdict
        if incident is None:
            return self._trip(channel, cause, threshold, age)
        with self._lock:
            # track the deepest silence seen while the incident is
            # open: the recovery row reports THIS as the stall's
            # duration (at recovery time the fresh beat has already
            # reset the channel's age)
            incident["worst_age"] = max(incident["worst_age"], age)
        if (
            not incident["dumped"]
            and self.flight_out
            and age >= threshold * ESCALATE_FACTOR
        ):
            self._escalate(channel, incident, age)
        return None

    # -- incident transitions ----------------------------------------------

    def _health_row(
        self, channel: str, cause: str, threshold: float, age: float
    ) -> dict:
        from xflow_tpu.obs.schema import health_row

        row = health_row(
            cause=cause,
            channel=channel,
            silence_seconds=age,
            threshold_seconds=threshold,
            detail=self.flight.last_detail(channel) or "",
            channels=self.flight.snapshot()["channels"],
        )
        if self.metrics_logger is not None:
            self.metrics_logger.log("health", row)
        with self._lock:
            self._last_row = row
        return row

    def state(self) -> dict:
        """JSON-ready live health view — the ``GET /v1/stats``
        enrichment (serve/server.py): open incidents per channel,
        lifetime trip/dump counts, and the last health row emitted,
        so one scrape answers "is this tier sick" without reading the
        metrics file."""
        with self._lock:
            incidents = {
                ch: {
                    "cause": inc["cause"],
                    "threshold_seconds": round(inc["threshold"], 3),
                    "worst_silence_seconds": round(inc["worst_age"], 3),
                    "dumped": inc["dumped"],
                }
                for ch, inc in self._incidents.items()
            }
            last = dict(self._last_row) if self._last_row else None
            return {
                "healthy": not incidents,
                "incidents": incidents,
                "trip_count": self.trip_count,
                "dump_count": self.dump_count,
                "last": last,
            }

    def _trip(
        self, channel: str, cause: str, threshold: float, age: float
    ) -> dict:
        with self._lock:
            self._incidents[channel] = {
                "cause": cause,
                "threshold": threshold,
                "dumped": False,
                "worst_age": age,
            }
            self.trip_count += 1
        self._log(
            f"watchdog: {cause} — {channel!r} silent {age:.1f}s "
            f"(threshold {threshold:.1f}s, last activity "
            f"{self.flight.last_detail(channel)!r})"
        )
        if self.tracer is not None:
            self.tracer.instant(
                "watchdog_trip", {"cause": cause, "channel": channel}
            )
        return self._health_row(channel, cause, threshold, age)

    def _escalate(self, channel: str, incident: dict, age: float) -> None:
        with self._lock:
            if incident["dumped"]:
                return
            incident["dumped"] = True
            self.dump_count += 1
        path = self.flight.dump(self.flight_out, reason="watchdog")
        self._log(
            f"watchdog: {incident['cause']} persists ({age:.1f}s) — "
            f"flight dump written to {path}"
        )

    def _recover(self, channel: str, incident: dict, age: float) -> dict:
        with self._lock:
            self._incidents.pop(channel, None)
        # the stall's duration is the deepest silence observed while
        # the incident was open — `age` here is the POST-recovery beat
        # age (~one poll interval), useless as a duration
        stalled = incident["worst_age"]
        self._log(
            f"watchdog: {channel!r} recovered from {incident['cause']} "
            f"after ~{stalled:.1f}s"
        )
        if self.tracer is not None:
            self.tracer.instant(
                "watchdog_recovered",
                {"cause": incident["cause"], "channel": channel},
            )
        return self._health_row(
            channel,
            f"recovered:{incident['cause']}",
            incident["threshold"],
            stalled,
        )
