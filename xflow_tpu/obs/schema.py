"""Metrics JSONL schema: every ``kind`` and its required fields.

This is the single source of truth for what the trainer emits
(docs/OBSERVABILITY.md documents the semantics).  Consumed by:

* ``obs.summary`` — tolerant reads, but warns on schema violations;
* ``scripts/check_metrics_schema.py`` — the CI lint that runs the toy
  pipeline and validates its output strictly;
* ``tests/test_observability.py`` — asserts every emitted row passes.

A field listed here must appear in EVERY row of that kind the current
code emits.  Adding a field is backward-compatible (old files still
summarize); removing or renaming one is a schema change — update this
module, the doc, and the lint together.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

# kind -> {field: type-or-tuple-of-types} for isinstance checks.
# Backend-dependent values (e.g. device memory stats) are OMITTED from
# their row rather than emitted as null, so no nullable types exist.
SCHEMA: dict[str, dict[str, Any]] = {
    # one per MetricsLogger open (run delimiter — summarize splits here;
    # hostname/pid — OPTIONAL below — let `obs merge`/`obs doctor`
    # label hosts in multi-host runs; stamped centrally by
    # MetricsLogger, so every current emitter carries them)
    "run_start": {
        "t": (int, float),
        "kind": str,
        "run_id": str,
        "config_digest": str,
        "rank": int,
        "num_hosts": int,
        "time_unix": (int, float),
    },
    # one per training epoch
    "train_epoch": {
        "t": (int, float),
        "kind": str,
        "epoch": int,
        "examples": (int, float),
        "steps": int,
        "train_logloss": (int, float),
        "examples_per_sec": (int, float),
        "seconds": (int, float),
        "checkpoint_seconds": (int, float),
        "preempted": bool,
        # main-thread-exclusive phase seconds: disjoint intervals whose
        # sum accounts for (nearly all of) `seconds`
        "phases": dict,
        # worker-thread phase seconds (parse/pack/h2d under
        # transfer-ahead): overlap the main thread, NOT additive with it
        "overlapped": dict,
        "input_stall_frac": (int, float),
        "step_time_p50": (int, float),
        "step_time_p90": (int, float),
        "step_time_p99": (int, float),
    },
    # one per evaluate() call
    "eval": {
        "t": (int, float),
        "kind": str,
        "epoch": int,
        "logloss": (int, float),
        "auc": (int, float),
        "examples": int,
        "tp": int,
        "fp": int,
        "seconds": (int, float),
        "phases": dict,
        "overlapped": dict,
    },
    # one per finished training shard (per host; loader throughput)
    "shard": {
        "t": (int, float),
        "kind": str,
        "epoch": int,
        "shard": str,
        "index": int,
        "examples": int,
        "seconds": (int, float),
        "examples_per_sec": (int, float),
    },
    # one per reader stream per epoch under the input fan-out
    # (Config.input_streams > 1; io/fanout.py, docs/PERF.md "Input
    # fan-out"): finished-shard totals, producer wall seconds, and
    # backpressure stall seconds (producer blocked on a full queue —
    # the consumer's fault, not the stream's).  read_seconds is the
    # directly measured read+parse+compact time (queue waits
    # excluded); examples_per_sec = examples / read_seconds, so `obs
    # doctor` can rank a genuinely slow stream (shard skew, slow
    # disk) as a straggler without blaming a stream parked behind a
    # saturated device.
    "stream": {
        "t": (int, float),
        "kind": str,
        "epoch": int,
        "stream": int,
        "shards": int,
        "batches": int,
        "examples": int,
        "seconds": (int, float),
        "read_seconds": (int, float),
        "stall_seconds": (int, float),
        "examples_per_sec": (int, float),
    },
    # one per epoch: jax.local_devices() memory stats
    "device_mem": {
        "t": (int, float),
        "kind": str,
        "epoch": int,
        "devices": list,
    },
    # one per training epoch when any batch crossed the host->device
    # link: wire-format accounting (parallel/step.py::_book_wire;
    # docs/PERF.md "Wire format and compaction").  format names the
    # wire that ran ("dict" = host-compacted dictionary wire, "compact",
    # "full"); wire_bytes_per_example is what actually crossed the link
    # per real example; compaction_ratio is cold occurrences per
    # big-table touch after host dedup (1.0 = no dedup)
    "wire": {
        "t": (int, float),
        "kind": str,
        "epoch": int,
        "format": str,
        "wire_bytes_per_example": (int, float),
        "compaction_ratio": (int, float),
    },
    # one per training epoch under store_mode='tiered': hierarchical
    # parameter-store accounting (store/tiered.py; docs/STORE.md).
    # hot_hit_rate is occurrence-weighted (feature occurrences the HBM
    # hot tier served / all real occurrences); cold_fetch_seconds is
    # host time spent gathering miss rows; hot_occupancy is the
    # fraction of hot-tier slots assigned at epoch end.  `obs doctor`
    # reads these for the store-thrash diagnosis.
    "store": {
        "t": (int, float),
        "kind": str,
        "epoch": int,
        "hot_hit_rate": (int, float),
        "promotions": int,
        "demotions": int,
        "cold_fetch_seconds": (int, float),
        "hot_occupancy": (int, float),
    },
    # -- serving (serve/; docs/SERVING.md) ---------------------------------
    # one per PredictEngine artifact load: bucket geometry + warmup cost
    "serve_load": {
        "t": (int, float),
        "kind": str,
        "artifact": str,
        "config_digest": str,
        "model": str,
        "buckets": list,
        "warm_seconds": (int, float),
        "compiles": int,
    },
    # one per MicroBatcher flush/close: per-request latency percentiles
    # (queue = enqueue→dequeue, featurize = request→Batch assembly,
    # device = h2d + execute + fetch) over the window since the last
    # emission, plus coalescing effectiveness (requests/batches) and
    # the admission-control sheds booked against this window
    "serve_stats": {
        "t": (int, float),
        "kind": str,
        "requests": int,
        "batches": int,
        "swaps": int,
        "batch_fill_mean": (int, float),
        "queue_p50": (int, float),
        "queue_p99": (int, float),
        "featurize_p50": (int, float),
        "featurize_p99": (int, float),
        "device_p50": (int, float),
        "device_p99": (int, float),
    },
    # one per `python -m xflow_tpu.serve bench` run: end-to-end serving
    # latency/throughput under concurrent load
    "serve_bench": {
        "t": (int, float),
        "kind": str,
        "requests": int,
        "concurrency": int,
        "seconds": (int, float),
        "requests_per_sec": (int, float),
        "e2e_p50": (int, float),
        "e2e_p99": (int, float),
        "queue_p50": (int, float),
        "queue_p99": (int, float),
        "featurize_p50": (int, float),
        "featurize_p99": (int, float),
        "device_p50": (int, float),
        "device_p99": (int, float),
        "compiles": int,
    },
    # one per fleet stats window (serve/fleet.py): admission-control
    # accounting — requests admitted vs shed (per cause) plus the live
    # backlog at emission.  A window whose shed_frac dominates is a
    # shed storm: admission control protected the deadline budget by
    # rejecting at the door (`obs doctor` blames capacity, not the
    # queue).
    "serve_shed": {
        "t": (int, float),
        "kind": str,
        "admitted": int,
        "shed_total": int,
        "shed_frac": (int, float),
        "by_cause": dict,
        "errors": int,
        "depth": int,
        "queue_age_s": (int, float),
    },
    # one per staged-rollout transition (serve/fleet.py): event is
    # begin / canary (open-rollout heartbeat, flushed with each stats
    # window) / commit / abort.  A stream whose LAST rollout row is
    # begin/canary died mid-rollout — `obs doctor` flags canary-stuck.
    "rollout": {
        "t": (int, float),
        "kind": str,
        "event": str,
        "from_digest": str,
        "to_digest": str,
        "canary_frac": (int, float),
        "canary_requests": int,
        "canary_errors": int,
        "detail": str,
    },
    # one per retrieval→ranking cascade stats window
    # (serve/cascade.py; docs/SERVING.md "Retrieval→ranking cascade"):
    # per-stage latency attribution (retrieval vs ranking p50/p99 —
    # `obs doctor` blames the right fleet) and candidate accounting
    # (k requested vs returned; `starved` counts requests the
    # retrieval stage answered with fewer than k candidates)
    "cascade": {
        "t": (int, float),
        "kind": str,
        "requests": int,
        "errors": int,
        "shed_total": int,
        "starved": int,
        "k": int,
        "k_returned_mean": (int, float),
        "retrieval_p50": (int, float),
        "retrieval_p99": (int, float),
        "rank_p50": (int, float),
        "rank_p99": (int, float),
        "e2e_p50": (int, float),
        "e2e_p99": (int, float),
    },
    # one per KEPT span per reqtrace flush (obs/reqtrace.py;
    # docs/OBSERVABILITY.md "Tracing a request"): span is "request"
    # (one request's passage through one fleet stage — trace/span/
    # parent ids, status ok|error|shed, e2e seconds, the five-phase
    # decomposition admission_wait/coalesce_wait/swap_stall/featurize/
    # device, and keep = WHY the sampler kept it: head|slow|error|
    # shed|tree) or "batch" (one coalesced batch fanning in its
    # member trace_ids — exactly one engine digest per batch).  The
    # two variants share only the trunk fields; the rest are
    # per-variant and OPTIONAL below.
    "reqtrace": {
        "t": (int, float),
        "kind": str,
        "span": str,
        "status": str,
        "phases": dict,
        "keep": str,
    },
    # one per continuous-training export/rollout transition
    # (stream/driver.py; docs/CONTINUOUS.md): event is export (a
    # delta/base was cut) / commit (the canary gate passed and the
    # fleet swapped — for commits, newest_event_age_s IS the
    # event-to-servable freshness the SLO is about) / abort (the gate
    # refused; the fleet stays on the incumbent and freshness keeps
    # aging).  `obs doctor` ranks a stream whose last row exceeds
    # slo_s, or whose rollouts repeatedly abort, as servable_stale.
    "freshness": {
        "t": (int, float),
        "kind": str,
        "event": str,
        "newest_event_age_s": (int, float),
        "slo_s": (int, float),
        "servable": str,
        "export_kind": str,
        "step": int,
        "rows": int,
        "delta_bytes": int,
        "deltas_since_base": int,
    },
    # -- robustness (xflow_tpu/chaos/; docs/ROBUSTNESS.md) -----------------
    # one per failpoint FIRE when the chaos fabric is armed
    # (Config.chaos_spec / XFLOW_CHAOS): site is the failpoint name,
    # hit the site's crossing count at fire time, fires the site's
    # cumulative fire count.  scripts/check_chaos.py reconciles these
    # rows against the registry's in-memory fire counts and demands a
    # matching `health` row from the layer that healed each fault.
    "chaos": {
        "t": (int, float),
        "kind": str,
        "site": str,
        "hit": int,
        "fires": int,
        "detail": str,
    },
    # -- diagnosis (obs/watchdog.py, obs/flight.py; docs/OBSERVABILITY.md
    # "Diagnosing a sick run") ---------------------------------------------
    # one per watchdog incident transition: a trip (cause names the
    # classified stall) or a recovery (cause "recovered:<original>",
    # silence_seconds = how long the stall lasted)
    "health": {
        "t": (int, float),
        "kind": str,
        "cause": str,
        "channel": str,
        "silence_seconds": (int, float),
        "threshold_seconds": (int, float),
        "detail": str,
        # every channel's last-heartbeat age at emission — the
        # cross-channel context that separates "loader dead" from
        # "loader fine, transfer wedged"
        "channels": dict,
    },
    # one per flight-recorder dump: a pointer row so `obs doctor` finds
    # the dump file from the metrics stream alone
    "flight_dump": {
        "t": (int, float),
        "kind": str,
        "path": str,
        "reason": str,
        "active_phase": str,
    },
    # -- live telemetry plane (obs/live.py, obs/export.py;
    # docs/OBSERVABILITY.md "Operating a live fleet") ----------------------
    # one per SLO alert transition (obs/live.py AlertEvaluator): state
    # is firing (both the short AND the long burn-rate window breached
    # the rule's threshold) or resolved (the short window dropped back
    # under it).  value is the short-window mean at transition time;
    # `obs doctor` treats these rows as first-class evidence.
    "alert": {
        "t": (int, float),
        "kind": str,
        "rule": str,
        "state": str,
        "value": (int, float),
        "threshold": (int, float),
        "short_s": (int, float),
        "long_s": (int, float),
        "samples": int,
        "detail": str,
    },
    # one per host resource sample (obs/export.py ResourceSampler):
    # stdlib-only process telemetry — RSS, cumulative CPU seconds,
    # live thread count, open file descriptors, cumulative GC
    # collections — so a leak or a CPU-bound straggler shows up in the
    # same stream as the metrics it distorts.
    "resource": {
        "t": (int, float),
        "kind": str,
        "rss_bytes": int,
        "cpu_seconds": (int, float),
        "threads": int,
        "open_fds": int,
        "gc_collections": int,
    },
}


# kind -> {field: types} for fields that are type-checked when present
# but NOT required: added after files of that kind already existed in
# the wild (append-mode files span upgrades — a resumed run writes a
# new-format header into a file whose old headers predate the field).
OPTIONAL: dict[str, dict[str, Any]] = {
    "run_start": {
        "hostname": str,
        "pid": int,
    },
    "train_epoch": {
        # single-host runs under trainer._transfer_ahead only
        "transfer_ahead_depth_mean": (int, float),
        # loaders that report parse phase bytes only
        "parse_mb_per_sec": (int, float),
    },
    # fleet-mode rows only (serve/fleet.py pools N replicas into one
    # registry; rows written before the production tier predate these
    # fields, so requiring them would fail old streams)
    "serve_stats": {
        "per_bucket": dict,
        "shed_total": int,
        # hot-key score cache window (serve/scache.py) — only fleets
        # with a cache attached write these
        "cache_hits": int,
        "cache_misses": int,
        "cache_hit_rate": (int, float),
        "cache_entries": int,
        "cache_bytes": int,
        "cache_evictions": int,
        "cache_invalidations": int,
        "cache_inserts_dropped": int,
    },
    # scored-and-returned count alongside admitted (completions lag
    # admissions by the in-flight window; rows from before the counter
    # predate the field)
    "serve_shed": {
        "completed": int,
        # per-QoS-class admitted/shed split (serve/fleet.py
        # QOS_CLASSES) — additive like per_bucket: pre-QoS metrics
        # streams without it still validate (pinned by
        # tests/test_serve_binary.py back-compat test)
        "by_class": dict,
    },
    # loadgen rows only (serve/loadgen.py open-loop SLO accounting;
    # the closed-loop `bench` CLI predates these fields)
    "serve_bench": {
        "offered_qps": (int, float),
        "offered_qps_actual": (int, float),
        "achieved_qps": (int, float),
        "shed_frac": (int, float),
        "shed_by_cause": dict,
        "errors": int,
        "outstanding": int,
        "per_bucket": dict,
        # 429s the HttpTarget retried after honoring Retry-After
        # (capped exponential backoff) — chaos runs measure RECOVERY,
        # not just rejection; rows from before the field predate it
        "retried": int,
        # traced runs only (obs/reqtrace.py): client-observed
        # slowest-3 as {trace_id, e2e_ms, phases_ms?} — the bench
        # row NAMES its tail so `obs doctor`'s attribution and a
        # human reading the row point at the same span trees
        "slowest_exemplars": list,
        # which wire carried the traffic: "fleet" (in-process),
        # "http", or "binary" — the two-leg SLO gate
        # (check_serve_slo.py --compare-transports) keys on it
        "transport": str,
        # mixed-QoS runs only: offered/shed counts per class
        "qos_offered": dict,
        "qos_shed": dict,
    },
    # per-variant fields (span "request" vs "batch" share only the
    # trunk — requiring the union would fail every row)
    "reqtrace": {
        "trace_id": str,
        "span_id": str,
        "parent_span_id": str,
        "stage": str,
        "sampled": bool,
        "e2e": (int, float),
        "replica": int,
        "batch": str,
        "bucket": int,
        "digest": str,
        "detail": str,
        "n": int,
        "trace_ids": list,
    },
}


def health_row(
    cause: str,
    channel: str,
    silence_seconds: float,
    threshold_seconds: float,
    detail: str,
    channels: dict | None = None,
) -> dict:
    """A schema-complete ``health`` record body.  Every emitter
    (watchdog trips/recoveries, loader prefetch-leak, batcher
    worker-leak, gate smokes) builds the row HERE so a field added to
    the ``health`` schema breaks one constructor, not N inlined
    dicts."""
    return {
        "cause": cause,
        "channel": channel,
        "silence_seconds": round(silence_seconds, 3),
        "threshold_seconds": round(threshold_seconds, 3),
        "detail": detail,
        "channels": channels if channels is not None else {},
    }


def alert_row(
    rule: str,
    state: str,
    value: float,
    threshold: float,
    short_s: float,
    long_s: float,
    samples: int,
    detail: str,
) -> dict:
    """A schema-complete ``alert`` record body (health_row discipline:
    every emitter builds the row here)."""
    return {
        "rule": rule,
        "state": state,
        "value": round(float(value), 6),
        "threshold": round(float(threshold), 6),
        "short_s": round(float(short_s), 3),
        "long_s": round(float(long_s), 3),
        "samples": int(samples),
        "detail": detail,
    }


def resource_row(
    rss_bytes: int,
    cpu_seconds: float,
    threads: int,
    open_fds: int,
    gc_collections: int,
) -> dict:
    """A schema-complete ``resource`` record body."""
    return {
        "rss_bytes": int(rss_bytes),
        "cpu_seconds": round(float(cpu_seconds), 3),
        "threads": int(threads),
        "open_fds": int(open_fds),
        "gc_collections": int(gc_collections),
    }


def validate_row(row: dict, lineno: int | None = None) -> list[str]:
    """Schema errors for one parsed JSONL row ([] = valid)."""
    where = f"line {lineno}: " if lineno is not None else ""
    kind = row.get("kind")
    if kind is None:
        return [f"{where}row has no 'kind' field"]
    spec = SCHEMA.get(kind)
    if spec is None:
        return [f"{where}unknown kind {kind!r}"]
    errors = []
    for name, types in spec.items():
        if name not in row:
            errors.append(f"{where}kind {kind!r} missing field {name!r}")
            continue
        if not isinstance(row[name], types):
            errors.append(
                f"{where}kind {kind!r} field {name!r}: expected "
                f"{types}, got {type(row[name]).__name__}"
            )
    for name, types in OPTIONAL.get(kind, {}).items():
        if name in row and not isinstance(row[name], types):
            errors.append(
                f"{where}kind {kind!r} optional field {name!r}: "
                f"expected {types}, got {type(row[name]).__name__}"
            )
    return errors


def validate_rows(rows: Iterable[dict]) -> list[str]:
    errors = []
    for i, row in enumerate(rows, 1):
        errors.extend(validate_row(row, lineno=i))
    return errors


def load_jsonl(path: str) -> list[dict]:
    """Parse a metrics file; raises ValueError on a malformed line."""
    rows = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError as e:
                raise ValueError(f"{path}:{i}: not valid JSON: {e}")
    return rows


def load_jsonl_tolerant(path: str) -> tuple[list[dict], int]:
    """Parse a metrics file that may still be APPENDED to: a torn
    FINAL line (the writer is mid-``write``, or the file was copied
    mid-line) is skipped and counted instead of raising.  A malformed
    line anywhere else is still corruption and raises exactly like
    ``load_jsonl`` — torn tails are expected on live files, torn
    middles are not.  Returns ``(rows, skipped)`` with skipped in
    {0, 1}."""
    with open(path) as f:
        lines = f.readlines()
    rows: list[dict] = []
    skipped = 0
    last = len(lines)
    for i, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped:
            continue
        try:
            rows.append(json.loads(stripped))
        except ValueError as e:
            if i == last:
                skipped = 1
                break
            raise ValueError(f"{path}:{i}: not valid JSON: {e}")
    return rows, skipped
