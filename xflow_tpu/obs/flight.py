"""Flight recorder — always-on, bounded in-memory record of recent
run state, dumped atomically on crash/preemption/watchdog trip.

A hang or crash mid-epoch used to leave only whatever JSONL happened to
flush; the forensic questions ("what was the trainer DOING?  which
shard?  when did it last checkpoint?  what are the threads stuck on?")
had no answer.  The recorder keeps exactly that state, cheaply:

* a ring of the newest ``capacity`` noted events (phase transitions,
  batch shapes, checkpoint saves, serve batches) — O(1) per note, no
  growth on arbitrarily long runs;
* per-channel last-heartbeat state (``train``/``loader``/``serve``)
  that doubles as the watchdog's liveness feed (obs/watchdog.py reads
  it; the notes ARE the heartbeats);
* at dump time only: per-thread stack dumps (``sys._current_frames``),
  the live metrics-registry snapshot, and the tail of the span
  tracer's ring.

``dump()`` writes the whole record as one JSON document via tmp-file +
``os.replace`` (atomic on POSIX: a reader never sees a torn dump) and
logs a ``flight_dump`` JSONL row pointing at it, so ``obs doctor``
finds the dump from the metrics stream alone.

Thread-safety (XF003 discipline): every mutation of shared state takes
``self._lock``; notes are a clock read + two dict/deque stores —
nothing on the hot path blocks or syncs the device (XF002).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any

FORMAT_VERSION = 1

# tracer-ring tail kept in a dump: enough to see the last few steps'
# span structure without re-serializing the whole 65536-event ring
_DUMP_SPAN_TAIL = 256


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 256,
        metrics_logger=None,
        registry=None,
        tracer=None,
        rank: int = 0,
    ):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        # channel -> (perf_counter seconds, detail str); the watchdog's
        # liveness feed (last_beat/beat_age read it)
        self._channels: dict[str, tuple[float, str]] = {}
        self._last_batch: dict[str, Any] | None = None
        self._last_checkpoint_step: int | None = None
        self._last_step: int = 0
        self.metrics_logger = metrics_logger
        self.registry = registry
        self.tracer = tracer
        self.rank = rank
        self._t0 = time.time()
        self._t0_perf = time.perf_counter()

    # -- hot-path notes (cheap: one clock read + ONE locked store) ---------

    def _note(self, kind: str, detail: str, channel: str | None = None) -> None:
        """Append to the event ring and (when ``channel``) update that
        channel's heartbeat — one lock acquisition per beat, so a
        concurrent dump() never sees an event without its channel
        update."""
        now = time.perf_counter()
        with self._lock:
            self._events.append((round(now - self._t0_perf, 6), kind, detail))
            if channel is not None:
                self._channels[channel] = (now, detail)

    def note_phase(self, phase: str, step: int = 0) -> None:
        """Trainer heartbeat: the main loop just ENTERED ``phase`` at
        global step ``step``.  Silence after an ``input_stall`` note
        means the loop is starved; after ``dispatch``/``device_block``
        it means the device (or its queue) is wedged."""
        now = time.perf_counter()
        with self._lock:
            self._events.append(
                (round(now - self._t0_perf, 6), "phase", phase)
            )
            self._channels["train"] = (now, phase)
            self._last_step = step

    def note_loader(self, detail: str = "block") -> None:
        """Loader heartbeat: a block parsed / a batch assembled.  A
        starving trainer WITH a beating loader points at transfer or
        consumer backpressure, not the input pipeline itself."""
        self._note("loader", detail, channel="loader")

    def note_serve(self, detail: str = "batch") -> None:
        """Serving heartbeat: the MicroBatcher finished (or the engine
        executed) one batch."""
        self._note("serve", detail, channel="serve")

    def note_http(self, detail: str = "accept") -> None:
        """HTTP front-end heartbeat: the accept loop completed one
        ``serve_forever`` poll (serve/server.py ``service_actions``).
        A separate channel from ``serve`` on purpose: the accept loop
        beats unconditionally while alive, so folding it into the
        serve channel would mask a wedged scoring path behind a
        healthy front door — the watchdog classifies ``http`` silence
        as serve_accept_stall and ``serve`` silence-with-backlog as
        serve_queue_stall, independently."""
        self._note("http", detail, channel="http")

    def note_store(self, detail: str = "note") -> None:
        """Tiered-store heartbeat: the promotion worker scored a batch
        of touch counts (store/promote.py).  Not watchdog-classified —
        placement is advisory — but the channel age in a flight dump
        separates 'promoter wedged' from 'promoter idle'."""
        self._note("store", detail, channel="store")

    def note_batch(self, shape: dict[str, Any]) -> None:
        """Record the most recent batch geometry (rows/nnz/bucket) —
        the 'what data was in flight' forensic."""
        with self._lock:
            self._last_batch = dict(shape)
            self._events.append((
                round(time.perf_counter() - self._t0_perf, 6),
                "batch",
                json.dumps(shape, sort_keys=True),
            ))

    def note_checkpoint(self, step: int) -> None:
        now = time.perf_counter()
        with self._lock:
            self._last_checkpoint_step = int(step)
            self._events.append(
                (round(now - self._t0_perf, 6), "checkpoint", f"step={step}")
            )

    # -- watchdog feed ------------------------------------------------------

    def beat_age(self, channel: str, now: float | None = None) -> float | None:
        """Seconds since ``channel`` last beat (None = never beat)."""
        state = self.channel_state(channel, now)
        return None if state is None else state[0]

    def channel_state(
        self, channel: str, now: float | None = None
    ) -> tuple[float, str] | None:
        """(beat age seconds, last detail) read ATOMICALLY — the
        watchdog classifies on this pair, and reading them under
        separate lock acquisitions would let a phase transition land
        in between (a stale large age paired with the new phase's
        tighter threshold = spurious trip)."""
        if now is None:
            now = time.perf_counter()
        with self._lock:
            last = self._channels.get(channel)
        return None if last is None else (now - last[0], last[1])

    def last_detail(self, channel: str) -> str | None:
        with self._lock:
            last = self._channels.get(channel)
        return None if last is None else last[1]

    # -- dump ---------------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """The in-memory record as plain JSON-ready dicts (no stacks,
        no registry — those are dump-time extras)."""
        now = time.perf_counter()
        with self._lock:
            channels = {
                ch: {"age_seconds": round(now - t, 6), "detail": d}
                for ch, (t, d) in self._channels.items()
            }
            events = [
                {"t": t, "kind": k, "detail": d} for t, k, d in self._events
            ]
            last_batch = self._last_batch
            last_ckpt = self._last_checkpoint_step
            last_step = self._last_step
        return {
            "channels": channels,
            "events": events,
            "last_batch": last_batch,
            "last_checkpoint_step": last_ckpt,
            "last_step": last_step,
        }

    def _thread_stacks(self) -> list[dict[str, Any]]:
        names = {t.ident: t.name for t in threading.enumerate()}
        stacks = []
        for ident, frame in sys._current_frames().items():
            stacks.append({
                "thread_id": ident,
                "name": names.get(ident, "?"),
                "stack": traceback.format_stack(frame),
            })
        return stacks

    def dump(
        self,
        path: str,
        reason: str,
        exc: BaseException | None = None,
    ) -> str | None:
        """Write the full record to ``path`` atomically; returns the
        path (None when writing failed — a dying process must not die
        harder because its black box had a disk error)."""
        active = self.last_detail("train") or ""
        doc: dict[str, Any] = {
            "format_version": FORMAT_VERSION,
            "reason": reason,
            "time_unix": round(time.time(), 3),
            "rank": self.rank,
            "active_phase": active,
            "record": self.snapshot(),
            "threads": self._thread_stacks(),
        }
        if exc is not None:
            doc["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__
                ),
            }
        if self.registry is not None:
            snap = self.registry.snapshot()
            doc["metrics"] = {
                "counters": snap.counters,
                "gauges": snap.gauges,
                "hists": snap.hists,
            }
        if self.tracer is not None and self.tracer.enabled:
            doc["spans"] = self.tracer.events()[-_DUMP_SPAN_TAIL:]
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        if self.metrics_logger is not None:
            self.metrics_logger.log("flight_dump", {
                "path": path,
                "reason": reason,
                "active_phase": active,
            })
        return path


def load_dump(path: str) -> dict[str, Any]:
    """Parse a flight dump; raises ValueError on a malformed file."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except json.JSONDecodeError as e:
        raise ValueError(f"{path}: not a valid flight dump: {e}")
    if not isinstance(doc, dict) or "reason" not in doc:
        raise ValueError(f"{path}: not a flight dump (no 'reason' field)")
    return doc
