"""Observability subsystem (ISSUE 1): span tracing, pipeline-health
metrics, and the trace/summary toolchain.

Three layers, all off-by-default-cheap:

* ``trace`` — nested context-manager spans, ring-buffered, exported as
  Chrome trace-event JSON for Perfetto (Config.obs_trace_out);
* ``registry`` — counters/gauges/histograms: per-phase wall-second
  accounting (parse, pack, h2d, dispatch, input stall, checkpoint,
  device block), step-time percentiles, transfer-ahead occupancy;
* ``summary`` / ``__main__`` — ``python -m xflow_tpu.obs summarize
  run.jsonl`` turns metrics JSONL into phase/throughput tables;
  ``compare a.jsonl b.jsonl`` diffs two runs.

The ``Obs`` facade bundles one tracer and one registry and is threaded
through the hot path (Trainer, TrainStep.put_batch, ShardLoader).  When
disabled, ``NULL_OBS`` is a shared object whose ``phase()`` returns one
shared no-op context manager — no per-step allocation.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from xflow_tpu.obs.registry import (
    NULL_REGISTRY,
    MetricsRegistry,
    NullRegistry,
    Snapshot,
)
from xflow_tpu.obs.trace import NULL_SPAN, NULL_TRACER, NullTracer, SpanTracer

__all__ = [
    "Obs",
    "NULL_OBS",
    "make_obs",
    "SpanTracer",
    "NullTracer",
    "MetricsRegistry",
    "NullRegistry",
    "Snapshot",
]


class _Phase:
    """Times a block and books it BOTH as a ``phase.<name>`` counter
    (wall-second accounting) and as a trace span."""

    __slots__ = ("_obs", "_name", "_t0")

    def __init__(self, obs: "Obs", name: str):
        self._obs = obs
        self._name = name

    def __enter__(self) -> "_Phase":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        dt = time.perf_counter() - self._t0
        self._obs.registry.counter_add("phase." + self._name, dt)
        self._obs.tracer.add_complete(self._name, self._t0, dt)
        return None


class Obs:
    """One tracer + one registry (and, when diagnosis is on, one
    flight recorder) shared by everything in a run.

    ``flight`` is the heartbeat sink (obs/flight.py): hot paths that
    hold an Obs — ShardLoader, PredictEngine — pulse it with
    ``note_loader``/``note_serve`` so the watchdog (obs/watchdog.py)
    can classify silence.  None when diagnosis is off: callers guard
    with ``if obs.flight is not None`` (one attribute read per beat
    site, nothing allocated).

    ``metrics_logger`` is the ``health``-row sink for the self-healing
    fabric (xflow_tpu/chaos/heal.py): a retried read, a quarantined
    record, a restarted worker must be LOUD whenever a metrics stream
    exists at all — not only when the flight recorder happens to be on
    (Trainer sets it alongside its MetricsLogger)."""

    __slots__ = ("tracer", "registry", "flight", "metrics_logger")
    enabled = True

    def __init__(self, tracer=None, registry=None, flight=None,
                 metrics_logger=None):
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.flight = flight
        self.metrics_logger = metrics_logger

    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def span(self, name: str, tags: dict | None = None):
        """Trace-only span (no phase counter) — for enclosing scopes
        like a whole epoch, where counting the seconds would double the
        inner phases."""
        return self.tracer.span(name, tags)

    def counter(self, name: str, v: float = 1.0) -> None:
        self.registry.counter_add(name, v)

    def gauge(self, name: str, v: float) -> None:
        self.registry.gauge_set(name, v)

    def observe(self, name: str, v: float) -> None:
        self.registry.observe(name, v)


class NullObs:
    """Disabled facade: every path is a no-op; ``phase``/``span`` return
    the one shared ``NULL_SPAN`` — zero per-step allocation."""

    __slots__ = ()
    enabled = False
    tracer = NULL_TRACER
    registry = NULL_REGISTRY
    flight = None
    metrics_logger = None

    def phase(self, name: str):
        return NULL_SPAN

    def span(self, name: str, tags: dict | None = None):
        return NULL_SPAN

    def counter(self, name: str, v: float = 1.0) -> None:
        pass

    def gauge(self, name: str, v: float) -> None:
        pass

    def observe(self, name: str, v: float) -> None:
        pass


NULL_OBS = NullObs()


def make_obs(
    trace: bool = False,
    trace_capacity: int = 65536,
    rank: int = 0,
    step_fn: Callable[[], int] | None = None,
) -> Obs:
    """Live Obs: registry always, tracer only when ``trace``."""
    tracer = (
        SpanTracer(capacity=trace_capacity, rank=rank, step_fn=step_fn)
        if trace
        else NULL_TRACER
    )
    return Obs(tracer=tracer, registry=MetricsRegistry())
