"""Low-overhead span tracer: nested context-manager spans exported as
Chrome trace-event JSON (viewable in Perfetto / chrome://tracing).

Complements — does not replace — the ``jax.profiler`` window
(Config.profile_dir): the XLA profile shows device-internal time for a
few steps; these spans show where the HOST loop's wall-clock goes
(parse, pack, h2d transfer, dispatch, stalls) for the whole run, at
~microsecond overhead per span.

Design constraints (ISSUE 1):

* ring-buffered — a fixed ``capacity`` of newest events, so an
  arbitrarily long run cannot grow host memory;
* rank/step-tagged — ``pid`` is the host rank (one Perfetto process row
  per host), every event's args carry the trainer's global step;
* disabled == free — ``NULL_TRACER`` returns one shared no-op span
  object; no allocation, no clock read, per call.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable


class _NullSpan:
    """Shared no-op context manager (the disabled-tracer span)."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        return None


NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every method is a no-op and ``span`` returns the
    one shared ``NULL_SPAN`` — nothing is allocated per step."""

    __slots__ = ()
    enabled = False

    def span(self, name: str, tags: dict | None = None) -> _NullSpan:
        return NULL_SPAN

    def add_complete(
        self, name: str, t0: float, dur: float, tags: dict | None = None
    ) -> None:
        pass

    def instant(self, name: str, tags: dict | None = None) -> None:
        pass

    def events(self) -> list[dict]:
        return []

    def export_chrome(self, path: str) -> str | None:
        return None


NULL_TRACER = NullTracer()


class _Span:
    """One live span: records a Chrome 'X' (complete) event on exit.
    Nesting is implicit — an inner span's [ts, ts+dur) interval lies
    inside its enclosing span's, which is exactly how Perfetto stacks
    same-tid events."""

    __slots__ = ("_tracer", "_name", "_tags", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, tags: dict | None):
        self._tracer = tracer
        self._name = name
        self._tags = tags

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._tracer.add_complete(
            self._name, self._t0, time.perf_counter() - self._t0, self._tags
        )
        return None


class SpanTracer:
    """Ring-buffered recorder of Chrome trace events.

    Thread-safe by construction: events append to a ``deque(maxlen=...)``
    (atomic under the GIL); the tid map takes a lock only on the first
    event from a new thread.
    """

    enabled = True

    def __init__(
        self,
        capacity: int = 65536,
        rank: int = 0,
        step_fn: Callable[[], int] | None = None,
    ):
        self.capacity = capacity
        self.rank = rank
        self._step_fn = step_fn
        self._t0 = time.perf_counter()
        self._events: deque = deque(maxlen=capacity)
        self._tids: dict[int, int] = {}
        self._lock = threading.Lock()

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def span(self, name: str, tags: dict | None = None) -> _Span:
        return _Span(self, name, tags)

    def add_complete(
        self, name: str, t0: float, dur: float, tags: dict | None = None
    ) -> None:
        """Record a finished [t0, t0+dur) span (perf_counter seconds)."""
        args: dict[str, Any] = dict(tags) if tags else {}
        if self._step_fn is not None:
            args["step"] = self._step_fn()
        ev = {
            "name": name,
            "ph": "X",
            "ts": round((t0 - self._t0) * 1e6, 3),  # Chrome wants µs
            "dur": round(dur * 1e6, 3),
            "pid": self.rank,
            "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        self._events.append(ev)

    def instant(self, name: str, tags: dict | None = None) -> None:
        """Zero-duration marker (Chrome 'i' event)."""
        args: dict[str, Any] = dict(tags) if tags else {}
        if self._step_fn is not None:
            args["step"] = self._step_fn()
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": round((time.perf_counter() - self._t0) * 1e6, 3),
            "pid": self.rank,
            "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        self._events.append(ev)

    def events(self) -> list[dict]:
        return list(self._events)

    def export_chrome(self, path: str) -> str:
        """Write the buffered events as a Chrome trace-event JSON object
        ({"traceEvents": [...]}); open with Perfetto (ui.perfetto.dev)
        or chrome://tracing."""
        with open(path, "w") as f:
            json.dump(
                {"traceEvents": self.events(), "displayTimeUnit": "ms"}, f
            )
        return path
