"""Training/eval driver — the worker loop of the reference
(LRWorker::train / batch_training / predict, lr_worker.cc:73-217)
re-expressed as a host loop feeding the pjit'd step.

Shard handling: the reference gives each of M worker processes one file
shard ``prefix-%05d`` by rank (lr_worker.cc:210).  Here one SPMD process
(per host) walks every shard assigned to it (``shard index % num_hosts
== host``); device-level data parallelism happens inside the step via
the batch's sharding, not via processes.

Evaluation reproduces the rank-0-only predict pass (lr_worker.cc:212-
215): stream the test shard(s), compute pctr, accumulate (label, pctr),
report rank-sum AUC + logloss, optionally dump prediction lines (the
reference's pred_<rank>_<block>.txt, lr_worker.cc:74-78).
"""

from __future__ import annotations

import glob
import os
import sys
import time
from collections import deque
from typing import Any, Callable, Iterator

import numpy as np

import jax

from xflow_tpu.config import Config
from xflow_tpu.io.batch import Batch
from xflow_tpu.io.loader import ShardLoader, make_parse_fn, shard_path
from xflow_tpu.models import make_model
from xflow_tpu.obs import NULL_OBS
from xflow_tpu.optim import make_optimizer
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.parallel.step import TrainStep, init_state
from xflow_tpu.utils.checkpoint import (
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from xflow_tpu.utils.metrics import AucAccumulator


def _ring_workers(depth: int) -> int:
    """Staging-ring worker count for a given depth: one per slot up to
    a core-bounded cap (at least 2 once the ring is deep enough for
    double buffering — compaction on one worker must be able to overlap
    a transfer on another)."""
    if depth <= 1:
        return 1
    return min(depth, max(2, min(4, (os.cpu_count() or 2) - 1)))


def find_shards(prefix: str) -> list[str]:
    """All existing ``prefix-%05d`` shards, in rank order; if none match,
    treat ``prefix`` itself as a single file."""
    shards = sorted(glob.glob(glob.escape(prefix) + "-" + "[0-9]" * 5))
    if not shards:
        if os.path.exists(prefix):
            return [prefix]
        raise FileNotFoundError(f"no shards matching {prefix}-NNNNN and no file {prefix}")
    return shards


class Trainer:
    def __init__(
        self,
        cfg: Config,
        mesh=None,
        log: Callable[[str], None] | None = None,
    ):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.num_devices)
        ndev = self.mesh.devices.size
        if cfg.batch_size % ndev:
            raise ValueError(
                f"batch_size {cfg.batch_size} not divisible by {ndev} devices"
            )
        if cfg.table_size % ndev:
            raise ValueError(
                f"table_size {cfg.table_size} not divisible by {ndev} devices"
            )
        self.model = make_model(cfg)
        self.optimizer = make_optimizer(cfg)
        self.step = TrainStep(self.model, self.optimizer, cfg, self.mesh)
        # Tiered store (Config.store_mode; store/): device state is the
        # bounded hot tier, NOT a [T, D] table — init_state at the
        # north-star 2^28 geometry would allocate the very buffers the
        # store exists to avoid.
        if self.step.store is not None:
            self.state = self.step.store.init_device_state()
        else:
            self.state = init_state(
                self.model, self.optimizer, cfg, self.mesh
            )
        self.epoch = 0
        # (shard_idx, byte_offset) to start the next epoch from; set by
        # restore(), consumed by the first train_epoch() after it.
        self._resume_cursor: tuple[int, int] = (0, 0)
        self._log = log if log is not None else lambda s: print(s, file=sys.stderr)
        # Multi-host: each process reads its own shard subset.
        self.host = jax.process_index()
        self.num_hosts = jax.process_count()
        self._global_steps = 0  # across epochs; drives the profile trigger
        # Live loader prefetch iterators (io/loader.py::_PrefetchIter),
        # closed explicitly by close() so abandoned producer threads
        # (crash, preemption, consumer break) never outlive the Trainer.
        self._live_prefetch: set = set()
        # Live transfer-ahead generators (_transfer_ahead): a mid-epoch
        # break (preemption) leaves the generator suspended inside its
        # `with ThreadPoolExecutor`, executor threads alive, until GC.
        # close() reaps them explicitly (XF006 — the _PrefetchIter leak
        # class, executor edition).
        self._live_transfer: set = set()
        # Continuous-training ingestion cursor (stream/follower.py::
        # IngestCursor), registered by the stream driver: close()
        # flushes it through the cursor's own atomic tmp+os.replace
        # path — the same discipline as checkpoints — so a preemption
        # between shard-complete and cursor-write replays at most one
        # shard (the at-least-once contract, docs/CONTINUOUS.md).
        self._stream_cursor = None
        # Observability (obs/__init__.py): a live tracer/registry bundle
        # when metrics or tracing is requested, else the shared no-op
        # NULL_OBS (zero per-step allocation).  Threaded into the step
        # (put_batch/dispatch phases) and every loader (parse/pack).
        self.obs = NULL_OBS
        if (
            cfg.metrics_out
            or cfg.obs_trace_out
            or cfg.obs_flight_out
            or cfg.obs_watchdog
        ):
            from xflow_tpu.obs import make_obs

            self.obs = make_obs(
                trace=bool(cfg.obs_trace_out),
                trace_capacity=cfg.obs_trace_capacity,
                rank=self.host,
                step_fn=lambda: self._global_steps,
            )
        self.step.obs = self.obs
        if self.step.store is not None:
            # checkpoint/export/close-path store heals report through
            # the live bundle (store/tiered.py complete_pending)
            self.step.store.obs = self.obs
        self.metrics_logger = None
        if cfg.metrics_out:
            from xflow_tpu.utils.logging import MetricsLogger

            # every host writes its own rank-suffixed file in
            # multi-host runs; `python -m xflow_tpu.obs merge` combines
            # them into one rank-tagged stream for `obs doctor`
            path = cfg.metrics_out
            if self.num_hosts > 1:
                path = f"{path}-r{self.host}"
            self.metrics_logger = MetricsLogger(
                path, run_header=self._run_header()
            )
            # the self-healing fabric's health-row sink (chaos/heal.py):
            # retries/quarantines/restarts are loud whenever a metrics
            # stream exists, flight recorder or not
            if self.obs.enabled:
                self.obs.metrics_logger = self.metrics_logger
        # Chaos fabric (xflow_tpu/chaos/; docs/ROBUSTNESS.md): arm the
        # failpoint registry from the config spec / env var, and route
        # its `chaos` audit rows into this run's metrics stream.
        from xflow_tpu import chaos

        # a config-armed schedule's lifetime is THIS trainer's: close()
        # disarms it, so a later non-chaos Trainer in the same process
        # never inherits the fault schedule.  Env-var arming is
        # process-level intent and stays.
        self._armed_chaos = bool(cfg.chaos_spec)
        if cfg.chaos_spec:
            chaos.arm(cfg.chaos_spec)
        else:
            chaos.arm_from_env()
        if chaos.armed() is not None and self.metrics_logger is not None:
            chaos.attach_logger(self.metrics_logger)
        # Flight recorder + stall watchdog (obs/flight.py, watchdog.py):
        # the recorder rides the live Obs so ShardLoader/PredictEngine
        # heartbeat it; the watchdog monitor starts now and stops in
        # close().  _flight_reason records WHY the run is ending so
        # close() writes exactly one dump on the crash/preemption paths.
        self._flight = None
        self._watchdog = None
        self._flight_reason: tuple[str, BaseException | None] | None = None
        self._last_batch_shape: tuple | None = None
        if self.obs.enabled and (cfg.obs_flight_out or cfg.obs_watchdog):
            from xflow_tpu.obs.flight import FlightRecorder

            self._flight = FlightRecorder(
                capacity=cfg.obs_flight_events,
                metrics_logger=self.metrics_logger,
                registry=self.obs.registry,
                tracer=self.obs.tracer if self.obs.tracer.enabled else None,
                rank=self.host,
            )
            self.obs.flight = self._flight
        if cfg.obs_watchdog and self._flight is not None:
            from xflow_tpu.obs.watchdog import Watchdog

            self._watchdog = Watchdog(
                self._flight,
                input_s=cfg.obs_watchdog_input_s,
                device_s=cfg.obs_watchdog_device_s,
                serve_s=cfg.obs_watchdog_serve_s,
                poll_s=cfg.obs_watchdog_poll_s,
                flight_out=self._flight_path(),
                metrics_logger=self.metrics_logger,
                tracer=self.obs.tracer if self.obs.tracer.enabled else None,
                log=self._log,
            )
            self._watchdog.start()
        # Live telemetry plane (obs/export.py, docs/OBSERVABILITY.md
        # "Operating a live fleet"): a host resource sampler emitting
        # `resource` rows into this run's metrics stream, and a
        # standalone /metrics exposition endpoint over the live
        # registry for runs with no HTTP surface of their own.  Both
        # are reaped by close() before the metrics logger shuts.
        self._resource_sampler = None
        self._exporter = None
        if cfg.obs_resource_every_s > 0 and self.metrics_logger is not None:
            from xflow_tpu.obs.export import ResourceSampler

            self._resource_sampler = ResourceSampler(
                metrics_logger=self.metrics_logger,
                registry=self.obs.registry if self.obs.enabled else None,
                interval_s=cfg.obs_resource_every_s,
            )
            self._resource_sampler.start()
        if cfg.obs_export_port:
            from xflow_tpu.obs.export import MetricsExporter

            # rank offsets the port so N single-box trainers coexist
            self._exporter = MetricsExporter(
                self.obs.registry,
                port=cfg.obs_export_port + self.host,
            )
            self._exporter.start()
            self._log(
                f"metrics exporter serving {self._exporter.address}"
                "/metrics"
            )
        # Lock-order sanitizer (analysis/sanitizer.py): when armed —
        # Config flag or XFLOW_LOCK_SANITIZER env — the obs-stack locks
        # are swapped for instrumented wrappers so real acquisition
        # orders can be cross-checked against the static XF007 graph
        # (scripts/check_concurrency.py).  The bare env-var presence
        # check only gates the IMPORT (off = nothing imported or
        # allocated); armed() is the one authoritative parse.
        if cfg.obs_lock_sanitizer or os.environ.get("XFLOW_LOCK_SANITIZER"):
            from xflow_tpu.analysis.sanitizer import armed, global_sanitizer

            if cfg.obs_lock_sanitizer or armed():
                san = global_sanitizer()
                for obj in (
                    self.metrics_logger,
                    self._flight,
                    self._watchdog,
                    self.obs.registry,
                ):
                    if obj is not None and hasattr(obj, "_lock"):
                        san.instrument(obj, "_lock")
        self._profiled = False
        self._preempted = False
        self._preempt_agreed = False
        # Hot-table frequency remap (io/freq.py): loaded from the
        # checkpoint dir when present, else measured from a deterministic
        # sample of the training data (identical on every host).
        self.remap = None
        if cfg.hot_size_log2:
            self._init_remap()
        else:
            # guard the reverse of _init_remap's table_size check: a
            # checkpoint trained WITH a hot table stores rows in the
            # permuted space; resuming it hot-off would read wrong rows
            path = self._remap_path()
            if path is not None:
                if os.path.exists(path):
                    raise ValueError(
                        f"{path} exists: this checkpoint_dir was trained "
                        "with a hot table; set hot_size_log2 to match "
                        "(or use a fresh checkpoint_dir)"
                    )

    # -- observability lifecycle -------------------------------------------

    def _run_header(self) -> dict:
        """Contents of the metrics file's ``run_start`` delimiter row:
        enough to tell two appended runs apart (the file opens in append
        mode) and to check their configs match without any log parsing."""
        return {
            "run_id": f"{int(time.time() * 1000):x}-{os.getpid():x}",
            "config_digest": self.cfg.digest(),
            "rank": self.host,
            "num_hosts": self.num_hosts,
            "model": self.cfg.model,
        }

    def _flight_path(self) -> str:
        path = self.cfg.obs_flight_out
        if path and self.num_hosts > 1:
            path = f"{path}-r{self.host}"
        return path

    def _pulse(self, phase: str) -> None:
        """Trainer heartbeat: the main loop just entered ``phase``.
        Feeds the flight recorder's ring AND the watchdog's liveness
        view — one clock read + locked dict store, nothing device-side
        (XF002)."""
        if self._flight is not None:
            self._flight.note_phase(phase, self._global_steps)

    def _note_batch_shape(self, batch: Batch, shard_idx: int) -> None:
        """Record the in-flight batch geometry, but only when it
        CHANGES (static loader shapes mean ~one note per run; a new
        shape right before a hang is exactly the forensic that points
        at a recompile or a mis-sized external batch)."""
        if self._flight is None:
            return
        shape = (batch.batch_size, batch.max_nnz, batch.hot_nnz)
        if shape != self._last_batch_shape:
            self._last_batch_shape = shape
            self._flight.note_batch({
                "rows": batch.batch_size,
                "cold_nnz": batch.max_nnz,
                "hot_nnz": batch.hot_nnz,
                "shard": shard_idx,
            })

    def flight_dump(self, reason: str, exc: BaseException | None = None) -> None:
        """Mark the run as dying for ``reason``; close() writes the
        dump (once) as part of the flush path, so metrics flush and
        dump ordering stay on the one exit road."""
        if self._flight_reason is None:
            self._flight_reason = (reason, exc)

    def close(self) -> None:
        """Flush-and-close observability outputs: stop the watchdog,
        write the flight dump when a crash/preemption was recorded,
        then the metrics JSONL and (when tracing) the Chrome trace
        export.  Idempotent.  train() calls it on its exception and
        preemption paths; use the Trainer as a context manager (or
        call this) to cover every other exit."""
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._resource_sampler is not None:
            # joins the sampler thread and emits the final resource
            # row — must precede metrics_logger.close() below
            self._resource_sampler.close()
        if self._exporter is not None:
            self._exporter.close()
        for gen in list(self._live_transfer):
            # GeneratorExit at the suspended yield -> _transfer_ahead's
            # abandon path -> shutdown(wait=False, cancel_futures=True):
            # idle ring workers exit on the signal, and a WEDGED one
            # cannot hang this (crash/preemption) cleanup path
            gen.close()
        self._live_transfer.clear()
        for it in list(self._live_prefetch):
            it.close()
        self._live_prefetch.clear()
        if self.step.store is not None:
            # flush the pending miss write-back and reap the promotion
            # worker (bounded join; a leak lands as a health row before
            # the metrics logger closes below)
            self.step.store.close()
        if self._stream_cursor is not None:
            # durable ingestion position on EVERY exit road (the
            # checkpoint discipline): a graceful preemption mid-shard
            # resumes at the exact batch offset; only a hard kill
            # falls back to the shard-boundary flush (<= 1 shard
            # replayed — at-least-once, docs/CONTINUOUS.md)
            try:
                self._stream_cursor.flush()
            except OSError as e:
                self._log(f"stream cursor flush failed: {e}")
        if (
            self._flight is not None
            and self._flight_reason is not None
            and self._flight_path()
        ):
            reason, exc = self._flight_reason
            self._flight_reason = None  # one dump per incident
            path = self._flight_path()
            if self._watchdog is not None and self._watchdog.dump_count:
                # the watchdog already dumped DURING the stall (stuck
                # thread stacks — the forensic that matters); the
                # exit-time dump must not overwrite it
                path = f"{path}.exit"
            self._flight.dump(path, reason, exc=exc)
        self._export_trace()
        from xflow_tpu import chaos

        if self.metrics_logger is not None:
            # an armed registry must not keep logging through a closed
            # logger (detach is a no-op for anyone else's logger)
            chaos.detach_logger(self.metrics_logger)
            self.metrics_logger.close()
        if self._armed_chaos:
            # the schedule this trainer armed from its config dies with
            # it (idempotent; env-armed registries are left alone)
            chaos.disarm()

    def _export_trace(self) -> None:
        if not (self.cfg.obs_trace_out and self.obs.tracer.enabled):
            return
        path = self.cfg.obs_trace_out
        if self.num_hosts > 1:
            path = f"{path}-r{self.host}"
        try:
            self.obs.tracer.export_chrome(path)
        except OSError as e:
            self._log(f"trace export failed: {e}")

    def __enter__(self) -> "Trainer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _remap_path(self) -> str | None:
        if not self.cfg.checkpoint_dir:
            return None
        return os.path.join(self.cfg.checkpoint_dir, "remap.npy")

    def _init_remap(self) -> None:
        cfg = self.cfg
        from xflow_tpu.io import freq

        path = self._remap_path()
        if path is not None:
            existing = freq.load_remap(path)
            if existing is not None:
                if len(existing) != cfg.table_size:
                    raise ValueError(
                        f"saved remap at {path} has {len(existing)} rows "
                        f"but table_size is {cfg.table_size} — "
                        "table_size_log2 changed between runs?"
                    )
                self.remap = existing
                return
        if path is not None and latest_checkpoint(cfg.checkpoint_dir):
            raise ValueError(
                "hot table enabled but this checkpoint_dir holds a "
                "checkpoint trained WITHOUT one (no remap.npy): the table "
                "rows live in the unpermuted key space — set "
                "hot_size_log2=0 to resume it, or use a fresh "
                "checkpoint_dir"
            )
        if not cfg.train_path:
            raise ValueError(
                "hot table enabled but no train_path to sample key "
                "frequencies from and no saved remap in checkpoint_dir"
            )
        # Global shard list (not this host's subset) so every host
        # computes the identical permutation without communication.
        shards = find_shards(cfg.train_path)
        counts = freq.count_keys(
            shards,
            self._parse_fn(),
            cfg.table_size,
            cfg.freq_sample_mib << 20,
            cfg.block_mib << 20,
        )
        self.remap = freq.build_remap(counts, cfg.hot_size)
        mass = freq.hot_mass(counts, self.remap, cfg.hot_size)
        self._log(
            f"hot remap: {cfg.hot_size} rows capture {mass:.1%} of "
            f"sampled feature occurrences"
        )
        if path is not None and self.host == 0:
            os.makedirs(cfg.checkpoint_dir, exist_ok=True)
            freq.save_remap(path, self.remap)

    # -- data --------------------------------------------------------------

    def _parse_fn(self):
        cfg = self.cfg
        return make_parse_fn(
            cfg.table_size,
            cfg.hash_mode,
            cfg.seed,
            prefer_native=cfg.native_parser,
        )

    def _loader(self, path: str) -> ShardLoader:
        cfg = self.cfg
        return ShardLoader(
            path,
            batch_size=cfg.batch_size,
            max_nnz=cfg.max_nnz,
            table_size=cfg.table_size,
            block_mib=cfg.block_mib,
            hash_mode=cfg.hash_mode,
            hash_seed=cfg.seed,
            parse_fn=self._parse_fn(),
            remap=self.remap,
            hot_size=cfg.hot_size,
            hot_nnz=cfg.hot_nnz,
            obs=self.obs,
            # v2 packed shards skip expansion AND re-compaction when
            # the step consumes the dict wire (io/compact.py)
            emit_compact=self.step.dict_wire,
            io_retries=cfg.io_retries,
            io_retry_backoff_s=cfg.io_retry_backoff_s,
            max_quarantined_frac=cfg.max_quarantined_frac,
        )

    def _tracked_prefetch(self, loader: ShardLoader, depth, offset, workers):
        """loader.prefetch registered for explicit shutdown: the
        producer thread (and its open shard file) dies on
        Trainer.close() even if the consumer abandoned the iterator
        mid-shard (crash/preemption) — not whenever the GC notices."""
        it = loader.prefetch(depth, offset, workers)
        self._live_prefetch.add(it)
        return it

    def _parse_workers(self) -> int:
        w = self.cfg.parse_workers
        if w < 0:
            w = max(1, min(6, (os.cpu_count() or 1) - 1))
        return w

    def _my_shards(self, prefix: str) -> list[str]:
        shards = find_shards(prefix)
        return [s for i, s in enumerate(shards) if i % self.num_hosts == self.host]

    def iter_train_batches(
        self, start_shard: int = 0, start_offset: int = 0
    ) -> Iterator[tuple[Batch, int, int]]:
        """Yields (batch, shard_index, resume_offset) over one epoch.

        With ``Config.input_streams > 1`` the epoch's shard list fans
        out over N concurrent reader streams (io/fanout.py) — same
        batch sequence, same resume contract, parallel host work.

        When metrics are on, each finished shard logs a ``shard`` row
        with its observed loader throughput — wall-clock measured at
        the consumer, so it includes parse + pack + any consumer
        backpressure: the rate the training loop actually saw."""
        shards = self._my_shards(self.cfg.train_path)
        if self.cfg.input_streams > 1:
            yield from self._iter_fanout(shards, start_shard, start_offset)
            return
        depth = self.cfg.prefetch_batches
        for si, path in enumerate(shards):
            if si < start_shard:
                continue
            offset = start_offset if si == start_shard else 0
            loader = self._loader(path)
            workers = self._parse_workers()
            it = (
                self._tracked_prefetch(loader, depth, offset, workers)
                if depth
                else loader.iter_batches(offset, workers)
            )
            t_shard = time.perf_counter()
            examples = 0
            try:
                for batch, resume in it:
                    examples += batch.num_real()
                    self._note_batch_shape(batch, si)
                    yield batch, si, resume
            finally:
                if depth:
                    it.close()
                    self._live_prefetch.discard(it)
            self._log_shard_row(
                si, path, examples, time.perf_counter() - t_shard
            )

    def _log_shard_row(
        self, si: int, path: str, examples: int, dt: float
    ) -> None:
        if self.metrics_logger is None:
            return
        self.metrics_logger.log("shard", {
            "epoch": self.epoch,
            "shard": os.path.basename(path),
            "index": si,
            "examples": examples,
            "seconds": round(dt, 3),
            "examples_per_sec": round(examples / max(dt, 1e-9), 1),
        })

    def _log_stream_rows(self, pool) -> None:
        """Per-stream fan-out accounting (``stream`` rows,
        obs/schema.py): one row per reader stream per epoch with its
        finished-shard totals and backpressure stall — the input of
        `obs doctor`'s stream-straggler diagnosis and `obs summarize`'s
        throughput-spread line."""
        if self.metrics_logger is None:
            return
        for row in pool.stream_stats():
            self.metrics_logger.log("stream", {"epoch": self.epoch, **row})

    def _iter_fanout(
        self, shards: list[str], start_shard: int, start_offset: int
    ) -> Iterator[tuple[Batch, int, int]]:
        """iter_train_batches through the N-stream fan-out
        (io/fanout.py): stream s reads shards i % N == s concurrently,
        each with its own parse workers and host compaction
        (TrainStep.precompact), and the merge restores serial shard
        order — training is bitwise-identical to the one-stream path.
        Per-shard ``shard`` rows keep the serial path's consumer-side
        timing semantics; per-stream ``stream`` rows land when the
        epoch's pool winds down (including the preemption break)."""
        from xflow_tpu.io.fanout import ShardStreamPool

        cfg = self.cfg
        workers = self._parse_workers()
        n_eff = max(1, min(cfg.input_streams, len(shards) - start_shard))
        pool = ShardStreamPool(
            shards,
            self._loader,
            num_streams=cfg.input_streams,
            depth=max(1, cfg.prefetch_batches),
            start_shard=start_shard,
            start_offset=start_offset,
            # the serial path's parse fan-out divides across streams so
            # N streams don't multiply the thread budget
            parse_workers=max(1, workers // n_eff) if workers > 1 else workers,
            transform=self.step.precompact,
            obs=self.obs,
        )
        self._live_prefetch.add(pool)
        cur: int | None = None
        examples = 0
        t_shard = time.perf_counter()
        try:
            for batch, si, resume in pool:
                if cur is None:
                    cur = si
                elif si != cur:
                    self._log_shard_row(
                        cur, shards[cur], examples,
                        time.perf_counter() - t_shard,
                    )
                    cur = si
                    examples = 0
                    t_shard = time.perf_counter()
                examples += batch.num_real()
                self._note_batch_shape(batch, si)
                yield batch, si, resume
            if cur is not None:
                self._log_shard_row(
                    cur, shards[cur], examples,
                    time.perf_counter() - t_shard,
                )
        finally:
            pool.close()
            self._live_prefetch.discard(pool)
            self._log_stream_rows(pool)

    def _empty_batch(self) -> Batch:
        """All-padding batch (weights/mask 0): a no-op training step with
        the same static shapes the loader produces."""
        from xflow_tpu.io.batch import make_batch

        cfg = self.cfg
        b = cfg.batch_size
        k = cfg.max_nnz + (cfg.hot_nnz if cfg.hot_size else 0)
        z_i = np.zeros((b, k), np.int32)
        z_f = np.zeros((b, k), np.float32)
        return make_batch(
            z_i, z_i, z_f, z_f,
            np.zeros(b, np.float32), np.zeros(b, np.float32),
            cfg.hot_size, cfg.hot_nnz,
        )

    def _synced_batches(
        self,
        it: Iterator[tuple[Batch, int, int]],
        vote_preempt: bool = False,
    ) -> Iterator[tuple[Batch, int, int]]:
        """SPMD step-count agreement across hosts.

        Every pjit'd step is collective over the global mesh, so all
        processes MUST call it the same number of times — but hosts own
        different shard subsets (``i % num_hosts``) whose sizes differ
        when shards don't divide evenly (the reference had no such
        constraint: its workers were fully async, SURVEY §2 parallelism
        table).  A host whose local data ran out keeps feeding
        zero-weight padding batches (no-op updates: FTRL/SGD are
        idempotent at g=0) until every host votes done; the vote rides a
        1-int allgather per step.

        With ``vote_preempt`` the same allgather carries this host's
        preemption flag (vote 2): ANY host's SIGTERM stops every host at
        the same step, and the caller sees ``self._preempt_agreed`` —
        required because the subsequent checkpoint save is itself
        collective.  Single-host runs skip the voting entirely (the
        caller checks ``self._preempted`` directly).
        """
        if self.num_hosts == 1:
            yield from it
            return
        from jax.experimental import multihost_utils

        local_done = False
        last = (0, 0)
        pad: Batch | None = None
        while True:
            item = None
            if not local_done:
                try:
                    item = next(it)
                except StopIteration:
                    local_done = True
            mine = 2 if (vote_preempt and self._preempted) else (
                0 if local_done else 1
            )
            votes = np.asarray(
                multihost_utils.process_allgather(np.int32(mine))
            )
            if votes.max() == 2:
                self._preempt_agreed = True
                return  # a host was preempted: stop everyone at this step
            if votes.max() == 0:
                return  # every host is out of data
            if item is not None:
                last = (item[1], item[2])
                yield item
            else:
                # keep collectives aligned while other hosts still train
                if pad is None:
                    pad = self._empty_batch()
                yield pad, last[0], last[1]

    def _transfer_ahead(
        self, it: Iterator[tuple[Batch, int, int]], depth: int | None = None
    ) -> Iterator[tuple[Any, int, int]]:
        """Device staging ring: run put_batch (host-side compaction +
        h2d transfer) up to ``depth`` (Config.transfer_ahead_depth,
        >= 2 for double buffering) items ahead on worker threads so
        link round-trips AND per-batch compaction overlap device
        compute — measured 2-3x e2e on the tunneled link
        (docs/PERF.md).  Worker count scales with the ring depth
        (capped by the host's cores) so a deep ring can compact one
        batch while others are on the wire; the pending deque preserves
        submission order, so batch order — and training — is identical
        at ANY depth.  Single-host only: multi-host put_batch is
        collective (host_local_array_to_global_array) and must stay on
        the voting thread."""
        from concurrent.futures import ThreadPoolExecutor

        if depth is None:
            depth = self.cfg.transfer_ahead_depth
        ex = ThreadPoolExecutor(_ring_workers(depth))
        try:
            pending: deque = deque()
            for batch, si, resume in it:
                pending.append(
                    (ex.submit(self.step.put_batch, batch), si, resume)
                )
                # queue occupancy: steadily == depth+1 means the device
                # is the bottleneck; hovering at 0-1 means the consumer
                # drains transfers as fast as they arrive (input-bound)
                self.obs.observe("transfer_ahead_depth", len(pending))
                if len(pending) > depth:
                    fut, psi, presume = pending.popleft()
                    yield fut.result(), psi, presume
            while pending:
                fut, psi, presume = pending.popleft()
                yield fut.result(), psi, presume
            ex.shutdown()  # normal path: workers idle, returns fast
        except BaseException:
            # abandon (GeneratorExit from close(), a worker raising, a
            # consumer exception): do NOT wait — a worker wedged in a
            # put_batch h2d transfer would otherwise hang the caller's
            # cleanup path forever (XF006: shutdown must be bounded).
            # cancel_futures drops the un-started queue; idle workers
            # exit on the shutdown signal; a wedged in-flight worker is
            # left to finish on its own rather than held against.
            ex.shutdown(wait=False, cancel_futures=True)
            raise

    def prepare_batch(self, batch: Batch) -> Batch:
        """Bring an externally built Batch (raw hash-space keys, see
        io/batch.py) into this model's key space: apply the hot remap
        and re-steer the hot/cold sections.  Loader-produced batches are
        already prepared; this is for user-supplied batches.  Delegates
        to the shared io/batch.py::remap_batch (also the serving
        engine's prepare path — serve/engine.py)."""
        from xflow_tpu.io.batch import remap_batch

        return remap_batch(
            batch, self.remap, self.cfg.hot_size, self.cfg.hot_nnz
        )

    # -- training ----------------------------------------------------------

    def _stop_profile(self, flush_metric) -> None:
        """The ONE jax.profiler.stop_trace site.  The flush-then-stop
        invariant lives here: dispatch is async, so without blocking on
        a step metric first the trace would close before the profiled
        steps' device work ran."""
        if flush_metric is not None:
            # this block IS the flush-then-stop invariant: it must run
            # unconditionally, span or no span (xf: ignore[XF002])
            jax.device_get(flush_metric["logloss"])  # flush pending work
        jax.profiler.stop_trace()
        self._profiled = True

    def _timed_save(self, shard_idx: int, offset: int) -> float:
        """save() booked as the 'checkpoint' phase; returns the seconds
        so train_epoch reports checkpoint_seconds separately instead of
        letting saves silently deflate examples_per_sec.  A FAILED save
        (I/O error, ckpt.* failpoint) leaves a ``health`` row before
        re-raising — the crash-atomic protocol guarantees the previous
        complete generation survives for ``--resume auto``."""
        t0 = time.perf_counter()
        try:
            with self.obs.phase("checkpoint"):
                self.save(shard_idx, offset)
        except BaseException as e:
            if self.metrics_logger is not None:
                from xflow_tpu.obs.schema import health_row

                self.metrics_logger.log("health", health_row(
                    cause="checkpoint_save_failed",
                    channel="train",
                    silence_seconds=0.0,
                    threshold_seconds=0.0,
                    detail=f"{type(e).__name__}: {e} — previous "
                    "complete generation remains restorable",
                ))
            raise
        return time.perf_counter() - t0

    def train_epoch(self, start_shard: int = 0, start_offset: int = 0) -> dict:
        cfg = self.cfg
        obs = self.obs
        obs.registry.reset()  # epoch-scoped phase accounting
        t0 = time.time()
        steps = 0
        ckpt_seconds = 0.0
        preempted = False
        device_metrics = []  # fetched once at epoch end to keep dispatch async
        profiling = False
        self._preempt_agreed = False
        last_cursor = (start_shard, start_offset)
        stream = self._synced_batches(
            self.iter_train_batches(start_shard, start_offset),
            vote_preempt=True,
        )
        # single-host: overlap host->device transfer with device compute
        # (multi-host keeps put_batch on the voting thread — collective;
        # the tiered store pins the ring OFF so the cold store keeps
        # read-your-writes order — a ring worker planning batch N+1
        # would otherwise cold-fetch keys whose batch-N write-back is
        # still in flight; docs/STORE.md "Ordering")
        ahead = self.num_hosts == 1 and self.step.store is None
        if ahead:
            stream = self._transfer_ahead(stream)
            # reaped below on the normal path; by Trainer.close() when
            # an exception (or an unclosed preemption) abandons it
            self._live_transfer.add(stream)
        it = iter(stream)
        with obs.span("train_epoch", {"epoch": self.epoch}):
            while True:
                t_step = time.perf_counter()
                try:
                    # waiting on the input iterator IS the input stall:
                    # with transfer-ahead/prefetch on, parse, pack and
                    # h2d all hide behind this wait; whatever doesn't
                    # overlap device time surfaces here
                    self._pulse("input_stall")
                    with obs.phase("input_stall"):
                        batch, shard_idx, resume = next(it)
                except StopIteration:
                    break
                self._pulse("dispatch")
                last_cursor = (shard_idx, resume)
                if (
                    cfg.profile_dir
                    and not self._profiled
                    and self._global_steps >= cfg.profile_start_step
                    and not profiling
                ):
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                    profile_end = self._global_steps + cfg.profile_steps
                arrays = batch if ahead else self.step.put_batch(batch)
                self.state, metrics = self.step.dispatch_train(
                    self.state, arrays
                )
                obs.observe("step_seconds", time.perf_counter() - t_step)
                steps += 1
                self._global_steps += 1
                device_metrics.append(metrics)
                if self.step.store is not None and (
                    steps % cfg.store_promote_every == 0
                ):
                    # between-steps tier maintenance: flush the miss
                    # write-back, apply the promotion worker's plan
                    # (store/tiered.py::maintain — in-flight batches
                    # never see a moving key->slot map)
                    self.state = self.step.store.maintain(
                        self.state, obs=obs
                    )
                if profiling and self._global_steps >= profile_end:
                    self._stop_profile(metrics)
                    profiling = False
                if cfg.checkpoint_dir and cfg.checkpoint_every_steps and (
                    steps % cfg.checkpoint_every_steps == 0
                ):
                    ckpt_seconds += self._timed_save(shard_idx, resume)
                if self.num_hosts == 1 and self._preempted:
                    ckpt_seconds += self._timed_save(shard_idx, resume)
                    preempted = True
                    break
            if self._preempt_agreed:
                # multi-host: every process left the loop at the same
                # step; the (collective) save is safe here
                ckpt_seconds += self._timed_save(*last_cursor)
                preempted = True
            if profiling:  # epoch ended inside the profile window
                self._stop_profile(
                    device_metrics[-1] if device_metrics else None
                )
            if self.step.store is not None:
                # epoch-end flush: the LAST step's miss write-back must
                # land before eval/save/export reads the cold store
                self.state = self.step.store.maintain(self.state, obs=obs)
            self._pulse("device_block")
            with obs.phase("device_block"):
                host_metrics = jax.device_get(device_metrics)
            self._pulse("idle")  # epoch compute over — silence is benign
        if ahead:
            # no-op when the stream ran dry; on a preemption break it
            # shuts the staging-ring executor down NOW instead of
            # leaving its threads to the garbage collector
            self._live_transfer.discard(stream)
            stream.close()
        seen = float(sum(m["count"] for m in host_metrics))
        ll_sum = float(
            sum(m["logloss"] * m["count"] for m in host_metrics)
        )
        dt = time.time() - t0
        return self._epoch_stats(
            seen, ll_sum, steps, dt, ckpt_seconds, preempted, ahead
        )

    def _epoch_stats(
        self,
        seen: float,
        ll_sum: float,
        steps: int,
        dt: float,
        ckpt_seconds: float,
        preempted: bool,
        ahead: bool,
    ) -> dict:
        """Epoch record assembly: throughput (checkpoint time excluded),
        per-phase wall-second accounting, stall fraction, step-time
        percentiles.  Phase semantics (docs/OBSERVABILITY.md): `phases`
        holds main-thread-EXCLUSIVE intervals whose sum accounts for
        (nearly all of) `seconds`; `overlapped` holds worker-thread
        phases (parse/pack, and h2d under transfer-ahead) that hide
        behind input_stall and must not be added to the wall-clock."""
        snap = self.obs.registry.snapshot(reset=True)
        phases = snap.phase_seconds()
        overlapped = {
            k: round(phases.pop(k), 6)
            for k in ("parse", "pack") if k in phases
        }
        if ahead and "h2d" in phases:
            overlapped["h2d"] = round(phases.pop("h2d"), 6)
        phases = {k: round(v, 6) for k, v in phases.items()}
        step_hist = snap.hists.get("step_seconds", {})
        stats = {
            "epoch": self.epoch,
            "examples": seen,
            "steps": steps,
            "train_logloss": ll_sum / max(seen, 1.0),
            "examples_per_sec": seen / max(dt - ckpt_seconds, 1e-9),
            "seconds": dt,
            "checkpoint_seconds": round(ckpt_seconds, 6),
            "preempted": preempted,
            "phases": phases,
            "overlapped": overlapped,
            "input_stall_frac": round(
                phases.get("input_stall", 0.0) / max(dt, 1e-9), 6
            ),
            "step_time_p50": round(step_hist.get("p50", 0.0), 6),
            "step_time_p90": round(step_hist.get("p90", 0.0), 6),
            "step_time_p99": round(step_hist.get("p99", 0.0), 6),
        }
        occ = snap.hists.get("transfer_ahead_depth")
        if occ:
            stats["transfer_ahead_depth_mean"] = round(occ["mean"], 3)
        if "wire.bytes" in snap.counters:
            # host->device wire accounting (parallel/step.py::_book_wire)
            # -> the epoch's `wire` metrics row; compaction_ratio = cold
            # occurrences per big-table touch the dict wire left (1.0 =
            # no dedup happened / plain wire)
            touched = snap.counters.get("wire.cold_touched", 0)
            occ_in = snap.counters.get("wire.cold_occ", 0)
            stats["_wire"] = {
                "epoch": self.epoch,
                "format": self.step.wire_format,
                "wire_bytes_per_example": round(
                    snap.counters["wire.bytes"]
                    / max(snap.counters.get("wire.examples", 0), 1),
                    2,
                ),
                "compaction_ratio": round(
                    occ_in / touched if touched else 1.0, 3
                ),
            }
        if "store.hit_occ" in snap.counters or (
            "store.miss_occ" in snap.counters
        ):
            # tiered-store accounting (store/tiered.py::plan_batch +
            # maintain) -> the epoch's `store` metrics row; hit rate is
            # occurrence-weighted (the share of feature occurrences the
            # hot tier served without a cold fetch)
            hits = snap.counters.get("store.hit_occ", 0)
            misses = snap.counters.get("store.miss_occ", 0)
            stats["_store"] = {
                "epoch": self.epoch,
                "hot_hit_rate": round(
                    hits / max(hits + misses, 1), 6
                ),
                "promotions": int(
                    snap.counters.get("store.promotions", 0)
                ),
                "demotions": int(
                    snap.counters.get("store.demotions", 0)
                ),
                "cold_fetch_seconds": round(
                    snap.counters.get("store.cold_fetch_seconds", 0.0), 6
                ),
                "hot_occupancy": round(
                    self.step.store.occupancy_frac()
                    if self.step.store is not None
                    else 0.0,
                    6,
                ),
            }
        if "loader.parse_bytes" in snap.counters:
            stats["parse_mb_per_sec"] = round(
                snap.counters["loader.parse_bytes"] / 2**20
                / max(overlapped.get("parse", 0.0), 1e-9),
                2,
            )
        return stats

    def train(self) -> list[dict]:
        """Full training run (reference batch_training loop over epochs,
        lr_worker.cc:179-205, with epoch banner every 30 at :202).

        Graceful preemption (capability gap vs the reference, whose only
        recovery story was ``pkill -9`` + full restart — SURVEY §5):
        with checkpointing enabled, SIGTERM/SIGINT during training
        finishes the in-flight step, saves weights + optimizer state +
        data cursor, and returns cleanly; a later run with --resume
        continues mid-shard.
        """
        history = []
        restore_handlers = self._install_preemption_handler()
        try:
            while self.epoch < self.cfg.epochs:
                start_shard, start_offset = self._resume_cursor
                self._resume_cursor = (0, 0)
                stats = self.train_epoch(start_shard, start_offset)
                wire_stats = stats.pop("_wire", None)
                store_stats = stats.pop("_store", None)
                history.append(stats)
                if self.metrics_logger is not None:
                    self.metrics_logger.log("train_epoch", stats)
                    if wire_stats is not None:
                        self.metrics_logger.log("wire", wire_stats)
                    if store_stats is not None:
                        self.metrics_logger.log("store", store_stats)
                self._log_device_mem()
                if self.epoch % 30 == 0 or self.epoch == self.cfg.epochs - 1:
                    self._log(
                        f"epoch {self.epoch}: logloss={stats['train_logloss']:.6f} "
                        f"examples/s={stats['examples_per_sec']:.0f}"
                    )
                if stats.get("preempted"):
                    # the process is about to exit for a restart: dump
                    # the flight record and flush metrics + trace NOW
                    self.flight_dump("preemption")
                    self.close()
                    break
                self.epoch += 1
                if self.cfg.checkpoint_dir:
                    # _timed_save: a failed epoch-end save emits its
                    # checkpoint_save_failed health row before the
                    # crash path takes over
                    self._timed_save(0, 0)
                if (
                    self.cfg.eval_every_epochs
                    and self.cfg.test_path
                    and self.epoch < self.cfg.epochs  # final eval is the caller's
                    and self.epoch % self.cfg.eval_every_epochs == 0
                ):
                    self.evaluate()
        except BaseException as e:
            # crash path: flight-dump the black box (active phase,
            # thread stacks, recent state), then never lose buffered
            # metrics rows or the trace
            self.flight_dump("exception", exc=e)
            self.close()
            raise
        finally:
            restore_handlers()
        return history

    def _log_device_mem(self) -> None:
        """Per-epoch jax.local_devices() memory gauge (``device_mem``
        row).  memory_stats() is unsupported on some backends (CPU
        returns None/raises) — the row still lands with whatever fields
        exist, so the schema stays uniform across backends."""
        if self.metrics_logger is None or not self.cfg.obs_device_memory:
            return
        devices = []
        for d in jax.local_devices():
            entry: dict[str, Any] = {
                "id": int(d.id), "platform": str(d.platform),
            }
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms:
                for key in (
                    "bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                ):
                    if key in ms:
                        entry[key] = int(ms[key])
            devices.append(entry)
        self.metrics_logger.log(
            "device_mem", {"epoch": self.epoch, "devices": devices}
        )

    def _install_preemption_handler(self) -> Callable[[], None]:
        """Install SIGTERM/SIGINT → checkpoint-and-stop handlers (only
        with checkpointing on, only from the main thread).  Returns a
        restore function.  The handler fires ONCE and then restores the
        previous handlers, so a second signal escalates normally (e.g.
        a second Ctrl-C kills a wedged step instead of being swallowed).
        """
        self._preempted = False
        self._preempt_agreed = False
        if not self.cfg.checkpoint_dir:
            return lambda: None
        import signal

        prev = {}

        def restore():
            for sig, h in prev.items():
                signal.signal(sig, h)
            prev.clear()

        def on_signal(signum, frame):
            self._log(
                f"signal {signum}: finishing step, checkpointing, stopping "
                "(send again to force)"
            )
            self._preempted = True
            restore()

        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(sig, on_signal)
        except ValueError:  # not the main thread — no handler possible
            return lambda: None
        return restore

    # -- continuous training (stream/; docs/CONTINUOUS.md) -----------------

    def register_stream_cursor(self, cursor) -> None:
        """Attach a stream ingestion cursor (stream/follower.py::
        IngestCursor) so close() flushes it durably on every exit road
        — crash, preemption, normal return."""
        self._stream_cursor = cursor

    def train_stream(self, batches) -> Iterator[tuple[int, Any]]:
        """Iterator-driven training for the continuous loop: consume
        ``(batch, meta)`` pairs (stream/follower.py ShardFollower) and
        dispatch one train step each, yielding ``(steps_so_far, meta)``
        AFTER the step so the driver can cut delta exports / drive
        rollouts between steps against a consistent state.

        Phase accounting, heartbeats, and store maintenance match
        train_epoch's hot loop; epoch semantics (multi-host shard
        voting, the transfer-ahead ring) deliberately do not apply —
        the stream is unbounded and single-host by construction (the
        continuous driver's topology, stream/driver.py)."""
        if self.num_hosts > 1:
            raise RuntimeError(
                "train_stream is single-host: continuous ingestion has "
                "no shard-count voting (docs/CONTINUOUS.md)"
            )
        cfg = self.cfg
        obs = self.obs
        steps = 0
        it = iter(batches)
        while True:
            t_step = time.perf_counter()
            self._pulse("input_stall")
            with obs.phase("input_stall"):
                try:
                    batch, meta = next(it)
                except StopIteration:
                    break
            self._pulse("dispatch")
            arrays = self.step.put_batch(batch)
            self.state, _ = self.step.dispatch_train(self.state, arrays)
            obs.observe("step_seconds", time.perf_counter() - t_step)
            steps += 1
            self._global_steps += 1
            if self.step.store is not None and (
                steps % cfg.store_promote_every == 0
            ):
                self.state = self.step.store.maintain(self.state, obs=obs)
            yield steps, meta
        if self.step.store is not None:
            # stream-end flush: the last step's miss write-back must
            # land before any export reads the cold store
            self.state = self.step.store.maintain(self.state, obs=obs)
        self._pulse("idle")

    # -- evaluation --------------------------------------------------------

    def evaluate(self, pred_out: str | None = None) -> dict:
        cfg = self.cfg
        obs = self.obs
        obs.registry.reset()  # eval-scoped phase accounting
        t0 = time.time()
        acc = AucAccumulator()
        pred_file = None
        out_path = pred_out if pred_out is not None else cfg.pred_out
        per_block = bool(out_path) and cfg.pred_style == "per_block"
        if per_block:
            os.makedirs(out_path, exist_ok=True)
            # clear THIS host's stale artifacts: a previous eval with
            # more blocks would otherwise leave old pred files mixed
            # into the new set ('single' mode truncates on open)
            for f in glob.glob(
                os.path.join(out_path, f"pred_{self.host}_*.txt")
            ):
                os.remove(f)
        elif out_path and self.host == 0:
            pred_file = open(out_path, "w")
        def batches() -> Iterator[tuple[Batch, int, int]]:
            workers = self._parse_workers()
            for path in self._my_shards(cfg.test_path):
                # Reference predict uses doubled block size (lr_worker.cc:80).
                loader = self._loader(path)
                loader.block_bytes = (cfg.block_mib * 2) << 20
                it = self._tracked_prefetch(
                    loader, cfg.prefetch_batches, 0, workers
                )
                try:
                    for batch, resume in it:
                        yield batch, 0, resume
                finally:
                    it.close()
                    self._live_prefetch.discard(it)

        try:
            # predict is collective too — keep hosts step-aligned
            block_idx = 0
            it = iter(self._synced_batches(batches()))
            while True:
                try:
                    self._pulse("input_stall")
                    with obs.phase("input_stall"):
                        batch, _, _ = next(it)
                except StopIteration:
                    break
                self._pulse("h2d")
                # books 'h2d' inline; predict=True lets the tiered
                # store ship param-only miss blocks
                arrays = self.step.put_batch(batch, predict=True)
                self._pulse("dispatch")
                with obs.phase("dispatch"):
                    garr = self.step.predict(self.state, arrays)
                if self.num_hosts > 1:
                    # inverse of put_batch's host-local→global assembly:
                    # this host's rows of the sharded pctr
                    from jax.experimental import multihost_utils

                    garr = multihost_utils.global_array_to_host_local_array(
                        garr, self.mesh, self.step._bsharding.spec
                    )
                self._pulse("device_block")
                with obs.phase("device_block"):
                    pctr = np.asarray(jax.device_get(garr))
                acc.add(batch.labels, pctr, batch.weights)
                if per_block and batch.weights.sum() > 0:
                    # reference artifact granularity: one
                    # pred_<rank>_<block>.txt per worker per block
                    # (lr_worker.cc:74-78); padding batches (multi-host
                    # step alignment) produce no file
                    with obs.phase("pred_write"), open(
                        os.path.join(
                            out_path, f"pred_{self.host}_{block_idx}.txt"
                        ),
                        "w",
                    ) as f:
                        for y, p, w in zip(batch.labels, pctr, batch.weights):
                            if w > 0:
                                f.write(f"{int(y)}\t{p:.6f}\n")
                    block_idx += 1
                elif pred_file is not None:
                    with obs.phase("pred_write"):
                        for y, p, w in zip(batch.labels, pctr, batch.weights):
                            if w > 0:
                                # "(label, pctr)" lines, lr_worker.cc:62-68.
                                pred_file.write(f"{int(y)}\t{p:.6f}\n")
        finally:
            if pred_file is not None:
                pred_file.close()
        with obs.phase("metrics_compute"):
            if self.num_hosts > 1:
                # Rank-sum AUC is not decomposable over shard subsets.  The
                # round-1 design allgathered every host's (label, pctr)
                # pairs — O(test set) memory on EVERY host.  Now each host
                # folds its pairs into fixed-size histograms (utils.metrics
                # .HistAuc) and only those reduce across hosts: O(buckets)
                # traffic/memory regardless of test-set size.  Logloss stays
                # exact; AUC uses midrank ties on BOTH the single- and
                # multi-host paths (AucAccumulator.compute is auc_midrank),
                # so host count never changes the reported AUC beyond
                # histogram quantization (< 1e-6 bucket width).
                from xflow_tpu.parallel.multihost import allgather_exact
                from xflow_tpu.utils.metrics import HistAuc

                hist = HistAuc()
                labels, pctr = acc.pairs()
                hist.add(labels, pctr)
                # bit-exact gather: the float64 histograms/sums must not be
                # canonicalized to float32 (counts > 2^24 would drift)
                summed = {
                    k: allgather_exact(v).sum(axis=0)
                    for k, v in hist.state().items()
                }
                hist = HistAuc.from_state(summed)
                ll, auc = hist.compute()
                n = hist.count()
                pos = hist.num_pos()
            else:
                ll, auc = acc.compute()
                n = acc.count()
                pos = int(acc.pairs()[0].sum()) if n else 0
        snap = obs.registry.snapshot(reset=True)
        phases = snap.phase_seconds()
        # parse/pack run on the eval loader's prefetch thread, h2d is
        # inline here — same exclusive/overlapped split as train_epoch
        overlapped = {
            k: round(phases.pop(k), 6)
            for k in ("parse", "pack") if k in phases
        }
        result = {
            "epoch": self.epoch,
            "logloss": ll,
            "auc": auc,
            "examples": n,
            "tp": pos,
            "fp": n - pos,
            "seconds": round(time.time() - t0, 3),
            "phases": {k: round(v, 6) for k, v in phases.items()},
            "overlapped": overlapped,
        }
        self._log(f"logloss: {ll:.6f}\tauc = {auc:.6f}\ttp = {pos} fp = {n - pos}")
        if self.metrics_logger is not None:
            self.metrics_logger.log("eval", result)
        self._pulse("idle")  # eval over — watchdog silence is benign
        return result

    # -- checkpointing -----------------------------------------------------

    def save(
        self,
        shard_idx: int = 0,
        offset: int = 0,
        extra: dict | None = None,
    ) -> str | None:
        """``extra`` merges additional keys into the manifest's cursor
        dict — the continuous driver embeds the stream ingestion
        cursor snapshot there (``{"stream": ...}``) so restore() hands
        it back and model state + stream position rewind together
        (docs/CONTINUOUS.md)."""
        if not self.cfg.checkpoint_dir:
            return None
        self._pulse("checkpoint")
        # Per-host cursors: shard_idx/offset are HOST-LOCAL (each host
        # walks its own ``i % num_hosts`` shard subset), so the manifest
        # records every host's position; a host restores its own.
        cursors = [{"shard": int(shard_idx), "offset": int(offset)}]
        if self.num_hosts > 1:
            # allgather_exact: byte offsets are int64 (shards can exceed
            # 2 GiB) and must not pass through JAX's 32-bit
            # canonicalization
            from xflow_tpu.parallel.multihost import allgather_exact

            pairs = allgather_exact(
                np.asarray([shard_idx, offset], np.int64)
            ).reshape(self.num_hosts, 2)
            cursors = [
                {"shard": int(s), "offset": int(o)} for s, o in pairs
            ]
        cursor = {
            "epoch": self.epoch,
            "num_hosts": self.num_hosts,
            "cursors": cursors,
            # rank-0 view kept for human inspection of the manifest
            "shard": cursors[0]["shard"],
            "offset": cursors[0]["offset"],
        }
        if extra:
            cursor.update(extra)
        if self.step.store is not None:
            # tier-erased fold (store/tiered.py): touched rows from
            # BOTH tiers, key-sorted, in the row-range shard format
            path = self.step.store.save_checkpoint(
                self.cfg.checkpoint_dir,
                self.state,
                cursor,
                self.cfg.to_json(),
                keep=self.cfg.checkpoint_keep,
            )
        else:
            path = save_checkpoint(
                self.cfg.checkpoint_dir,
                self.state,
                cursor,
                self.cfg.to_json(),
                keep=self.cfg.checkpoint_keep,
            )
        if self._flight is not None:
            self._flight.note_checkpoint(self._global_steps)
        # close the 'checkpoint' activity: after a post-epoch save the
        # trainer may sit in caller code indefinitely, and lingering
        # 'checkpoint' as the last note would read as checkpoint_stall
        self._pulse("idle")
        return path

    def restore(self, auto: bool = False) -> dict | None:
        """Resume from a checkpoint if one exists; returns the cursor
        or None.  Each host resumes from ITS OWN saved cursor; if the
        host count changed since the save, the shard→host assignment
        (``i % num_hosts``) no longer matches and the epoch restarts
        from the beginning instead of silently skipping or replaying
        data.

        ``auto`` (``--resume auto``, docs/ROBUSTNESS.md): walk EVERY
        generation newest-first and restore the newest *complete,
        loadable* one — a generation with no manifest (killed or
        corrupted mid-commit) or a transiently unreadable one is
        skipped with a ``checkpoint_fallback`` health row instead of
        crashing the resume.  Plain mode keeps the LATEST-marker fast
        path and treats an unusable checkpoint as "start fresh"."""
        if not self.cfg.checkpoint_dir:
            return None
        from xflow_tpu.chaos import ChaosError
        from xflow_tpu.utils.checkpoint import (
            IncompatibleCheckpoint,
            checkpoint_candidates,
        )

        if auto:
            candidates = checkpoint_candidates(self.cfg.checkpoint_dir)
        else:
            path = latest_checkpoint(self.cfg.checkpoint_dir)
            candidates = [path] if path is not None else []
        cursor = None
        for path in candidates:
            try:
                if self.step.store is not None:
                    self.state, cursor = self.step.store.load_checkpoint(
                        path, self.state
                    )
                else:
                    self.state, cursor = load_checkpoint(path, self.state)
                break
            except IncompatibleCheckpoint as e:
                if not auto:
                    self._log(
                        f"ignoring unusable checkpoint: {e} — starting "
                        "fresh"
                    )
                    return None
                self._fallback_health(path, e)
            except (OSError, ValueError, ChaosError) as e:
                if not auto:
                    raise
                self._fallback_health(path, e)
        if cursor is None:
            return None
        self.epoch = int(cursor.get("epoch", 0))
        cursors = cursor.get("cursors")
        saved_hosts = int(cursor.get("num_hosts", 1))
        if cursors is not None and saved_hosts == self.num_hosts:
            mine = cursors[self.host]
            self._resume_cursor = (int(mine["shard"]), int(mine["offset"]))
        elif cursors is not None:
            self._log(
                f"checkpoint was saved with {saved_hosts} hosts, now "
                f"{self.num_hosts}: shard assignment changed — restarting "
                f"epoch {self.epoch} from the beginning"
            )
            self._resume_cursor = (0, 0)
        else:
            self._resume_cursor = (
                int(cursor.get("shard", 0)),
                int(cursor.get("offset", 0)),
            )
        return cursor

    def _fallback_health(self, path: str, err: BaseException) -> None:
        """One skipped restore candidate (auto mode): log + health row
        so `obs doctor` sees the fallback instead of a silent rewind."""
        self._log(
            f"resume auto: skipping unusable checkpoint {path} "
            f"({type(err).__name__}: {err}) — falling back to the next "
            "newest complete generation"
        )
        if self.metrics_logger is not None:
            from xflow_tpu.obs.schema import health_row

            self.metrics_logger.log("health", health_row(
                cause="checkpoint_fallback",
                channel="train",
                silence_seconds=0.0,
                threshold_seconds=0.0,
                detail=f"{os.path.basename(path)}: "
                f"{type(err).__name__}: {err}",
            ))
