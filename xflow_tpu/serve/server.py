"""Concurrent HTTP front end over a ReplicaFleet — the RPC tier.

Dependency-free (stdlib ``http.server.ThreadingHTTPServer``): every
connection gets a handler thread, every scoring request routes through
the fleet's admission control into a replica MicroBatcher, so the
device still sees bucketed coalesced batches no matter how many
concurrent sockets are open.

Endpoints:

* ``POST /v1/score`` — JSON ``{"rows": [{"keys": [...], "slots":
  [...]?, "vals": [...]?}, ...]}`` → ``{"pctr": [...], "digest":
  ...}``.  A single row may be passed as ``{"keys": [...]}``.
* ``POST /v1/score_packed`` — the packed-binary wire (below), for
  callers who care about encode cost; same scoring path.
* ``GET /healthz`` — liveness + serving digest + rollout state.
* ``GET /v1/stats`` — non-destructive fleet stats snapshot.
* ``POST /v1/rollout`` — ``{"artifact": dir, "canary_frac": 0.1,
  "auto_commit": false, ...}`` begins a staged rollout;
  ``POST /v1/rollout/commit`` / ``/v1/rollout/abort`` resolve it.

Backpressure is TYPED: an admission-control shed returns **429** with
``{"error": "backpressure", "cause": "queue_depth"|"queue_age",
"retry_after_ms": ...}`` and a ``Retry-After`` header — clients
distinguish "slow down" from "broken" without string-matching.

Packed wire (little-endian): request ``b"XFS1" u32 nrows`` then per
row ``u16 nnz, nnz*u64 keys, nnz*u32 slots, nnz*f32 vals``; response
``u32 n, n*f32 pctr``.  ``encode_packed_request`` /
``decode_packed_response`` are the client halves (serve/loadgen.py
uses them).  A traced request uses magic ``b"XFS2"`` with a 17-byte
trace triple (``u64 trace_id, u64 parent_span_id, u8 sampled``)
between the magic and ``nrows`` — the packed-wire twin of the
``X-XFlow-Trace`` header (obs/reqtrace.py); either way the response
echoes the trace id in an ``X-XFlow-Trace`` response header so
clients can name their slow requests.

Liveness: the accept loop beats the flight recorder's ``http`` channel
from ``service_actions`` (called every poll of ``serve_forever``), so
a watchdog classifies a wedged accept loop as ``serve_accept_stall``
while the per-batch ``serve`` channel keeps covering the scoring path.
The same hook drives ``fleet.rollout_tick()`` — auto rollouts advance
even when no admin client is polling.

Shutdown (XF006): ``close()`` stops the accept loop, joins the server
thread with a timeout, waits briefly for in-flight handlers to drain
through the fleet, then closes the fleet (which drains every replica
queue — accepted requests all score) and flushes the final stats rows.
``python -m xflow_tpu.serve serve`` routes SIGTERM here.
"""

from __future__ import annotations

import json
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from concurrent.futures import TimeoutError as FutureTimeout

from xflow_tpu.chaos import ChaosError, failpoint
from xflow_tpu.obs.reqtrace import TraceContext, format_header, parse_header
from xflow_tpu.serve.fleet import (
    QOS_CLASSES,
    ReplicaFleet,
    RolloutError,
    ShedError,
)

PACKED_MAGIC = b"XFS1"
# traced packed request (ISSUE 16): magic + u64 trace_id + u64
# parent_span_id + u8 sampled, then the XFS1 body from nrows on
PACKED_TRACE_MAGIC = b"XFS2"
# how long a handler waits on its scoring futures before 504
# (ServeTier default; Config.serve_score_timeout_s overrides per tier)
SCORE_TIMEOUT_S = 60.0
# per-connection socket timeout on handler reads/writes (ServeTier
# default; Config.serve_socket_timeout_s overrides per tier): a client
# stalled mid-request releases its handler thread instead of pinning
# it forever (analysis rule XF017)
SOCKET_TIMEOUT_S = 30.0


# -- packed wire --------------------------------------------------------------


def encode_packed_request(rows: list, trace=None) -> bytes:
    """Rows are ``(keys, slots, vals)`` tuples (slots/vals may be
    None) or bare key arrays — the ``featurize_raw`` row protocol.
    With ``trace`` (a ``TraceContext``) the XFS2 traced variant is
    emitted so the server correlates its spans with this client."""
    if trace is None:
        out = [PACKED_MAGIC, struct.pack("<I", len(rows))]
    else:
        out = [
            PACKED_TRACE_MAGIC,
            struct.pack(
                "<QQB",
                trace.trace_id,
                trace.parent_span_id,
                1 if trace.sampled else 0,
            ),
            struct.pack("<I", len(rows)),
        ]
    for row in rows:
        keys, slots, vals = row if isinstance(row, tuple) else (
            row, None, None
        )
        k = np.asarray(keys, dtype=np.uint64)
        n = len(k)
        s = (
            np.zeros(n, np.uint32) if slots is None
            else np.asarray(slots, dtype=np.uint32)
        )
        v = (
            np.ones(n, np.float32) if vals is None
            else np.asarray(vals, dtype=np.float32)
        )
        if len(s) != n or len(v) != n:
            raise ValueError("keys/slots/vals length mismatch")
        out.append(struct.pack("<H", n))
        out.append(k.astype("<u8").tobytes())
        out.append(s.astype("<u4").tobytes())
        out.append(v.astype("<f4").tobytes())
    return b"".join(out)


def decode_packed_request(buf: bytes) -> list[tuple]:
    """Rows only — the pre-tracing signature every existing caller
    holds; traced callers use :func:`decode_packed_request_traced`."""
    return decode_packed_request_traced(buf)[0]


def decode_packed_request_traced(
    buf: bytes,
) -> tuple[list[tuple], TraceContext | None]:
    """(rows, trace) — ``trace`` is None for the untraced XFS1 magic."""
    trace: TraceContext | None = None
    off = 4
    if buf[:4] == PACKED_TRACE_MAGIC:
        if len(buf) < 25:  # magic + trace triple + nrows
            raise ValueError("truncated packed request (trace triple)")
        tid, pid, flag = struct.unpack_from("<QQB", buf, off)
        if tid == 0 or flag not in (0, 1):
            raise ValueError("bad packed-request trace triple")
        trace = TraceContext(tid, pid, bool(flag))
        off += 17
    elif buf[:4] != PACKED_MAGIC:
        raise ValueError(
            f"bad packed-request magic {buf[:4]!r} (want {PACKED_MAGIC!r}"
            f" or {PACKED_TRACE_MAGIC!r})"
        )
    (nrows,) = struct.unpack_from("<I", buf, off)
    off += 4
    rows: list[tuple] = []
    for _ in range(nrows):
        if off + 2 > len(buf):
            raise ValueError("truncated packed request (row header)")
        (nnz,) = struct.unpack_from("<H", buf, off)
        off += 2
        need = nnz * (8 + 4 + 4)
        if off + need > len(buf):
            raise ValueError("truncated packed request (row payload)")
        keys = np.frombuffer(buf, "<u8", nnz, off).astype(np.int64)
        off += nnz * 8
        slots = np.frombuffer(buf, "<u4", nnz, off).astype(np.int32)
        off += nnz * 4
        vals = np.frombuffer(buf, "<f4", nnz, off).astype(np.float32)
        off += nnz * 4
        rows.append((keys, slots, vals))
    if off != len(buf):
        raise ValueError(
            f"packed request has {len(buf) - off} trailing byte(s)"
        )
    return rows, trace


def encode_packed_response(pctr: np.ndarray) -> bytes:
    p = np.asarray(pctr, dtype=np.float32)
    return struct.pack("<I", len(p)) + p.astype("<f4").tobytes()


def decode_packed_response(buf: bytes) -> np.ndarray:
    (n,) = struct.unpack_from("<I", buf, 0)
    out = np.frombuffer(buf, "<f4", n, 4)
    if len(out) != n:
        raise ValueError("truncated packed response")
    return np.array(out)


# -- server -------------------------------------------------------------------


class _TierServer(ThreadingHTTPServer):
    # handler threads must not block process exit on a wedged socket.
    # NOTE: stdlib _Threads.append SKIPS daemon threads, so
    # server_close() joins nothing here — the drain contract ("every
    # accepted request scores and gets its response written") is
    # instead enforced by ServeTier's in-flight handler counter:
    # close() waits (bounded) for _inflight to hit zero BEFORE closing
    # the fleet, covering handlers still parsing a body (not yet
    # submitted) and handlers still writing a response
    daemon_threads = True
    tier: "ServeTier"

    def service_actions(self) -> None:
        # accept-loop heartbeat (every serve_forever poll): the
        # watchdog's `http` channel — silence here means the front
        # door is wedged, regardless of how the scoring path feels
        tier = self.tier
        try:
            # chaos site: a transient accept-loop/socket-layer error.
            # The loop SURVIVES it (the chaos row is already logged by
            # the registry; handler sockets are untouched) — an accept
            # loop that dies on one bad poll is a total outage, which
            # is exactly what the watchdog's serve_accept_stall exists
            # to catch if this discipline ever regresses.
            failpoint("serve.accept")
        except ChaosError:
            tier.accept_faults += 1
        if tier.flight is not None:
            tier.flight.note_http("accept")
        # auto rollouts advance here so they progress with no admin
        # client polling.  Known tradeoff: an auto-COMMIT clones the
        # candidate per replica on this thread, pausing accepts (new
        # connections queue in the listen backlog) for the clone time
        # — once per rollout; fleets where that outlasts the watchdog
        # http threshold should commit via POST /v1/rollout/commit
        # (handler thread) instead of auto_commit.
        try:
            for fleet in tier.fleets():
                fleet.rollout_tick()
        except Exception as e:
            # a failing transition (clone OOM, logger I/O) must not
            # unwind serve_forever and turn a rollout problem into a
            # total serving outage — the rollout stays open, so the
            # canary-stuck doctor diagnosis surfaces it
            import warnings

            warnings.warn(
                f"rollout_tick failed (rollout left open): {e!r}",
                RuntimeWarning,
                stacklevel=2,
            )


class _Handler(BaseHTTPRequestHandler):
    server_version = "xflow-serve/1"
    protocol_version = "HTTP/1.1"

    # -- plumbing -----------------------------------------------------------

    def setup(self) -> None:
        # BaseHTTPRequestHandler's `timeout` class attribute is None,
        # so a client that stalls mid-request (half-open TCP, paused
        # upload) would pin this handler thread indefinitely; a timed-
        # out read surfaces as ConnectionError/OSError in _do_post's
        # client-went-away handling
        self.timeout = self.server.tier.socket_timeout_s  # type: ignore[attr-defined]
        super().setup()

    def log_message(self, fmt: str, *args: Any) -> None:
        pass  # metrics rows, not stderr chatter

    @property
    def tier(self) -> "ServeTier":
        return self.server.tier  # type: ignore[attr-defined]

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def _respond(self, code: int, payload: bytes, ctype: str,
                 headers: dict[str, str] | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _json(self, code: int, doc: dict,
              headers: dict[str, str] | None = None) -> None:
        self._respond(
            code,
            json.dumps(doc, sort_keys=True).encode(),
            "application/json",
            headers,
        )

    def _shed(self, e: ShedError) -> None:
        retry_ms = max(
            1, int(self.tier.fleet.policy.deadline_budget_s * 1000)
        )
        doc = {
            "error": "backpressure",
            "cause": e.cause,
            "depth": e.depth,
            "queue_age_ms": round(e.queue_age_s * 1000.0, 3),
            "retry_after_ms": retry_ms,
        }
        if e.qos is not None:
            doc["qos"] = e.qos
        self._json(
            429, doc,
            headers={"Retry-After": str(max(1, retry_ms // 1000))},
        )

    # -- scoring ------------------------------------------------------------

    def _trace_ctx(self, fleet, wire=None) -> TraceContext | None:
        """The request's TraceContext at the front door: a packed-wire
        triple beats the ``X-XFlow-Trace`` header beats minting fresh
        (only when the target fleet traces at all — no sink, no ids).
        A malformed header is treated as absent, never a 400: a bad
        trace annotation must not fail the request it rides."""
        if wire is not None:
            return wire
        ctx = parse_header(self.headers.get("X-XFlow-Trace"))
        if ctx is not None:
            return ctx
        sink = getattr(fleet, "reqtrace", None)
        return sink.mint() if sink is not None else None

    def _trace_headers(self, ctx) -> dict[str, str] | None:
        """Echo the trace id on the response so clients correlate."""
        return None if ctx is None else {
            "X-XFlow-Trace": format_header(ctx)
        }

    def _qos(self) -> str | None:
        """The request's QoS admission class from the ``X-XFlow-QoS``
        header (the HTTP twin of the XFB1 frame's QoS byte); None =
        the fleet default.  Unlike a malformed trace header, an
        UNKNOWN class is a 400: the client asked for an admission
        contract the fleet does not have, and silently downgrading it
        would defeat the whole point of classed shedding."""
        raw = self.headers.get("X-XFlow-QoS")
        if raw is None:
            return None
        qos = raw.strip().lower()
        if qos not in QOS_CLASSES:
            raise ValueError(
                f"unknown QoS class {qos!r} (want one of {QOS_CLASSES})"
            )
        return qos

    def _score_rows(self, rows: list[tuple], trace=None,
                    qos: str | None = None) -> np.ndarray:
        """All-or-nothing admission: the first shed fails the whole
        request (already-admitted rows still score and resolve — the
        batcher drains them — but the client is told to back off).
        Every row of one HTTP request rides ONE trace id (each gets
        its own span)."""
        fleet = self.tier.fleet
        futs = [fleet.submit(*row, trace=trace, qos=qos) for row in rows]
        deadline = time.perf_counter() + self.tier.score_timeout_s
        return np.asarray([
            f.result(timeout=max(0.001, deadline - time.perf_counter()))
            for f in futs
        ], dtype=np.float32)

    def _handle_score_json(self, body: bytes) -> None:
        doc = json.loads(body.decode())
        if not isinstance(doc, dict):
            raise ValueError(
                "request body must be a JSON object "
                '({"rows": [...]} or one row {"keys": [...]})'
            )
        raw = doc["rows"] if "rows" in doc else [doc]
        if not isinstance(raw, list):
            raise ValueError('"rows" must be a list of row objects')
        rows = []
        for r in raw:
            if not isinstance(r, dict):
                raise ValueError('each row must be an object with "keys"')
            try:
                keys = np.asarray(r["keys"], dtype=np.int64)
                slots = (
                    np.asarray(r["slots"], dtype=np.int32)
                    if r.get("slots") is not None else None
                )
                vals = (
                    np.asarray(r["vals"], dtype=np.float32)
                    if r.get("vals") is not None else None
                )
            except TypeError as e:
                # np.asarray raises TypeError on ragged/object fields
                # — a client problem, not a server fault (400 not 500)
                raise ValueError(f"bad row field: {e}") from None
            rows.append((keys, slots, vals))
        ctx = self._trace_ctx(self.tier.fleet)
        pctr = self._score_rows(rows, trace=ctx, qos=self._qos())
        self._json(200, {
            "pctr": [round(float(p), 6) for p in pctr],
            "digest": self.tier.fleet.digest,
        }, headers=self._trace_headers(ctx))

    def _handle_score_packed(self, body: bytes) -> None:
        rows, wire_ctx = decode_packed_request_traced(body)
        ctx = self._trace_ctx(self.tier.fleet, wire=wire_ctx)
        pctr = self._score_rows(rows, trace=ctx, qos=self._qos())
        self._respond(
            200, encode_packed_response(pctr), "application/octet-stream",
            headers=self._trace_headers(ctx),
        )

    # -- HTTP verbs ---------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler protocol)
        self.tier._handler_enter()
        try:
            self._do_get()
        finally:
            self.tier._handler_exit()

    def _do_get(self) -> None:
        try:
            if self.path == "/healthz":
                fleet = self.tier.fleet
                doc = {
                    "status": "serving",
                    "digest": fleet.digest,
                    "model": fleet.cfg.model,
                    "replicas": fleet.replicas,
                    "depth": fleet.depth(),
                    "rollout": fleet.rollout_state(),
                }
                casc = self.tier.cascade
                if casc is not None:
                    doc["cascade"] = {
                        "retrieval_digest": casc.retrieval.digest,
                        "ranking_digest": casc.ranking.digest,
                        "k": casc.k,
                    }
                self._json(200, doc)
            elif self.path == "/v1/stats":
                doc = self.tier.fleet.stats()
                if self.tier.cascade is not None:
                    doc["cascade"] = self.tier.cascade.stats()
                if self.tier.watchdog is not None:
                    doc["watchdog"] = self.tier.watchdog.state()
                if self.tier.alerts is not None:
                    doc["alerts"] = self.tier.alerts.summary()
                self._json(200, doc)
            elif self.path == "/metrics":
                # Prometheus text exposition rendered from a lock-safe
                # registry snapshot (obs/export.py) — non-destructive,
                # so scraping never perturbs the stats-window counters
                from xflow_tpu.obs.export import render_exposition

                text = render_exposition(
                    self.tier.fleet.registry.snapshot(reset=False)
                )
                self._respond(
                    200,
                    text.encode(),
                    ctype="text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._json(404, {"error": f"no such path {self.path}"})
        except ConnectionError:
            pass  # client went away mid-read/write; nothing to answer
        except Exception as e:  # handler threads must answer, not die
            try:
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
            except ConnectionError:
                pass  # the failure WAS the dead socket

    def do_POST(self) -> None:  # noqa: N802
        self.tier._handler_enter()
        try:
            self._do_post()
        finally:
            self.tier._handler_exit()

    def _rollout_fleet(self, doc: dict):
        """The fleet a rollout request targets: ``stage`` routes to a
        cascade stage ("retrieval"/"ranking"); default is the tier's
        primary fleet — either stage rolls out INDEPENDENTLY through
        its own canary gate."""
        stage = doc.get("stage")
        if stage is None:
            return self.tier.fleet
        casc = self.tier.cascade
        if casc is None:
            raise ValueError(
                f"stage {stage!r} given but this tier serves no "
                "cascade"
            )
        if stage == "retrieval":
            return casc.retrieval
        if stage == "ranking":
            return casc.ranking
        raise ValueError(
            f"unknown stage {stage!r} (want 'retrieval' or 'ranking')"
        )

    @staticmethod
    def _request_k(doc) -> int | None:
        """Validated optional per-request k (400 on garbage — a
        non-numeric k must not surface as a 500 TypeError)."""
        k = doc.get("k") if isinstance(doc, dict) else None
        if k is None:
            return None
        try:
            k = int(k)
        except (TypeError, ValueError):
            raise ValueError(f"bad k: {k!r}") from None
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        return k

    @staticmethod
    def _request_rows(doc) -> list[tuple]:
        """Validated rows from a JSON body — the _handle_score_json
        client-garbage contract (400, never 500, on malformed input),
        shared by the topk/recommend endpoints."""
        if not isinstance(doc, dict):
            raise ValueError(
                "request body must be a JSON object "
                '({"rows": [...]} or one row {"keys": [...]})'
            )
        raw = doc["rows"] if "rows" in doc else [doc]
        if not isinstance(raw, list):
            raise ValueError('"rows" must be a list of row objects')
        rows = []
        for r in raw:
            if not isinstance(r, dict):
                raise ValueError('each row must be an object with "keys"')
            try:
                rows.append((
                    np.asarray(r["keys"], dtype=np.int64),
                    np.asarray(r["slots"], dtype=np.int32)
                    if r.get("slots") is not None else None,
                    np.asarray(r["vals"], dtype=np.float32)
                    if r.get("vals") is not None else None,
                ))
            except TypeError as e:
                # np.asarray raises TypeError on ragged/object fields
                # — a client problem, not a server fault (400 not 500)
                raise ValueError(f"bad row field: {e}") from None
        return rows

    def _handle_topk(self, body: bytes) -> None:
        """Top-k retrieval over the tier's topk fleet: rows of
        USER-side features -> per-row candidate ids + dot scores."""
        fleet = self.tier.topk_fleet()
        doc = json.loads(body.decode())
        rows = self._request_rows(doc)
        k = self._request_k(doc)
        ctx = self._trace_ctx(fleet)
        futs = [fleet.submit(*row, trace=ctx) for row in rows]
        deadline = time.perf_counter() + self.tier.score_timeout_s
        items, scores = [], []
        for f in futs:
            ids, sc, _ = f.result(  # 3rd: the producing index (cascade's)
                timeout=max(0.001, deadline - time.perf_counter())
            )
            if k is not None:
                ids, sc = ids[:k], sc[:k]
            items.append([int(i) for i in ids])
            scores.append([round(float(s), 6) for s in sc])
        self._json(200, {
            "items": items,
            "scores": scores,
            "digest": fleet.digest,
        }, headers=self._trace_headers(ctx))

    def _handle_recommend(self, body: bytes) -> None:
        """The cascade front door: USER features -> retrieval top-k ->
        ranked candidates (serve/cascade.py)."""
        casc = self.tier.cascade
        if casc is None:
            raise ValueError("this tier serves no cascade")
        doc = json.loads(body.decode())
        rows = self._request_rows(doc)
        if len(rows) != 1:
            raise ValueError(
                f"recommend takes exactly one row, got {len(rows)}"
            )
        ctx = self._trace_ctx(casc.retrieval)
        result = casc.recommend(
            *rows[0], k=self._request_k(doc), trace=ctx
        )
        self._json(200, result, headers=self._trace_headers(ctx))

    def _do_post(self) -> None:
        try:
            body = self._body()
            if self.path == "/v1/score":
                self._handle_score_json(body)
            elif self.path == "/v1/score_packed":
                self._handle_score_packed(body)
            elif self.path == "/v1/topk":
                self._handle_topk(body)
            elif self.path == "/v1/recommend":
                self._handle_recommend(body)
            elif self.path == "/v1/rollout":
                doc = json.loads(body.decode()) if body else {}
                state = self._rollout_fleet(doc).begin_rollout(
                    doc["artifact"],
                    canary_frac=float(doc.get(
                        "canary_frac", self.tier.default_canary_frac
                    )),
                    min_canary_requests=int(
                        doc.get("min_canary_requests", 32)
                    ),
                    max_error_frac=float(doc.get("max_error_frac", 0.0)),
                    max_p99_ms=doc.get("max_p99_ms"),
                    auto_commit=bool(doc.get("auto_commit", False)),
                    force=bool(doc.get("force", False)),
                )
                self._json(200, {"rollout": state})
            elif self.path == "/v1/rollout/commit":
                doc = json.loads(body.decode()) if body else {}
                health = self._rollout_fleet(doc).commit_rollout(
                    force=bool(doc.get("force", False))
                )
                self._json(200, {"committed": health})
            elif self.path == "/v1/rollout/abort":
                doc = json.loads(body.decode()) if body else {}
                health = self._rollout_fleet(doc).abort_rollout(
                    detail="api"
                )
                self._json(200, {"aborted": health})
            else:
                self._json(404, {"error": f"no such path {self.path}"})
        except ShedError as e:
            self._shed(e)
        except RolloutError as e:
            self._json(409, {"error": str(e)})
        except (TimeoutError, FutureTimeout) as e:
            # admitted but the scoring future outlived the tier's
            # score_timeout_s: a gateway timeout, not a server bug
            self._json(504, {"error": f"scoring timed out: {e}"})
        except (ValueError, KeyError, json.JSONDecodeError,
                struct.error) as e:
            # struct.error: truncated/garbage packed wire is a client
            # problem, same as unparseable JSON
            self._json(400, {"error": f"{type(e).__name__}: {e}"})
        except ConnectionError:
            pass  # client went away mid-read/write; nothing to answer
        except Exception as e:
            try:
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
            except ConnectionError:
                pass  # the failure WAS the dead socket


class ServeTier:
    """The running server: fleet + accept loop + drain discipline."""

    def __init__(
        self,
        fleet: ReplicaFleet,
        host: str = "127.0.0.1",
        port: int = 0,
        flight=None,
        poll_s: float = 0.25,
        drain_timeout_s: float = 30.0,
        default_canary_frac: float = 0.1,
        cascade=None,
        score_timeout_s: float = SCORE_TIMEOUT_S,
        socket_timeout_s: float = SOCKET_TIMEOUT_S,
    ):
        self.fleet = fleet
        # timeout discipline (XF017): every handler wait is bounded —
        # scoring futures by score_timeout_s (504 past it), socket
        # reads/writes by socket_timeout_s (_Handler.setup).  The serve
        # CLI wires these from Config.serve_{score,socket}_timeout_s.
        if score_timeout_s <= 0 or socket_timeout_s <= 0:
            raise ValueError(
                "score_timeout_s and socket_timeout_s must be > 0"
            )
        self.score_timeout_s = score_timeout_s
        self.socket_timeout_s = socket_timeout_s
        # retrieval→ranking cascade (serve/cascade.py): when set, the
        # tier additionally serves /v1/topk (the cascade's retrieval
        # fleet) and /v1/recommend, and rollout endpoints accept a
        # ``stage`` selector.  ``fleet`` stays the primary point-score
        # surface — conventionally the cascade's ranking fleet, so
        # /v1/score traffic and cascade traffic share replicas the
        # way mixed production traffic would.
        self.cascade = cascade
        self.flight = flight
        # optional live-telemetry attachments (serve CLI wires these):
        # a Watchdog whose .state() and an AlertEvaluator whose
        # .summary() enrich GET /v1/stats — set once before start(),
        # read-only from handler threads thereafter
        self.watchdog = None
        self.alerts = None
        self.default_canary_frac = default_canary_frac
        # survived serve.accept failpoint fires (written only from the
        # accept loop, read by tests/the chaos gate after close)
        self.accept_faults = 0
        self._poll_s = poll_s
        self._drain_timeout_s = drain_timeout_s
        self._httpd = _TierServer((host, port), _Handler)
        self._httpd.tier = self
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._closed = False
        self._final_rows: dict = {}
        # live handler-thread count (daemon handlers are NOT joined by
        # server_close — see _TierServer); close() drains on this
        self._inflight = 0

    def fleets(self) -> list:
        """Every fleet this tier fronts (primary + cascade stages,
        deduped by identity) — the accept loop ticks each one's auto
        rollout."""
        out = [self.fleet]
        if self.cascade is not None:
            for f in (self.cascade.retrieval, self.cascade.ranking):
                if all(f is not g for g in out):
                    out.append(f)
        return out

    def topk_fleet(self) -> ReplicaFleet:
        """The fleet behind /v1/topk: the cascade's retrieval stage,
        or the primary fleet when it is itself a topk fleet."""
        if self.cascade is not None:
            return self.cascade.retrieval
        if getattr(self.fleet, "topk", False):
            return self.fleet
        raise ValueError(
            "this tier serves no top-k fleet (load a retrieval "
            "artifact with ReplicaFleet(..., topk=True) or front a "
            "cascade)"
        )

    def _handler_enter(self) -> None:
        with self._lock:
            self._inflight += 1

    def _handler_exit(self) -> None:
        with self._lock:
            self._inflight -= 1

    def inflight(self) -> int:
        """Handler threads currently between accept and response-
        written — the drain barrier's second condition (a handler may
        hold an accepted request it has not yet submitted, which
        ``fleet.pending()`` cannot see)."""
        with self._lock:
            return self._inflight

    @property
    def running(self) -> bool:
        """The accept loop should be beating: started and not closed —
        the watchdog's pending probe for the ``http`` channel
        (``wd.set_pending("http", lambda: tier.running)``): silence
        while True is a serve_accept_stall, silence after close() is
        just a stopped server."""
        with self._lock:
            return self._thread is not None and not self._closed

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServeTier":
        with self._lock:
            if self._closed:
                raise RuntimeError("ServeTier is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._serve,
                    name="xflow-serve-accept",
                    daemon=True,
                )
                self._thread.start()
        return self

    def _serve(self) -> None:
        self._httpd.serve_forever(poll_interval=self._poll_s)

    def close(self) -> dict:
        """Graceful drain: stop accepting, join the accept loop, wait
        for in-flight handlers to push their work into the replica
        queues, then close the fleet (drains every accepted request)
        and return the final stats rows.  Idempotent."""
        with self._lock:
            first = not self._closed
            self._closed = True
            thread = self._thread
            self._thread = None
        if not first:
            return self._final_rows
        if thread is not None:
            # shutdown() blocks on serve_forever's is-shut-down event;
            # on a never-started tier that event never sets, so only
            # a live accept loop gets the shutdown handshake
            self._httpd.shutdown()
            thread.join(timeout=10.0)
            if thread.is_alive():  # pragma: no cover - wedged socket
                import warnings

                warnings.warn(
                    "serve accept loop outlived shutdown join",
                    RuntimeWarning,
                    stacklevel=2,
                )
        self._httpd.server_close()
        # drain window: every live handler finishes (parse → submit →
        # result → response WRITTEN) and every replica queue empties;
        # only then may the fleet close — an accepted request must
        # never see "ReplicaFleet is closed"
        deadline = time.perf_counter() + self._drain_timeout_s
        while (
            (self.inflight() > 0 or self.fleet.pending())
            and time.perf_counter() < deadline
        ):
            time.sleep(0.01)
        if self.cascade is not None:
            # cascade drains retrieval→ranking in order (its in-flight
            # fan-outs must land before the ranking queues close);
            # fleet.close() below is then idempotent if the primary
            # fleet IS a cascade stage
            self.cascade.close()
        final = self.fleet.close()
        with self._lock:
            self._final_rows = final
        return final

    def __enter__(self) -> "ServeTier":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()
