"""Zipf-traffic load generator — offered-QPS open loop with SLO
accounting (`serve_bench` rows for scripts/check_serve_slo.py).

Closed-loop benchmarking (the ``bench`` CLI) measures latency at
whatever rate the system happens to sustain — it can never show load
shedding, because the clients slow down with the server.  Production
SLOs are stated the other way: *offered* traffic arrives on its own
clock and the tier either serves it inside the deadline or sheds it.
This generator models that:

* **Open loop.**  Arrivals are scheduled on a fixed global timeline
  (request *i* at ``i / offered_qps`` seconds); ``concurrency`` worker
  threads stripe the timeline and never wait for responses — each
  submit attaches a completion callback and moves to its next arrival.
  A slow tier therefore builds real queue depth and real sheds,
  exactly what admission control is for.
* **Zipf keys.**  Request keys are zipf(a)-ranked ids spread over the
  table by an odd multiplier (a bijection mod the power-of-two table
  size, so frequencies are preserved but hot keys aren't clustered) —
  the ads-traffic skew the whole input stack is built around.
* **SLO accounting.**  The summary carries offered vs achieved QPS,
  shed fraction per cause, error count, client-observed e2e p50/p99,
  and the fleet's per-bucket latency percentiles — everything
  ``check_serve_slo.py`` gates on, flushed as one ``serve_bench`` JSONL
  row (plus the fleet's ``serve_stats``/``serve_shed`` rows).

Targets: a :class:`~xflow_tpu.serve.fleet.ReplicaFleet` directly
(in-process — the SLO gate's mode, and the only TRULY open-loop one:
``submit`` returns a Future immediately) or a running HTTP tier via
:class:`HttpTarget`.  **HTTP-mode caveat:** each worker scores
synchronously over its connection (429 → shed), so the offered rate
caps at ``concurrency / e2e_latency`` — size ``--concurrency`` at
least ``offered_qps × expected_e2e_s`` or the run degrades toward
closed-loop; the summary's ``offered_qps`` (requested) vs
``offered_qps_actual`` (what the timeline actually achieved) exposes
the gap, and ``check_serve_slo.py`` gates against the actual.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from xflow_tpu.obs.registry import Histogram
from xflow_tpu.serve.fleet import ShedError

# spread multiplier: odd → bijective mod any power-of-two table size
_SPREAD = 0x9E3779B1


def zipf_rows(
    rng: np.random.Generator,
    n: int,
    *,
    table_size: int,
    nnz: int,
    zipf_a: float = 1.3,
    max_fields: int = 10,
) -> list[tuple]:
    """``n`` featurize_raw-protocol rows of zipf-skewed keys."""
    ranks = rng.zipf(zipf_a, size=(n, nnz)).astype(np.uint64)
    keys = ((ranks * _SPREAD) % table_size).astype(np.int64)
    slots = (np.arange(nnz, dtype=np.int32) % max(max_fields, 1))
    return [(keys[i], slots.copy(), None) for i in range(n)]


class HttpTarget:
    """Adapter giving an HTTP serving tier the fleet ``submit``
    protocol: synchronous single-row POST per call (the worker thread
    IS the connection), resolved-Future return, 429 → backoff-retry →
    ShedError.

    Each worker thread keeps ONE persistent HTTP/1.1 connection
    (thread-local, reconnect-once on a server-closed keep-alive
    socket): a per-request TCP handshake would inflate the client
    e2e percentiles that ``check_serve_slo.py`` gates on with a cost
    the tier never incurred.

    Typed 429s are honored, not just booked: the server's retry
    advice (the typed body's ``retry_after_ms``, falling back to the
    coarser ``Retry-After`` header) seeds a capped exponential backoff
    and the request is re-offered up to ``max_retries`` times before
    it counts as a shed — so chaos runs measure RECOVERY, not just
    rejection.  Retries are counted in ``self.retried`` and land in
    the ``serve_bench`` row."""

    transport = "http"

    def __init__(
        self,
        url: str,
        timeout_s: float = 30.0,
        max_retries: int = 2,
        backoff_cap_s: float = 1.0,
        qos: str | None = None,
    ):
        from urllib.parse import urlsplit

        # default QoS admission class for every request this target
        # offers (rides the X-XFlow-QoS header); per-submit qos=
        # overrides.  None = let the tier apply its fleet default.
        self.qos = qos
        self.url = url.rstrip("/")
        parts = urlsplit(self.url)
        if parts.scheme not in ("http", ""):
            raise ValueError(
                f"HttpTarget speaks plain http, got {parts.scheme!r}"
            )
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or 80
        self._path = parts.path.rstrip("/")
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_cap_s = backoff_cap_s
        self._local = threading.local()
        self._retry_lock = threading.Lock()
        self.retried = 0

    def _post(self, path: str, body: bytes,
              headers: dict | None = None) -> tuple[int, bytes, str]:
        """(status, payload, Retry-After header or "")."""
        import http.client

        hdrs = {"Content-Type": "application/octet-stream"}
        if headers:
            hdrs.update(headers)
        conn = getattr(self._local, "conn", None)
        reused = conn is not None
        for attempt in (0, 1):
            if conn is None:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self.timeout_s
                )
                self._local.conn = conn
            try:
                conn.request(
                    "POST", self._path + path, body=body, headers=hdrs,
                )
                r = conn.getresponse()
                return (
                    r.status, r.read(), r.getheader("Retry-After") or ""
                )
            except ConnectionError:
                # the server may close an idle keep-alive socket
                # between arrivals (RemoteDisconnected subclasses
                # ConnectionResetError) — retry ONCE on a fresh
                # connection, and only when THIS socket had served
                # before.  Anything else (timeout after the request
                # was delivered, failure on a fresh connection) must
                # NOT be re-sent: the tier may have admitted the
                # request, and a duplicate both double-scores it and
                # double-loads a tier that is already struggling — it
                # surfaces as ONE failed request instead.
                conn.close()
                self._local.conn = conn = None
                if attempt or not reused:
                    raise
            except Exception:
                conn.close()
                self._local.conn = conn = None
                raise
        raise AssertionError("unreachable")

    def _retry_delay_s(self, retry_after: str, doc: dict,
                       attempt: int) -> float:
        """Backoff seed, most-precise source first: the typed body's
        ``retry_after_ms`` (our tier's millisecond advice), then the
        Retry-After header (HTTP-spec integer seconds — the tier
        floors it at 1s, so preferring it would park every retry a
        full second), then 50ms — doubled per attempt, capped."""
        base = 0.05
        if "retry_after_ms" in doc:
            base = max(float(doc["retry_after_ms"]) / 1000.0, 0.001)
        elif retry_after:
            try:
                base = max(float(retry_after), 0.001)
            except ValueError:
                pass  # HTTP-date form / garbage: keep the fallback
        return min(base * 2.0**attempt, self.backoff_cap_s)

    def submit(self, keys, slots=None, vals=None, trace=None,
               qos: str | None = None) -> Future:
        """``trace`` (a ``TraceContext``) rides the packed wire's XFS2
        traced variant so the tier's reqtrace spans correlate with
        this client's trace ids (obs/reqtrace.py).  ``qos`` overrides
        the target's default admission class for this request."""
        import json

        from xflow_tpu.serve.server import (
            decode_packed_response,
            encode_packed_request,
        )

        qos = qos if qos is not None else self.qos
        headers = {"X-XFlow-QoS": qos} if qos is not None else None
        fut: Future = Future()
        body = encode_packed_request([(keys, slots, vals)], trace=trace)
        for attempt in range(self.max_retries + 1):
            try:
                status, payload, retry_after = self._post(
                    "/v1/score_packed", body, headers=headers
                )
            except Exception as e:  # connection errors → failed request
                fut.set_exception(e)
                return fut
            if status != 429:
                break
            try:
                doc = json.loads(payload.decode() or "{}")
            except ValueError:
                doc = {}  # a proxy's bare 429 is still a shed
            if attempt == self.max_retries:
                # retries exhausted: NOW it is a shed
                raise ShedError(
                    doc.get("cause", "unknown"),
                    int(doc.get("depth", 0)),
                    float(doc.get("queue_age_ms", 0.0)) / 1000.0,
                    "remote",
                    qos=doc.get("qos", qos),
                )
            with self._retry_lock:
                self.retried += 1
            time.sleep(self._retry_delay_s(retry_after, doc, attempt))
        if status != 200:
            fut.set_exception(RuntimeError(
                f"HTTP {status}: {payload[:200]!r}"
            ))
            return fut
        try:
            fut.set_result(float(decode_packed_response(payload)[0]))
        except Exception as e:
            fut.set_exception(e)
        return fut


class _BinConn:
    """One worker stripe's persistent XFB1 connection: a send side
    (the stripe's own thread), a reader thread resolving responses by
    request id, and a pipelining semaphore bounding frames in
    flight."""

    def __init__(self, sock, depth: int):
        self.sock = sock
        self.lock = threading.Lock()
        self.pending: dict[int, tuple[Future, str]] = {}
        # plain Semaphore, not Bounded: connection teardown releases
        # one permit per failed pending frame, racing normal releases
        self.sem = threading.Semaphore(depth)
        self.rid = 0
        self.buf = bytearray()
        self.off = 0
        self.reader: threading.Thread | None = None
        self.dead = False


class BinaryTarget:
    """The fleet ``submit`` protocol over the persistent XFB1 binary
    transport (serve/binary.py).  Unlike :class:`HttpTarget` — one
    synchronous request per worker connection — this target PIPELINES:
    each worker stripe keeps one persistent connection with up to
    ``pipeline_depth`` frames in flight, and ``submit`` returns its
    Future as soon as the frame is written (a per-connection reader
    thread matches responses by request id).  That makes binary runs
    truly open-loop like in-process fleet runs, at any latency.

    A shed response (status 1 — the wire's typed 429) resolves the
    Future with a :class:`ShedError`; the loadgen's recorder books it
    as a shed, not an error, so both transports produce comparable
    ``serve_bench`` rows.  No transparent retry on this path: a
    pipelined stream re-offering frames would reorder the open-loop
    timeline (``retried`` stays 0; the HTTP leg's backoff is its own
    transport's discipline)."""

    transport = "binary"

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        timeout_s: float = 30.0,
        pipeline_depth: int = 32,
        qos: str | None = None,
    ):
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.pipeline_depth = pipeline_depth
        self.qos = qos
        self._local = threading.local()
        self._conns_lock = threading.Lock()
        self._conns: list[_BinConn] = []
        self._closed = False

    def _conn(self) -> _BinConn:
        import socket as _socket

        conn = getattr(self._local, "conn", None)
        if conn is not None and not conn.dead:
            return conn
        if self._closed:
            raise RuntimeError("BinaryTarget is closed")
        sock = _socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        sock.setsockopt(
            _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1
        )
        conn = _BinConn(sock, self.pipeline_depth)
        # not fire-and-forget: tracked in self._conns and joined
        # (bounded) by close() (xf: ignore[XF006])
        conn.reader = threading.Thread(
            target=self._read_loop, args=(conn,),
            name="xflow-binary-reader", daemon=True,
        )
        conn.reader.start()
        with self._conns_lock:
            self._conns.append(conn)
        self._local.conn = conn
        return conn

    def _read_loop(self, conn: _BinConn) -> None:
        from xflow_tpu.serve.binary import _frame_at

        try:
            # client-side reader, not a serving worker: bounded by the
            # socket timeout (recv raises) and exits on EOF/close —
            # the flight recorder lives server-side
            # (xf: ignore[XF009])
            while True:
                data = conn.sock.recv(1 << 16)
                if not data:
                    break
                conn.buf += data
                # bounded by the bytes just buffered (_frame_at breaks
                # on an incomplete frame) (xf: ignore[XF009])
                while True:
                    got = _frame_at(conn.buf, conn.off)
                    if got is None:
                        break
                    rid, status, body, conn.off = got
                    self._resolve(conn, rid, status, body)
                if conn.off:
                    del conn.buf[:conn.off]
                    conn.off = 0
        except (OSError, ValueError):
            pass  # teardown below fails whatever is still pending
        finally:
            self._teardown(
                conn, ConnectionError("binary connection closed")
            )

    def _resolve(self, conn: _BinConn, rid: int, status: int,
                 body: bytes) -> None:
        import json

        from xflow_tpu.serve import binary
        from xflow_tpu.serve.server import decode_packed_response

        with conn.lock:
            entry = conn.pending.pop(rid, None)
        if entry is None:
            return  # duplicate/unknown id: nothing is waiting
        conn.sem.release()
        fut, qos = entry
        try:
            if status == binary.STATUS_OK:
                fut.set_result(float(decode_packed_response(body)[0]))
                return
            doc = json.loads(body.decode() or "{}")
            if status == binary.STATUS_SHED:
                fut.set_exception(ShedError(
                    doc.get("cause", "unknown"),
                    int(doc.get("depth", 0)),
                    float(doc.get("queue_age_ms", 0.0)) / 1000.0,
                    "remote",
                    qos=doc.get("qos", qos),
                ))
            elif status == binary.STATUS_TIMEOUT:
                fut.set_exception(TimeoutError(
                    doc.get("error", "scoring timed out")
                ))
            else:
                fut.set_exception(RuntimeError(
                    doc.get("error", f"binary status {status}")
                ))
        except Exception as e:  # malformed body: still resolve
            if not fut.done():
                fut.set_exception(e)

    def _teardown(self, conn: _BinConn, err: Exception) -> None:
        with conn.lock:
            conn.dead = True
            pending = list(conn.pending.values())
            conn.pending.clear()
        for fut, _ in pending:
            conn.sem.release()
            if not fut.done():
                fut.set_exception(err)
        try:
            conn.sock.close()
        except OSError:
            pass

    def submit(self, keys, slots=None, vals=None, trace=None,
               qos: str | None = None) -> Future:
        from xflow_tpu.serve.binary import encode_frame
        from xflow_tpu.serve.server import encode_packed_request

        qos = qos if qos is not None else (self.qos or "normal")
        body = encode_packed_request([(keys, slots, vals)], trace=trace)
        conn = self._conn()
        # pipelining bound (XF017-bounded: the server's deadline sweep
        # answers every frame within its score timeout, so permits
        # always come back)
        if not conn.sem.acquire(timeout=self.timeout_s):
            raise TimeoutError(
                f"pipeline full for {self.timeout_s}s "
                f"(depth {self.pipeline_depth})"
            )
        fut: Future = Future()
        with conn.lock:
            if conn.dead:
                conn.sem.release()
                raise ConnectionError("binary connection closed")
            conn.rid += 1
            rid = conn.rid
            conn.pending[rid] = (fut, qos)
        try:
            conn.sock.sendall(encode_frame(rid, qos, body))
        except OSError:
            self._teardown(
                conn, ConnectionError("binary connection closed")
            )
            raise
        return fut

    def close(self) -> None:
        self._closed = True
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.sock.shutdown(2)  # SHUT_RDWR: wake the reader
            except OSError:
                pass
            try:
                conn.sock.close()
            except OSError:
                pass
        for conn in conns:
            if conn.reader is not None:
                conn.reader.join(timeout=5.0)

    def __enter__(self) -> "BinaryTarget":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class _Recorder:
    """Thread-safe completion sink (callbacks run on replica worker
    threads; workers read nothing until the drain barrier)."""

    def __init__(self, slow_k: int = 3) -> None:
        self._lock = threading.Lock()
        self._lat = Histogram(capacity=65536)
        self.submitted = 0
        self.completed = 0
        self.errors = 0
        self.shed: dict[str, int] = {}
        self._shed_total = 0
        # per-QoS-class offered/shed counts (mixed-class runs)
        self.qos_offered: dict[str, int] = {}
        self.qos_shed: dict[str, int] = {}
        # client-observed slowest-k (e2e seconds, trace id hex) — the
        # serve_bench row names its slowest exemplars by trace id so a
        # p99 outlier maps straight onto its reqtrace span tree
        self._slow_k = slow_k
        self._slow: list[tuple[float, str]] = []

    def note_submit(self, qos: str | None = None) -> None:
        with self._lock:
            self.submitted += 1
            if qos is not None:
                self.qos_offered[qos] = self.qos_offered.get(qos, 0) + 1

    def note_shed(self, cause: str, qos: str | None = None) -> None:
        with self._lock:
            self.shed[cause] = self.shed.get(cause, 0) + 1
            self._shed_total += 1
            if qos is not None:
                self.qos_shed[qos] = self.qos_shed.get(qos, 0) + 1

    def note_error(self) -> None:
        """A request that failed AT submit (no Future ever existed) —
        books a completed-with-error so ``outstanding`` stays exact."""
        with self._lock:
            self.completed += 1
            self.errors += 1

    def note_done(
        self, fut: Future, t0: float, trace_id: str | None = None
    ) -> None:
        dt = time.perf_counter() - t0
        err = fut.exception()
        if isinstance(err, ShedError):
            # a shed delivered THROUGH the Future (the pipelined
            # binary transport's status-1 frame) is still a shed, not
            # an error — booked like a door-shed so both transports'
            # serve_bench rows compare like for like.  Not counted as
            # completed: `outstanding` subtracts sheds separately.
            self.note_shed(err.cause, qos=err.qos)
            return
        with self._lock:
            self.completed += 1
            if err is not None:
                self.errors += 1
            else:
                self._lat.observe(dt)
                if trace_id is not None:
                    self._slow.append((dt, trace_id))
                    self._slow.sort(reverse=True)
                    del self._slow[self._slow_k:]

    def slowest(self) -> list[tuple[float, str]]:
        with self._lock:
            return list(self._slow)

    def outstanding(self) -> int:
        """Offered requests still awaiting resolution.  Sheds resolved
        AT the door (no Future ever existed), so they must not count —
        the drain barrier would otherwise spin its full timeout on
        every run with a single shed."""
        with self._lock:
            return self.submitted - self.completed - self._shed_total

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "errors": self.errors,
                "shed": dict(self.shed),
                "qos_offered": dict(self.qos_offered),
                "qos_shed": dict(self.qos_shed),
                "e2e_p50": round(self._lat.percentile(50), 6),
                "e2e_p99": round(self._lat.percentile(99), 6),
            }


def run_loadgen(
    target,
    *,
    offered_qps: float,
    duration_s: float,
    concurrency: int = 8,
    nnz: int = 8,
    zipf_a: float = 1.3,
    table_size: int | None = None,
    seed: int = 0,
    drain_timeout_s: float = 30.0,
    metrics_logger=None,
    trace: bool | None = None,
    trace_sample: float = 0.01,
    qos_mix: dict[str, float] | None = None,
) -> dict:
    """Drive ``target`` (a ReplicaFleet or HttpTarget) with open-loop
    zipf traffic; returns (and optionally logs as ``serve_bench``) the
    SLO summary.  When the target is a fleet, its stats window is
    flushed into the summary (queue/featurize/device + per-bucket
    percentiles + shed rows).

    Tracing (obs/reqtrace.py): ``trace=None`` auto-enables when the
    target fleet has a ``reqtrace`` sink attached; ``trace=True``
    forces client-side minting (e.g. an HttpTarget against a traced
    tier — ids ride the XFS2 packed wire at ``trace_sample``).  With
    tracing on, every request carries a trace id and the summary's
    ``slowest_exemplars`` names the client-observed slowest-3 with
    their server-side phase breakdowns when available."""
    if offered_qps <= 0 or duration_s <= 0 or concurrency < 1:
        raise ValueError("offered_qps/duration_s/concurrency must be > 0")
    if zipf_a <= 1.0:
        raise ValueError("zipf_a must be > 1 (numpy zipf domain)")
    # mixed-class traffic: arrival i's class comes from a 100-slot
    # proportional pattern (deterministic — the same seed offers the
    # same class sequence over both transports of a two-leg run)
    qos_pattern: list[str] | None = None
    if qos_mix:
        from xflow_tpu.serve.fleet import QOS_CLASSES

        bad = set(qos_mix) - set(QOS_CLASSES)
        if bad:
            raise ValueError(
                f"unknown QoS class(es) {sorted(bad)} in qos_mix "
                f"(want {QOS_CLASSES})"
            )
        total = sum(qos_mix.values())
        if total <= 0:
            raise ValueError("qos_mix fractions must sum > 0")
        # error-accumulator (Bresenham) spread: classes INTERLEAVE at
        # their fractions instead of arriving in per-class bursts —
        # the same striping discipline the fleet's canary router uses
        mix = {
            c: qos_mix[c] / total for c in QOS_CLASSES if c in qos_mix
        }
        acc = dict.fromkeys(mix, 0.0)
        qos_pattern = []
        for _ in range(100):
            for c in mix:
                acc[c] += mix[c]
            top = max(acc, key=lambda c: acc[c])
            acc[top] -= 1.0
            qos_pattern.append(top)
    sink = getattr(target, "reqtrace", None)
    if trace is None:
        trace = sink is not None
    mint = None
    if trace:
        if sink is None:
            # client-side minting against a remote tier: a local sink
            # used only for id/sampling-decision generation
            from xflow_tpu.obs.reqtrace import ReqTraceSink

            sink_local = ReqTraceSink(sample=trace_sample)
            mint = sink_local.mint
        else:
            mint = sink.mint
    if table_size is None:
        cfg = getattr(target, "cfg", None)
        if cfg is None:
            # HttpTarget has no engine config to read the key space
            # from — a remote tier's table size isn't knowable here
            raise ValueError(
                "table_size is required for targets without a .cfg "
                "(e.g. HttpTarget): pass table_size=2**cfg_log2 "
                "matching the serving artifact"
            )
        table_size = int(cfg.table_size)
    count = max(1, int(offered_qps * duration_s))
    rec = _Recorder()
    # the open-loop clock starts AFTER every stripe has pre-generated
    # its rows (barrier action runs in the last arriving thread): a
    # start stamped before generation would put large runs behind
    # schedule from arrival 0 and turn the ramp into a burst that
    # inflates the very numbers check_serve_slo gates on
    start_cell = [0.0]

    def _stamp_start() -> None:
        start_cell[0] = time.perf_counter() + 0.05

    gen_barrier = threading.Barrier(concurrency + 1, action=_stamp_start)

    def worker(wid: int) -> None:
        # pre-generate this worker's rows so the hot loop is
        # sleep → submit, not RNG time
        idxs = range(wid, count, concurrency)
        rows = None
        try:
            rng = np.random.default_rng(seed + wid)
            rows = zipf_rows(
                rng, len(idxs),
                table_size=table_size, nnz=nnz, zipf_a=zipf_a,
            )
        except Exception:  # xf: ignore[XF015]
            # NOT a silent swallow: rows stays None and every arrival
            # of this stripe is booked as a failed request after the
            # barrier (the loud path lives below)
            pass
        try:
            gen_barrier.wait(timeout=60.0)
        except threading.BrokenBarrierError:
            rows = None  # no shared clock; this stripe cannot run
        if rows is None:
            # a stripe that cannot build its rows must not vanish: book
            # every one of its arrivals as a failed request, or the
            # summary reports a clean gate-passing run over traffic
            # that was never sent
            for _ in idxs:
                rec.note_submit()
                rec.note_error()
            return
        start = start_cell[0]
        for j, i in enumerate(idxs):
            delay = (start + i / offered_qps) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            q = (
                qos_pattern[i % len(qos_pattern)]
                if qos_pattern is not None
                else None
            )
            rec.note_submit(qos=q)
            ctx = mint() if mint is not None else None
            tid = f"{ctx.trace_id:016x}" if ctx is not None else None
            kw: dict[str, Any] = {}
            if ctx is not None:
                kw["trace"] = ctx
            if q is not None:
                kw["qos"] = q
            t0 = time.perf_counter()
            try:
                fut = target.submit(*rows[j], **kw)
            except ShedError as e:
                rec.note_shed(e.cause, qos=getattr(e, "qos", None) or q)
                continue
            except Exception:
                # a submit-side failure is ONE failed request, not a
                # dead worker: the stripe must keep offering its
                # 1/concurrency share or the summary reports a clean
                # run over traffic that was never sent
                rec.note_error()
                continue
            fut.add_done_callback(
                lambda f, t0=t0, tid=tid: rec.note_done(f, t0, tid)
            )

    threads = [
        # daemon: the bounded join below already tolerates (and
        # reports) leaked workers — a non-daemon stripe wedged in a
        # socket timeout would hold interpreter shutdown hostage for
        # its whole remaining arrival schedule
        threading.Thread(
            target=worker, args=(w,), name=f"xflow-loadgen-{w}",
            daemon=True,
        )
        for w in range(concurrency)
    ]
    for t in threads:
        t.start()
    try:
        gen_barrier.wait(timeout=60.0)
    except threading.BrokenBarrierError:
        # a stripe died before generating (hard failure): workers see
        # the same break and book their arrivals as errors; fall back
        # to "now" so the deadlines below still bound the run
        start_cell[0] = time.perf_counter()
    start = start_cell[0]
    join_deadline = (
        start + duration_s + drain_timeout_s
    )
    for t in threads:
        t.join(timeout=max(0.1, join_deadline - time.perf_counter()))
    leaked = sum(t.is_alive() for t in threads)
    # open-loop drain: submissions stopped; wait (bounded) for the
    # tier to resolve what it admitted
    while rec.outstanding() > 0 and time.perf_counter() < join_deadline:
        time.sleep(0.01)
    seconds = time.perf_counter() - start
    snap = rec.snapshot()
    sheds = sum(snap["shed"].values())
    denom = snap["submitted"]
    summary: dict[str, Any] = {
        # serve_bench required fields
        "requests": snap["completed"] - snap["errors"],
        "concurrency": concurrency,
        "seconds": round(seconds, 6),
        "requests_per_sec": round(
            (snap["completed"] - snap["errors"]) / max(seconds, 1e-9), 1
        ),
        "e2e_p50": snap["e2e_p50"],
        "e2e_p99": snap["e2e_p99"],
        # SLO extras (schema-optional)
        "offered_qps": round(offered_qps, 1),
        "offered_qps_actual": round(denom / max(seconds, 1e-9), 1),
        "achieved_qps": round(
            (snap["completed"] - snap["errors"]) / max(seconds, 1e-9), 1
        ),
        "shed_frac": round(sheds / denom, 6) if denom else 0.0,
        "shed_by_cause": snap["shed"],
        "errors": snap["errors"] + leaked,
        "outstanding": rec.outstanding(),
        # 429s the target transparently retried (HttpTarget honoring
        # Retry-After; in-process fleets never retry — 0)
        "retried": int(getattr(target, "retried", 0)),
        # which wire carried the traffic ("fleet" = in-process): the
        # two-leg SLO gate (check_serve_slo.py --compare-transports)
        # picks its legs by this field
        "transport": getattr(target, "transport", "fleet"),
    }
    if qos_pattern is not None:
        summary["qos_offered"] = snap["qos_offered"]
        summary["qos_shed"] = snap["qos_shed"]
    if hasattr(target, "emit_stats"):
        rows = target.emit_stats()  # serve_stats + serve_shed flushed
        stats = rows["stats"]
        for f in (
            "queue_p50", "queue_p99", "featurize_p50", "featurize_p99",
            "device_p50", "device_p99",
        ):
            summary[f] = stats[f]
        summary["per_bucket"] = stats.get("per_bucket", {})
        summary["compiles"] = target.engines[0].compile_count
        if trace and sink is not None:
            # emit_stats just flushed the sink's window, so server-side
            # phase breakdowns for the client's slowest trace ids are
            # available via the last-flush exemplar view
            exemplars = []
            for dt, tid in rec.slowest():
                e: dict[str, Any] = {
                    "trace_id": tid, "e2e_ms": round(dt * 1e3, 3),
                }
                ph = sink.phases_of(tid)
                if ph is not None:
                    e["phases_ms"] = ph
                exemplars.append(e)
            summary["slowest_exemplars"] = exemplars
    else:
        for f in (
            "queue_p50", "queue_p99", "featurize_p50", "featurize_p99",
            "device_p50", "device_p99",
        ):
            summary[f] = 0.0
        summary["compiles"] = 0
        if trace:
            # remote tier: client e2e only — the server's phase
            # breakdowns live in ITS reqtrace stream under these ids
            summary["slowest_exemplars"] = [
                {"trace_id": tid, "e2e_ms": round(dt * 1e3, 3)}
                for dt, tid in rec.slowest()
            ]
    if metrics_logger is not None:
        metrics_logger.log("serve_bench", summary)
    return summary
