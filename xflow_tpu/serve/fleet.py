"""Replica fleet — N PredictEngine replicas behind one admission-
controlled router, with digest-guarded staged rollout.

The in-process serving stack (engine + MicroBatcher) serves one
replica.  Production traffic wants three more disciplines, modeled on
the replica/rollout/SLO structure of Google's ads scoring
infrastructure (PAPERS.md, arXiv:2501.10546) and the model-freshness
hot-swap hooks the online-advertising framework paper treats as table
stakes (arXiv:2201.05500):

* **Replication.**  ``ReplicaFleet.load`` loads ONE artifact and fans
  it out to N replicas via ``PredictEngine.clone()`` — shared weights
  and shared AOT executables (one compile set fleet-wide), but a
  private MicroBatcher + TrainStep per replica so each replica's
  worker thread owns its host staging.  Requests route round-robin;
  every replica batcher pools ONE registry, so ``serve_stats`` rows
  are fleet-wide windows.

* **Admission control / load shedding.**  Before a request enqueues,
  the chosen replica's backlog is checked against the micro-batch
  deadline budget: queue DEPTH over ``depth_budget`` or queue AGE over
  ``deadline_budget_ms`` sheds the request with a typed
  :class:`ShedError` (cause ``queue_depth`` / ``queue_age``), counted
  per cause and reported in ``serve_shed`` JSONL rows.  Shedding at
  the door keeps the p99 of ADMITTED requests inside the deadline
  budget instead of letting the queue eat the SLO for everyone.

* **Staged rollout.**  ``begin_rollout(artifact)`` loads the candidate
  (digest-guarded: a different config digest is a redeploy, refused
  unless ``force``), swaps it into ONE canary replica, and routes
  ``canary_frac`` of traffic there.  The canary's completions/errors/
  latency accumulate under the fleet lock; ``commit_rollout`` refuses
  until the health gate passes (``min_canary_requests`` served,
  error fraction ≤ ``max_error_frac``) and then swaps every remaining
  replica atomically (each batcher swap is atomic per coalesced batch,
  so no batch ever mixes two artifacts).  ``abort_rollout`` swaps the
  canary back.  Every transition logs a ``rollout`` JSONL row;
  ``obs doctor`` flags a rollout that begins and never resolves
  (canary-stuck).

* **Replica health / self-healing** (docs/ROBUSTNESS.md).  A replica
  whose scoring keeps raising (``evict_after_errors`` consecutive
  errors — the ``serve.replica_score`` failpoint drives this in the
  chaos gate) is EVICTED from routing with a ``replica_evicted``
  health row; the shrunken fleet's backlog sheds at the door via
  ``AdmissionPolicy`` (typed 429s, never a silent SLO bleed), and a
  background revive thread re-clones the replica from the shared
  artifact state and swaps it back (``replica_revived``).  With every
  replica evicted, submits shed with cause ``replica_unavailable``.

Thread model (XF006–XF009 clean by construction): the fleet owns no
long-lived threads — replica MicroBatcher workers and the HTTP handler
threads (serve/server.py) drive it; the short-lived revive threads are
tracked in ``_revive_threads`` and joined (bounded) by ``close()``.  All mutable fleet state (router counter,
rollout state, shed/error counters) lives under ``self._lock``; the
lock is never held across a blocking call, a batcher submit, or an
engine swap's digest check... with one deliberate exception: commit/
abort swap replicas under the fleet lock so a concurrent ``submit``
can never route to a half-swapped fleet (lock order fleet._lock →
MicroBatcher._swap_lock, acyclic — batcher code never takes the fleet
lock).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Sequence

from xflow_tpu.obs.registry import Histogram, MetricsRegistry
from xflow_tpu.obs.schema import health_row
from xflow_tpu.serve.batcher import MicroBatcher, stats_row_from_snapshot

# QoS admission classes, best-protected first.  All classes share one
# queue; lower classes see SCALED admission budgets (ReplicaFleet
# qos_normal_frac / qos_best_effort_frac), so as pressure mounts
# best_effort crosses its (smallest) budget and sheds first, normal
# next, and bidding — the auction-critical path — last, at the full
# budget.  The wire carries the class as the XFB1 frame's QoS byte
# (serve/binary.py) or the X-XFlow-QoS header (serve/server.py).
QOS_CLASSES = ("bidding", "normal", "best_effort")


class ShedError(RuntimeError):
    """Typed backpressure: the request was REJECTED by admission
    control, not failed — the caller should retry after backoff (the
    HTTP front end maps this to 429 with the cause in the body)."""

    def __init__(self, cause: str, depth: int, queue_age_s: float,
                 budget: str, qos: str | None = None):
        super().__init__(
            f"request shed: {cause} (depth {depth}, oldest queued "
            f"{queue_age_s * 1e3:.1f}ms, budget {budget}"
            + (f", class {qos}" if qos else "")
            + ")"
        )
        self.cause = cause
        self.depth = depth
        self.queue_age_s = queue_age_s
        self.qos = qos


class RolloutError(RuntimeError):
    """A rollout transition was refused (no rollout open, one already
    open, or the canary health gate has not passed)."""


class AdmissionPolicy:
    """Shed decision for ONE replica backlog against the micro-batch
    deadline budget.  ``deadline_budget_ms`` bounds the oldest queued
    request's age (a newcomer queues behind it, so its age floors the
    newcomer's wait); ``depth_budget`` bounds raw backlog depth."""

    def __init__(self, deadline_budget_ms: float = 50.0,
                 depth_budget: int = 256):
        if deadline_budget_ms <= 0 or depth_budget < 1:
            raise ValueError(
                "deadline_budget_ms must be > 0 and depth_budget >= 1"
            )
        self.deadline_budget_s = deadline_budget_ms / 1000.0
        self.depth_budget = depth_budget

    def check(self, batcher: MicroBatcher) -> str | None:
        """Shed cause for admitting one more request to ``batcher``
        right now, or None to admit."""
        if batcher.depth() >= self.depth_budget:
            return "queue_depth"
        if batcher.queue_age_s() > self.deadline_budget_s:
            return "queue_age"
        return None

    def describe(self) -> str:
        return (
            f"age<={self.deadline_budget_s * 1e3:.0f}ms,"
            f"depth<{self.depth_budget}"
        )

    def scaled(self, frac: float) -> "AdmissionPolicy":
        """A strictly-tighter copy for a lower QoS class: both budgets
        scaled by ``frac`` (depth floored at 1 so the class can still
        admit on an idle fleet)."""
        if not 0.0 < frac <= 1.0:
            raise ValueError("QoS budget fraction must be in (0, 1]")
        return AdmissionPolicy(
            deadline_budget_ms=self.deadline_budget_s * 1000.0 * frac,
            depth_budget=max(1, int(self.depth_budget * frac)),
        )


class ReplicaFleet:
    def __init__(
        self,
        engine,
        replicas: int = 2,
        *,
        max_wait_ms: float = 2.0,
        max_batch: int | None = None,
        deadline_budget_ms: float = 50.0,
        depth_budget: int = 256,
        metrics_logger=None,
        flight=None,
        registry: MetricsRegistry | None = None,
        evict_after_errors: int = 3,
        revive: bool = True,
        topk: bool = False,
        reqtrace=None,
        qos_normal_frac: float = 0.75,
        qos_best_effort_frac: float = 0.45,
        default_qos: str = "normal",
        cache=None,
    ):
        if replicas < 1:
            raise ValueError("a fleet needs at least 1 replica")
        if evict_after_errors < 1:
            raise ValueError("evict_after_errors must be >= 1")
        if default_qos not in QOS_CLASSES:
            raise ValueError(
                f"default_qos {default_qos!r} not in {QOS_CLASSES}"
            )
        if not (0.0 < qos_best_effort_frac <= qos_normal_frac <= 1.0):
            raise ValueError(
                "need 0 < qos_best_effort_frac <= qos_normal_frac <= 1 "
                "(best_effort sheds first, bidding last)"
            )
        self.policy = AdmissionPolicy(deadline_budget_ms, depth_budget)
        # per-class admission: bidding at the FULL budget, lower
        # classes strictly tighter — the ordering invariant `obs
        # doctor` checks as qos_inversion
        self.policies = {
            "bidding": self.policy,
            "normal": self.policy.scaled(qos_normal_frac),
            "best_effort": self.policy.scaled(qos_best_effort_frac),
        }
        self.default_qos = default_qos
        # topk fleets never cache: entries are scalar pctrs, not
        # (ids, scores) pairs
        if topk:
            cache = None
        self.registry = registry if registry is not None else MetricsRegistry()
        self.metrics_logger = metrics_logger
        self.flight = flight
        # request-scoped tracing (obs/reqtrace.py, ISSUE 16): when a
        # ReqTraceSink is attached, every submit opens a RequestSpan
        # (minting a root TraceContext when the caller carried none)
        # and emit_stats flushes the head+tail-sampled window.  None =
        # tracing off, zero per-request overhead.  A cascade's two
        # fleets share ONE sink and override reqtrace_stage so
        # retrieval/ranking spans of a trace land in the same window.
        self.reqtrace = reqtrace
        self.reqtrace_stage = "topk" if topk else "score"
        # top-k fleet (the cascade's retrieval stage): every replica
        # batcher runs the engine's topk leg; submit() Futures resolve
        # to (item_ids, scores).  Mode is fleet-wide — one fleet, one
        # endpoint semantics.
        self.topk = topk
        self.engines = [engine] + [
            engine.clone() for _ in range(replicas - 1)
        ]
        self.batchers = [
            MicroBatcher(
                e,
                max_wait_ms=max_wait_ms,
                max_batch=max_batch,
                registry=self.registry,
                metrics_logger=None,  # the fleet owns the stats rows
                flight=flight,
                emit_on_close=False,
                topk=topk,
                cache=cache,
            )
            for e in self.engines
        ]
        self._lock = threading.Lock()
        self._seq = 0  # request sequence (idle round-robin)
        # non-canary round-robin under an open rollout — its OWN
        # counter: _seq stays phase-locked with the canary stripe (at
        # canary_frac=0.5 every non-canary _seq is odd), so indexing
        # others[] by _seq would starve some replicas entirely
        self._rr = 0
        self._admitted = 0
        self._completed = 0
        self._errors = 0
        self._shed: dict[str, int] = {}
        # per-QoS-class window counters (serve_shed by_class)
        self._class_admitted = {c: 0 for c in QOS_CLASSES}
        self._class_shed = {c: 0 for c in QOS_CLASSES}
        # replica health (docs/ROBUSTNESS.md): a replica whose scoring
        # keeps raising is EVICTED from routing (capacity shrinks, so
        # AdmissionPolicy sheds the overflow at the door) and a
        # background revive thread re-clones it from the shared
        # artifact state.  All of it under self._lock; revive threads
        # are tracked and joined (bounded) by close() — XF006.
        self.evict_after_errors = evict_after_errors
        self._revive_enabled = revive
        self._err_streak = [0] * replicas
        self._unhealthy: set[int] = set()
        self._revive_threads: list[threading.Thread] = []
        self._evictions = 0
        self._revivals = 0
        self._rollout: dict[str, Any] | None = None
        # serializes rollout-row emission (terminal rows vs the stats
        # window's canary heartbeat) WITHOUT holding the fleet lock
        # across logger I/O — see emit_stats
        self._ro_log_lock = threading.Lock()
        self._closed = False
        self._drained = threading.Event()
        self._final_rows: dict = {}
        self._load_kw: dict[str, Any] = {}
        self.digest = engine.digest
        # servable identity (config digest @ step — serve/artifact.py
        # ::servable_digest): advances on every COMMITTED rollout,
        # including delta refreshes where the config digest does not
        # change; the continuous driver and /v1/stats read it to tell
        # which model VERSION traffic converged on
        self.servable = getattr(engine, "servable_digest", "?")
        # hot-key score cache (serve/scache.py) in front of the
        # batchers (they insert; submit() looks up).  The cache pins
        # THIS fleet's servable digest; commit_rollout re-pins it
        # inside the same critical section that swaps `servable`, so
        # lookups and inserts can never disagree about the current
        # version.
        self.cache = cache
        if self.cache is not None:
            self.cache.registry = self.registry
            self.cache.set_current(self.servable)

    # -- construction -------------------------------------------------------

    @classmethod
    def load(
        cls,
        artifact: str,
        replicas: int = 2,
        *,
        num_devices: int = 1,
        buckets: Sequence[int] | None = None,
        obs=None,
        warm: bool = True,
        topk_k: int | None = None,
        cache_capacity: int | None = None,
        **kw,
    ) -> "ReplicaFleet":
        """Load one artifact from the shared store and fan it out to
        ``replicas`` clones (one compile set, shared weights).
        ``topk_k`` sizes the compiled top-k width for retrieval
        artifacts (engine.load attaches their item index either
        way).  ``cache_capacity`` sizes the hot-key score cache
        (serve/scache.py; 0 = off, None = the artifact config's
        ``serve_cache_capacity`` knob); the artifact's QoS budget
        fractions seed the per-class admission policies unless
        overridden in ``kw``."""
        from xflow_tpu.serve.engine import PredictEngine

        engine = PredictEngine.load(
            artifact,
            num_devices=num_devices,
            buckets=buckets,
            obs=obs,
            warm=warm,
            topk_k=topk_k,
        )
        cfg = engine.cfg
        kw.setdefault("qos_normal_frac", cfg.serve_qos_normal_frac)
        kw.setdefault(
            "qos_best_effort_frac", cfg.serve_qos_best_effort_frac
        )
        if "cache" not in kw:
            if cache_capacity is None:
                cache_capacity = cfg.serve_cache_capacity
            if cache_capacity > 0:
                from xflow_tpu.serve.scache import ScoreCache

                kw["cache"] = ScoreCache(cache_capacity)
        fleet = cls(engine, replicas, **kw)
        # rollouts load candidates the same way this fleet was loaded
        fleet._load_kw = {
            "num_devices": num_devices,
            "buckets": buckets,
            "obs": obs,
            "topk_k": topk_k,
        }
        fleet.log_load(artifact)
        return fleet

    def log_load(self, artifact: str) -> None:
        """One ``serve_load`` row for the artifact this fleet serves.
        ``load`` calls it; the CLI calls it AGAIN after attaching a
        metrics logger (the logger's run header needs the loaded
        digest, so it cannot exist before ``load`` returns)."""
        if self.metrics_logger is None:
            return
        with self._lock:  # engines[] mutates under rollout/revive
            e = self.engines[0]
        self.metrics_logger.log("serve_load", {
            "artifact": artifact,
            "config_digest": e.digest,
            "model": e.cfg.model,
            "buckets": list(e.buckets),
            "warm_seconds": round(e.warm_seconds, 6),
            "compiles": e.compile_count,
        })

    @property
    def cfg(self):
        with self._lock:  # engines[] mutates under rollout/revive
            e = self.engines[0]
        return e.cfg

    @property
    def replicas(self) -> int:
        return len(self.batchers)

    # -- request side -------------------------------------------------------

    def _route(self) -> tuple[int, dict | None]:
        """(replica index, rollout token) for the next request — the
        token is the open rollout dict when this request is canary
        traffic, else None (``_done`` compares it by IDENTITY, so a
        straggler from an aborted rollout can never pollute the next
        rollout's health gate).  Under an open rollout,
        ``canary_frac`` of the sequence goes to the canary replica —
        error-accumulator striping (Bresenham), so canary requests
        INTERLEAVE with fleet traffic at any fraction (a modulo split
        would aim a contiguous burst of full offered QPS at the one
        canary replica and shed it into a spurious gate failure) —
        and the rest round-robins the others; idle fleets round-robin
        everything."""
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaFleet is closed")
            self._seq += 1
            healthy = [
                i for i in range(len(self.batchers))
                if i not in self._unhealthy
            ]
            if not healthy:
                # every replica is evicted: shed at the door with its
                # own typed cause — capacity is gone, not queued away
                self._shed["replica_unavailable"] = (
                    self._shed.get("replica_unavailable", 0) + 1
                )
                raise ShedError(
                    "replica_unavailable", 0, 0.0,
                    "all replicas evicted (revive pending)",
                )
            ro = self._rollout
            if ro is not None:
                # an evicted canary falls through to the healthy rest:
                # the rollout gate simply stops accumulating until the
                # revive lands (health rows make the overlap visible)
                if ro["canary"] in healthy:
                    ro["acc"] += ro["canary_frac"]
                    if ro["acc"] >= 1.0:
                        ro["acc"] -= 1.0
                        return ro["canary"], ro
                others = [i for i in healthy if i != ro["canary"]]
                if not others:  # single healthy replica: all canary
                    return ro["canary"], ro
                self._rr += 1
                return others[self._rr % len(others)], None
            return healthy[self._seq % len(healthy)], None

    def submit(self, keys, slots=None, vals=None, trace=None,
               qos: str | None = None) -> Future:
        """Admission-checked enqueue onto one replica; returns the
        pctr Future.  Raises :class:`ShedError` when the replica's
        backlog breaches the deadline budget — the typed backpressure
        signal, never silently queued past the SLO.  ``trace`` is an
        optional ``obs.reqtrace.TraceContext`` carried in from the
        wire; with a sink attached, the span opens HERE (t_arrival)
        so admission wait + routing are inside the tree — sheds
        complete immediately with status "shed" (always kept by the
        sampler).

        ``qos`` picks the admission class (QOS_CLASSES; None = the
        fleet's ``default_qos``) — each class checks ITS policy, so
        under pressure best_effort sheds first and bidding last.

        With a score cache attached, a row already scored by the
        CURRENT servable resolves right here — no routing, no queue,
        no device.  Cache lookups are suspended while a rollout is
        open so the canary stripe sees full traffic (a cache-starved
        health gate would never accumulate its min_requests)."""
        if qos is None:
            qos = self.default_qos
        elif qos not in QOS_CLASSES:
            raise ValueError(
                f"unknown QoS class {qos!r} (want one of {QOS_CLASSES})"
            )
        sink = self.reqtrace
        span = (
            sink.start(trace, self.reqtrace_stage)
            if sink is not None
            else None
        )
        if self.cache is not None:
            with self._lock:
                servable = self.servable
                cacheable = self._rollout is None and not self._closed
            if cacheable:
                score = self.cache.lookup(servable, keys, slots, vals)
                if score is not None:
                    with self._lock:
                        self._admitted += 1
                        self._completed += 1
                        self._class_admitted[qos] += 1
                    if span is not None:
                        sink.complete(span, "ok", detail="cache_hit")
                    fut: Future = Future()
                    fut.set_result(score)
                    return fut
        try:
            idx, ro_token = self._route()
        except ShedError as e:
            with self._lock:
                self._class_shed[qos] += 1
            if span is not None:
                sink.complete(span, "shed", detail=e.cause)
            e.qos = qos
            raise
        if span is not None:
            span.replica = idx
        batcher = self.batchers[idx]
        cause = self.policies[qos].check(batcher)
        if cause is not None:
            batcher.note_shed(cause)
            with self._lock:
                self._shed[cause] = self._shed.get(cause, 0) + 1
                self._class_shed[qos] += 1
            if span is not None:
                sink.complete(span, "shed", detail=cause)
            raise ShedError(
                cause,
                batcher.depth(),
                batcher.queue_age_s(),
                self.policies[qos].describe(),
                qos=qos,
            )
        t0 = time.perf_counter()
        fut = batcher.submit(keys, slots, vals, trace=span)
        with self._lock:
            self._admitted += 1
            self._class_admitted[qos] += 1
        fut.add_done_callback(
            lambda f, t0=t0, ro=ro_token, i=idx: self._done(f, t0, ro, i)
        )
        return fut

    def score(self, keys, slots=None, vals=None,
              timeout: float | None = 60.0) -> float:
        return float(self.submit(keys, slots, vals).result(timeout))

    def _done(self, fut: Future, t0: float,
              ro_token: dict | None, idx: int) -> None:
        """Completion bookkeeping (runs on the resolving replica's
        worker thread — worker context, so everything under the fleet
        lock).  Canary health only counts completions whose routing
        token IS the still-open rollout: a straggler from a resolved
        rollout must not feed the gate of the one that replaced it.
        Scoring errors feed the replica-health streak: at
        ``evict_after_errors`` consecutive errors the replica is
        evicted from routing and a background revive re-clones it."""
        err = fut.exception() is not None
        dt = time.perf_counter() - t0
        evict = False
        with self._lock:
            self._completed += 1
            if err:
                self._errors += 1
                self._err_streak[idx] += 1
                if (
                    self._err_streak[idx] >= self.evict_after_errors
                    and idx not in self._unhealthy
                    and not self._closed
                ):
                    self._unhealthy.add(idx)
                    self._evictions += 1
                    evict = True
            else:
                self._err_streak[idx] = 0
            ro = self._rollout
            if ro_token is not None and ro is ro_token:
                ro["requests"] += 1
                if err:
                    ro["errors"] += 1
                else:
                    # errors have their own gate (max_error_frac); a
                    # fast-failing or timed-out request must not skew
                    # the p99 gate's success-latency population
                    ro["latency"].observe(dt)
        if evict:
            self._evict(idx)

    # -- replica health (eviction / revive) ---------------------------------

    def _evict(self, idx: int) -> None:
        """One replica just crossed the error streak: it is already out
        of routing (``_unhealthy``, set by _done under the lock);
        here — outside the lock — comes the loud part (health row,
        counter) and the background revive.  Shrunk capacity is real:
        the survivors' queues grow and AdmissionPolicy sheds the
        overflow at the door, which is the design (never queue past
        the deadline budget on a sick fleet)."""
        self.registry.counter_add("serve.replica_evicted")
        if self.metrics_logger is not None:
            self.metrics_logger.log("health", health_row(
                cause="replica_evicted",
                channel="serve",
                silence_seconds=0.0,
                threshold_seconds=0.0,
                detail=f"replica {idx}: {self.evict_after_errors} "
                "consecutive scoring error(s) — evicted from routing",
            ))
        if not self._revive_enabled:
            return
        # not fire-and-forget: tracked in _revive_threads and joined
        # (bounded) by close()
        t = threading.Thread(  # xf: ignore[XF006]
            target=self._revive,
            args=(idx,),
            name=f"xflow-replica-revive-{idx}",
            daemon=True,
        )
        with self._lock:
            # prune finished revives so a flapping replica can't grow
            # the list for the process lifetime
            self._revive_threads = [
                rt for rt in self._revive_threads if rt.is_alive()
            ]
            self._revive_threads.append(t)
        t.start()

    def _revive(self, idx: int) -> None:
        """Background revive: re-clone the replica from the shared
        artifact state (PredictEngine.clone — shared weights + AOT
        executables, fresh host-side staging) and swap it back into
        routing.  A failed revive leaves the replica evicted (capacity
        stays shed) with its own health row — never a silent retry
        loop."""
        try:
            for _ in range(8):  # bounded: rollouts can't starve this
                with self._lock:
                    src = self.engines[idx]
                clone = src.clone()  # outside the lock: not free
                # re-verify under the lock before installing (the
                # commit_rollout discipline): a rollout that committed
                # while we cloned has already swapped engines[idx] —
                # force-installing our pre-commit clone would silently
                # revert this one replica to the old artifact, the
                # exact mixed-fleet state rollouts exist to prevent.
                # Lock order fleet._lock -> batcher._swap_lock matches
                # commit/abort.
                with self._lock:
                    if self.engines[idx] is not src:
                        continue  # re-clone from the new incumbent
                    self.batchers[idx].swap(clone, force=True)
                    self.engines[idx] = clone
                    self._unhealthy.discard(idx)
                    self._err_streak[idx] = 0
                    self._revivals += 1
                    break
            else:
                raise RuntimeError(
                    "engine kept changing under the revive (8 "
                    "rollout swaps mid-clone)"
                )
            self.registry.counter_add("serve.replica_revived")
            if self.metrics_logger is not None:
                self.metrics_logger.log("health", health_row(
                    cause="replica_revived",
                    channel="serve",
                    silence_seconds=0.0,
                    threshold_seconds=0.0,
                    detail=f"replica {idx}: re-cloned from the shared "
                    "artifact and returned to routing",
                ))
        except Exception as e:
            if self.metrics_logger is not None:
                self.metrics_logger.log("health", health_row(
                    cause="replica_revive_failed",
                    channel="serve",
                    silence_seconds=0.0,
                    threshold_seconds=0.0,
                    detail=f"replica {idx}: {type(e).__name__}: {e} — "
                    "left evicted, fleet serving at reduced capacity",
                ))

    def health(self) -> dict:
        """Live replica-health snapshot (the /v1/stats and chaos-gate
        surface)."""
        with self._lock:
            return {
                "unhealthy": sorted(self._unhealthy),
                "evictions": self._evictions,
                "revivals": self._revivals,
            }

    def pending(self) -> bool:
        """Any replica has queued or in-flight work — the watchdog's
        serve-channel pending probe for the whole fleet."""
        return any(b.pending() for b in self.batchers)

    def depth(self) -> int:
        return sum(b.depth() for b in self.batchers)

    def queue_age_s(self) -> float:
        return max(b.queue_age_s() for b in self.batchers)

    # -- staged rollout -----------------------------------------------------

    def _load_candidate(self, artifact):
        if not isinstance(artifact, str):
            return artifact  # pre-built engine (tests, live handoff)
        from xflow_tpu.serve.engine import PredictEngine

        # candidates must match the incumbent's serving geometry; a
        # directly-constructed fleet (no load()) derives it from the
        # engine it was built around instead of silently loading the
        # defaults (1-device mesh, default buckets → recompiles and
        # latency shifts with no error)
        with self._lock:  # engines[] mutates under rollout/revive
            inc = self.engines[0]
        kw = self._load_kw or {
            "num_devices": int(inc.mesh.devices.size),
            "buckets": list(inc.buckets),
            "obs": inc.obs,
        }
        return PredictEngine.load(artifact, warm=True, **kw)

    def _log_rollout(self, event: str, ro: dict, detail: str) -> None:
        if self.metrics_logger is not None:
            self.metrics_logger.log("rollout", {
                "event": event,
                "from_digest": ro["from_digest"],
                "to_digest": ro["to_digest"],
                "canary_frac": ro["canary_frac"],
                "canary_requests": ro["requests"],
                "canary_errors": ro["errors"],
                "detail": detail,
            })

    def begin_rollout(
        self,
        artifact,
        canary_frac: float = 0.1,
        *,
        min_canary_requests: int = 32,
        max_error_frac: float = 0.0,
        max_p99_ms: float | None = None,
        auto_commit: bool = False,
        force: bool = False,
    ) -> dict:
        """Load the candidate artifact (or take a pre-built engine),
        swap it into one canary replica, and start routing
        ``canary_frac`` of traffic there.  Digest-guarded: a candidate
        whose config digest differs from the serving digest is refused
        unless ``force`` (that is a redeploy, not a rollout) — the
        check runs BEFORE any traffic shifts.  Returns the rollout
        state snapshot."""
        if not 0.0 < canary_frac <= 1.0:
            raise ValueError("canary_frac must be in (0, 1]")
        # cheap refusals BEFORE the candidate load: an already-open
        # rollout must not cost a full artifact load + warm compile on
        # the handler thread (the authoritative re-check still runs
        # under the lock below)
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaFleet is closed")
            if self._rollout is not None:
                raise RolloutError(
                    "a rollout is already open (commit or abort it "
                    "first)"
                )
        candidate = self._load_candidate(artifact)
        if self.topk and getattr(candidate, "topk_k", 0) < 1:
            raise ValueError(
                "rollout refused: this is a top-k fleet but the "
                "candidate artifact has no item index — run "
                "serve.artifact.export_item_index on it first (a "
                "candidate that cannot answer top-k would evict every "
                "replica it reaches)"
            )
        if not force and candidate.digest != self.digest:
            raise ValueError(
                f"rollout refused: candidate digest {candidate.digest} "
                f"!= serving digest {self.digest} (different config/"
                "geometry is a redeploy — pass force=True only if you "
                "mean it)"
            )
        # _ro_log_lock held across rollout creation AND the begin row:
        # the rollout becomes routable the moment the fleet lock drops,
        # and a fast auto-commit (accept-loop tick) takes _ro_log_lock
        # for its terminal row — holding it here guarantees "begin" is
        # the stream's first row for this rollout.  Order matches
        # emit_stats: _ro_log_lock -> _lock.
        with self._ro_log_lock:
            ro = self._begin_rollout_locked(
                candidate, canary_frac, min_canary_requests,
                max_error_frac, max_p99_ms, auto_commit, force,
            )
            self._log_rollout(
                "begin", ro,
                f"canary replica {ro['canary']}; servable "
                f"{getattr(ro['old'], 'servable_digest', '?')} -> "
                f"{getattr(candidate, 'servable_digest', '?')}",
            )
        return self.rollout_state()

    def _begin_rollout_locked(
        self, candidate, canary_frac, min_canary_requests,
        max_error_frac, max_p99_ms, auto_commit, force,
    ) -> dict:
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaFleet is closed")
            if self._rollout is not None:
                raise RolloutError(
                    "a rollout is already open (commit or abort it "
                    "first)"
                )
            canary = 0
            old = self.batchers[canary].engine
            self.batchers[canary].swap(candidate, force=force)
            # keep engines[] mirroring what each batcher serves: stats
            # reads compile_count through it, and a canary recompile
            # storm must be visible DURING the canary phase
            self.engines[canary] = candidate
            self._rollout = {
                "canary": canary,
                "candidate": candidate,
                "old": old,
                "from_digest": old.digest,
                "to_digest": candidate.digest,
                "canary_frac": float(canary_frac),
                "min_requests": int(min_canary_requests),
                "max_error_frac": float(max_error_frac),
                "max_p99_ms": max_p99_ms,
                "auto_commit": bool(auto_commit),
                # a forced begin (redeploy) implies forced swaps at
                # commit: the remaining replicas still run the OLD
                # digest, so the commit-side swap needs force too
                "force": bool(force),
                "acc": 0.0,  # canary striping accumulator (_route)
                "requests": 0,
                "errors": 0,
                "latency": Histogram(capacity=4096),
                "t0": time.perf_counter(),
            }
            return self._rollout

    def rollout_state(self) -> dict | None:
        """JSON-ready snapshot of the open rollout (None when idle):
        counters, health verdict, and the gate it is waiting on."""
        with self._lock:
            ro = self._rollout
            if ro is None:
                return None
            return dict(self._health_locked(ro), **{
                "from_digest": ro["from_digest"],
                "to_digest": ro["to_digest"],
                "canary_frac": ro["canary_frac"],
                "canary_replica": ro["canary"],
                "auto_commit": ro["auto_commit"],
                "age_seconds": round(
                    time.perf_counter() - ro["t0"], 3
                ),
            })

    def _health_locked(self, ro: dict) -> dict:
        """Canary health under the already-held fleet lock."""
        n, e = ro["requests"], ro["errors"]
        error_frac = e / n if n else 0.0
        p99_s = ro["latency"].percentile(99)
        healthy = n >= ro["min_requests"] and error_frac <= ro[
            "max_error_frac"
        ]
        if healthy and ro["max_p99_ms"] is not None:
            healthy = p99_s * 1000.0 <= ro["max_p99_ms"]
        return {
            "canary_requests": n,
            "canary_errors": e,
            "error_frac": round(error_frac, 6),
            "canary_p99_ms": round(p99_s * 1000.0, 3),
            "healthy": healthy,
            "gate": (
                f"requests>={ro['min_requests']},"
                f"error_frac<={ro['max_error_frac']}"
                + (
                    f",p99<={ro['max_p99_ms']}ms"
                    if ro["max_p99_ms"] is not None
                    else ""
                )
            ),
        }

    def commit_rollout(self, force: bool = False) -> dict:
        """Atomic fleet-wide swap to the candidate — refused until the
        canary health gate passes (``force`` overrides).  Every
        remaining replica gets its own clone of the candidate (shared
        weights + executables); each batcher swap is per-batch atomic,
        so in-flight batches finish on the old engine and no batch
        ever mixes artifacts."""
        with self._lock:
            ro = self._rollout
            if ro is None:
                raise RolloutError("no rollout open")
            health = self._health_locked(ro)
            if not force and not health["healthy"]:
                raise RolloutError(
                    f"commit refused: canary not healthy ({health}) — "
                    "wait for the gate or abort_rollout()"
                )
            candidate = ro["candidate"]
        # clone outside the lock (TrainStep construction is not free;
        # submits must not stall behind it), then re-take it and verify
        # the rollout is still THIS one before the atomic swap
        clones = [
            candidate.clone()
            for i in range(len(self.batchers))
            if i != ro["canary"]
        ]
        with self._lock:
            if self._rollout is not ro:
                raise RolloutError(
                    "rollout changed during commit (concurrent "
                    "commit/abort won)"
                )
            health = self._health_locked(ro)
            it = iter(clones)
            for i, b in enumerate(self.batchers):
                if i == ro["canary"]:
                    self.engines[i] = candidate
                    continue
                b.swap(next(it), force=force or ro["force"])
                self.engines[i] = b.engine
            self.digest = candidate.digest
            self.servable = getattr(candidate, "servable_digest", "?")
            if self.cache is not None:
                # re-pin + evict the old generation ATOMICALLY with
                # the servable swap (scache.py's whole contract): no
                # window where a lookup under the new digest could see
                # a pre-swap score, and old-engine stragglers that
                # resolve after this point insert under a digest the
                # cache no longer accepts.  Lock order fleet._lock →
                # ScoreCache._lock, acyclic (cache code never takes
                # the fleet lock — XF007).
                self.cache.set_current(self.servable)
            self._rollout = None
        with self._ro_log_lock:
            self._log_rollout("commit", ro, f"health {health}")
        return health

    def abort_rollout(self, detail: str = "") -> dict:
        """Swap the canary back to the old engine and close the
        rollout; traffic re-converges on the incumbent artifact."""
        with self._lock:
            ro = self._rollout
            if ro is None:
                raise RolloutError("no rollout open")
            health = self._health_locked(ro)
            self.batchers[ro["canary"]].swap(ro["old"], force=True)
            self.engines[ro["canary"]] = ro["old"]
            if self.cache is not None:
                # servable unchanged on abort — same-digest re-pin is
                # a no-op, but it defends the invariant explicitly
                self.cache.set_current(self.servable)
            self._rollout = None
        with self._ro_log_lock:
            self._log_rollout("abort", ro, detail or f"health {health}")
        return health

    def rollout_delta(self, delta_dir: str, **gate_kw) -> dict:
        """Begin a staged rollout of an incremental delta export
        (stream/delta.py, docs/CONTINUOUS.md): the candidate is built
        by applying the delta onto the incumbent servable —
        ``PredictEngine.apply_delta`` verifies the digest chain and
        shares the AOT executables, so the refresh costs zero
        recompiles — and then rides the SAME canary health gate as a
        full-artifact rollout (``gate_kw`` = begin_rollout's knobs).
        The chain check runs before any traffic shifts."""
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaFleet is closed")
            if self._rollout is not None:
                raise RolloutError(
                    "a rollout is already open (commit or abort it "
                    "first)"
                )
            inc = self.engines[0]
        candidate = inc.apply_delta(delta_dir)
        return self.begin_rollout(candidate, **gate_kw)

    def rollout_tick(self) -> str | None:
        """Advance an auto rollout: commit once the health gate passes,
        abort once the error gate is provably failed (enough canary
        traffic, too many errors).  Called periodically from the HTTP
        server's accept loop (serve/server.py ``service_actions``);
        returns the transition taken, if any."""
        with self._lock:
            ro = self._rollout
            if ro is None or not ro["auto_commit"]:
                return None
            health = self._health_locked(ro)
            doomed = (
                ro["requests"] >= ro["min_requests"]
                and health["error_frac"] > ro["max_error_frac"]
            )
        try:
            if health["healthy"]:
                self.commit_rollout()
                return "commit"
            if doomed:
                self.abort_rollout(detail="auto: error gate failed")
                return "abort"
        except RolloutError:
            # a concurrent manual commit/abort won the race — the
            # rollout resolved either way
            pass
        return None

    # -- stats / lifecycle --------------------------------------------------

    def _shed_row_locked(self) -> dict:
        total = sum(self._shed.values())
        denom = self._admitted + total
        return {
            "admitted": self._admitted,
            "completed": self._completed,
            "shed_total": total,
            "shed_frac": round(total / denom, 6) if denom else 0.0,
            "by_cause": dict(self._shed),
            # per-QoS-class split (additive-OPTIONAL in obs/schema.py:
            # pre-QoS streams without it still validate).  The
            # ordering invariant — bidding sheds only after best_effort
            # does — is what `obs doctor` checks as qos_inversion.
            "by_class": {
                c: {
                    "admitted": self._class_admitted[c],
                    "shed": self._class_shed[c],
                }
                for c in QOS_CLASSES
            },
            "errors": self._errors,
        }

    def emit_stats(self) -> dict:
        """Flush one fleet-wide window: a ``serve_stats`` row (pooled
        registry snapshot, with per-bucket e2e percentiles) and a
        ``serve_shed`` row (admitted/shed per cause + live backlog).
        Window counters reset; returns ``{"stats": ..., "shed": ...}``.
        """
        snap = self.registry.snapshot(reset=True)
        row = stats_row_from_snapshot(snap)
        per_bucket = {}
        pre = "serve.e2e.b"
        for name, h in sorted(snap.hists.items()):
            if name.startswith(pre):
                per_bucket[name[len(pre):]] = {
                    "requests": int(h["count"]),
                    "p50": round(h["p50"], 6),
                    "p99": round(h["p99"], 6),
                }
        row["per_bucket"] = per_bucket
        if self.cache is not None:
            # windowed cache counters ride the serve_stats row
            # (additive-OPTIONAL fields in obs/schema.py)
            row.update(self.cache.stats_row(reset=True))
        with self._lock:
            shed = self._shed_row_locked()
            self._admitted = 0
            self._completed = 0
            self._errors = 0
            self._shed = {}
            self._class_admitted = {c: 0 for c in QOS_CLASSES}
            self._class_shed = {c: 0 for c in QOS_CLASSES}
            ro = self._rollout
        shed["depth"] = self.depth()
        shed["queue_age_s"] = round(self.queue_age_s(), 6)
        if self.metrics_logger is not None:
            self.metrics_logger.log("serve_stats", row)
            self.metrics_logger.log("serve_shed", shed)
        if self.reqtrace is not None:
            # trace windows align with stats windows: the same tick
            # that flushes serve_stats emits the window's sampled
            # reqtrace rows (errors + sheds + slowest-k + head sample)
            self.reqtrace.flush()
        if ro is not None:
            # open-rollout heartbeat row: a stream that ends on one of
            # these (no commit/abort after) is what `obs doctor` flags
            # as canary-stuck.  Ordering discipline WITHOUT logger I/O
            # under the fleet lock: the still-open check runs under
            # the fleet lock, the log itself only under _ro_log_lock.
            # commit/abort clear _rollout (fleet lock) BEFORE taking
            # _ro_log_lock for their terminal row, so either we see
            # the rollout resolved and skip, or we hold _ro_log_lock
            # first and the terminal row lands after our heartbeat —
            # a stale "canary" can never be the stream's last word.
            with self._ro_log_lock:
                with self._lock:
                    still_open = self._rollout is ro
                if still_open:
                    self._log_rollout("canary", ro, "rollout open")
        return {"stats": row, "shed": shed}

    def stats(self) -> dict:
        """Non-destructive live view (the /v1/stats endpoint): pooled
        registry snapshot WITHOUT reset + admission counters + rollout
        state."""
        snap = self.registry.snapshot(reset=False)
        with self._lock:
            shed = self._shed_row_locked()
            engine0 = self.engines[0]
        return {
            "digest": self.digest,
            "servable": self.servable,
            "replicas": self.replicas,
            "stats": stats_row_from_snapshot(snap),
            "shed": shed,
            "depth": self.depth(),
            "queue_age_s": round(self.queue_age_s(), 6),
            "rollout": self.rollout_state(),
            "health": self.health(),
            "compiles": engine0.compile_count,
            "qos": {
                c: self.policies[c].describe() for c in QOS_CLASSES
            },
            "cache": (
                self.cache.stats_row(reset=False)
                if self.cache is not None
                else None
            ),
        }

    def close(self) -> dict:
        """Drain every replica (accepted requests all score), then
        flush the final fleet window.  Idempotent; a rollout still
        open at close stays UNRESOLVED in the stream — shutting down
        mid-canary IS the canary-stuck condition doctor should see."""
        with self._lock:
            first = not self._closed
            self._closed = True
        if first:
            try:
                for b in self.batchers:
                    b.close()
                # revive threads joined (bounded) before the final
                # window: a revive racing shutdown must not swap into
                # a closed fleet unobserved (XF006 — no thread outlives
                # close silently)
                with self._lock:
                    revives = list(self._revive_threads)
                for t in revives:
                    t.join(timeout=10.0)
                    if t.is_alive():
                        import warnings

                        warnings.warn(
                            f"replica revive thread {t.name} outlived "
                            "the close() join",
                            RuntimeWarning,
                            stacklevel=2,
                        )
                        self.registry.counter_add(
                            "serve.revive_thread_leak"
                        )
                final = self.emit_stats()
                with self._lock:
                    self._final_rows = final
            finally:
                # set even on failure so concurrent closers never hang
                self._drained.set()
        else:
            # bounded by construction: the FIRST closer sets _drained in
            # a finally even when drain raises, and its joins are
            # timeout-bounded (xf: ignore[XF017])
            self._drained.wait()
        with self._lock:
            return self._final_rows

    def __enter__(self) -> "ReplicaFleet":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
