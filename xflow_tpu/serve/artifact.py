"""Exportable inference artifacts — the training→serving handoff.

An artifact is a directory holding everything prediction needs and
NOTHING training needs:

* ``<table>.param.r<start>-<stop>.npy`` — frozen weight-table rows in
  the checkpoint row-range shard format (utils/checkpoint.py): each
  process writes only the rows its devices own, and a later load can
  assemble ANY target sharding from whatever ranges exist via mmap —
  an artifact exported on a pod restores onto a 1-chip scoring tier.
  Optimizer aux arrays (FTRL n/z) are deliberately absent: they are
  ~2/3 of a checkpoint's bytes and serve no inference purpose.
* ``dense.<name>.npy`` — replicated dense params (MLP models).
* ``remap.npy`` — the hot-table frequency remap (io/freq.py), present
  iff the model was trained with a hot table.  The remap is part of
  the model: raw hash-space keys are addressed through it, so it ships
  inside the artifact instead of living beside checkpoints.
* ``manifest.json`` — format version, model name, the FULL training
  config JSON plus its digest (config.Config.digest), array metadata,
  and the train-step counter.  PredictEngine refuses artifacts whose
  stored digest doesn't match the embedded config (tampering/drift)
  or a caller-expected config (serving the wrong model).

Multi-host protocol: identical to save_checkpoint — all processes
write into a temp dir, every stage votes through ``all_ok`` (a barrier
that propagates local failures instead of deadlocking), process 0
writes the manifest and atomically renames.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any

import numpy as np

import jax

from xflow_tpu.chaos import failpoint
from xflow_tpu.utils.checkpoint import all_ok, iter_owned_shards

MANIFEST = "manifest.json"
FORMAT = 1
REMAP_FILE = "remap.npy"
# serve-time item-embedding index (retrieval families — models with
# user/item towers, models/two_tower.py): written ALONGSIDE an
# exported artifact by export_item_index, read back by
# PredictEngine.load for the top-k mode.  The meta file is the
# presence marker and is written LAST (tmp + atomic replace per file),
# so a crashed export never leaves a half-index that loads.
ITEM_INDEX_META = "item_index.json"


def servable_digest(config_digest: str, step: int) -> str:
    """Identity of one SERVABLE — a (config, train-step) point in the
    continuous-training chain (docs/CONTINUOUS.md).  A full export at
    step S and base + deltas applied up to step S are the same model
    by the delta round-trip guarantee, so both carry this digest:
    incremental deltas chain on it (``base_digest`` → ``delta_digest``,
    stream/delta.py) and ``PredictEngine``/``ReplicaFleet`` refuse a
    delta whose base is not the servable they currently hold."""
    import hashlib

    return hashlib.sha256(
        f"{config_digest}@{int(step)}".encode()
    ).hexdigest()[:16]


def export_artifact(trainer, directory: str) -> str:
    """Freeze ``trainer``'s model into a serving artifact at
    ``directory`` (replaced atomically if it exists); returns the path.

    Multi-host: COLLECTIVE — all processes call together; each writes
    its own table row ranges (module docstring)."""
    from xflow_tpu.obs import NULL_OBS

    state = trainer.state
    cfg = trainer.cfg
    # book the export's device fetches as an obs phase so a slow export
    # shows up in phase accounting instead of vanishing (XF002)
    obs = getattr(trainer, "obs", None) or NULL_OBS
    # chaos site: a fault anywhere in the export — the all_ok voting +
    # tmp-dir/rename-aside recovery below is what it exercises (XF018)
    failpoint("artifact.export")
    with obs.phase("export_fetch"):
        step = int(jax.device_get(state["step"]))
    proc = jax.process_index()
    parent = os.path.dirname(os.path.abspath(directory))
    tmp = os.path.join(
        parent, f".tmp-artifact-{os.path.basename(directory)}"
    )
    err: BaseException | None = None
    try:
        if proc == 0:
            os.makedirs(parent, exist_ok=True)
            if os.path.exists(tmp):  # leftover from a crashed attempt
                shutil.rmtree(tmp)
            os.makedirs(tmp)
    except BaseException as e:
        err = e
    if not all_ok(err is None):
        if err is not None:
            raise err
        raise RuntimeError("artifact mkdir failed on process 0")
    try:
        arrays_meta: dict[str, Any] = {}
        store = getattr(getattr(trainer, "step", None), "store", None)
        for tname in sorted(state["tables"]):
            key = f"{tname}.param"
            if store is not None:
                # tiered store (Config.store_mode): fold BOTH tiers
                # into the logical [T, D] table, materialized in
                # bounded chunks (store/tiered.py::
                # iter_logical_param_shards) — the artifact is
                # indistinguishable from a dense-mode export, so
                # PredictEngine loads it unchanged
                dim = store.cold.tables[tname].dim
                arrays_meta[key] = {
                    "shape": [cfg.table_size, dim],
                    "dtype": "float32",
                }
                with obs.phase("export_fetch"):
                    for start, stop, block in (
                        store.iter_logical_param_shards(state, tname)
                    ):
                        np.save(
                            os.path.join(
                                tmp,
                                f"{key}.r{start:012d}-{stop:012d}.npy",
                            ),
                            block,
                        )
                continue
            arr = state["tables"][tname]["param"]
            arrays_meta[key] = {
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
            for start, stop, host_data in iter_owned_shards(arr):
                np.save(
                    os.path.join(
                        tmp, f"{key}.r{start:012d}-{stop:012d}.npy"
                    ),
                    host_data,
                )
        if proc == 0:
            for dname in sorted(state.get("dense", {})):
                with obs.phase("export_fetch"):
                    host_dense = np.asarray(
                        jax.device_get(state["dense"][dname])
                    )
                np.save(
                    os.path.join(tmp, f"dense.{dname}.npy"), host_dense
                )
            if trainer.remap is not None:
                np.save(os.path.join(tmp, REMAP_FILE), trainer.remap)
    except BaseException as e:
        err = e
    if not all_ok(err is None):
        if proc == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        if err is not None:
            raise err
        raise RuntimeError("artifact export failed on another process")
    try:
        if proc == 0:
            manifest = {
                "format": FORMAT,
                "model": cfg.model,
                "step": step,
                "config": cfg.to_json(),
                "config_digest": cfg.digest(),
                "arrays": arrays_meta,
                "dense": sorted(state.get("dense", {})),
                "remap": trainer.remap is not None,
                "created_unix": round(time.time(), 3),
            }
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(manifest, f, indent=2)
            # never leave the target path without a loadable artifact:
            # move the old one ASIDE first, rename the new one in, THEN
            # delete — a crash in between still leaves either the old
            # or the new artifact at (or recoverable next to) the path
            old = None
            if os.path.exists(directory):
                old = directory + ".old"
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(directory, old)
            os.rename(tmp, directory)
            if old is not None:
                shutil.rmtree(old)
    except BaseException as e:
        err = e
    if not all_ok(err is None):
        if proc == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        if err is not None:
            raise err
        raise RuntimeError("artifact finalize failed on process 0")
    return directory


def _atomic_save(directory: str, name: str, arr: np.ndarray) -> None:
    tmp = os.path.join(directory, f".tmp-{name}")
    with open(tmp, "wb") as f:  # file object: np.save never re-suffixes
        np.save(f, arr)
    os.replace(tmp, os.path.join(directory, name))


def item_catalog_from_block(
    block, split_field: int, max_items: int = 0
) -> list[tuple]:
    """Deduplicated item catalog in the featurize_raw row protocol
    from one parsed libffm block: each sample's ITEM-side features
    (slots >= ``split_field``) form a candidate, identified by its
    sorted key set.  The ONE copy of the catalog-identity rule, shared
    by the ``serve index`` CLI and the cascade smoke gate so the
    shipped tool and the tier-1 gate cannot diverge."""
    import numpy as np  # local: the module-level import exists; keep explicit

    items: list[tuple] = []
    seen: set[tuple] = set()
    for i in range(block.num_samples):
        lo, hi = int(block.row_ptr[i]), int(block.row_ptr[i + 1])
        ks = block.keys[lo:hi].astype(np.int64)
        ss = block.slots[lo:hi].astype(np.int32)
        sel = ss >= split_field
        ident = tuple(sorted(ks[sel]))
        if ident and ident not in seen:
            seen.add(ident)
            items.append((ks[sel], ss[sel], None))
        if max_items and len(items) >= max_items:
            break
    return items


def export_item_index(
    engine,
    directory: str,
    item_rows: list,
    item_ids=None,
) -> dict:
    """Freeze the item-tower embeddings of a retrieval model into a
    serve-time index inside an already-exported artifact directory.

    ``item_rows`` is the catalog in the ``featurize_raw`` row protocol
    (item-side features: slots in [tower_split_field, max_fields) and
    raw hash-space keys); ``item_ids`` the external item identity per
    row (default: the row ordinal).  ``engine`` must be a
    PredictEngine loaded from — or digest-identical to — ``directory``
    (a mismatched engine would bake embeddings from a different model
    into this artifact's index).

    Written files: ``item_index.npy`` [N, model.index_dim] embeddings
    (tower_dim core + 2 bias lanes — the top-k scan operand),
    ``item_ids.npy`` [N] int64, and the padded
    raw feature planes ``item_keys/item_slots/item_vals.npy`` [N, nnz]
    + ``item_nnz.npy`` [N] — the cascade (serve/cascade.py) reads
    those to assemble user+candidate rows for the ranking stage.
    Meta (``item_index.json``) carries count/dim/config digest and the
    servable step, so a stale index against a re-exported artifact is
    refused at load."""
    failpoint("artifact.export")
    manifest = load_manifest(directory)
    if engine.digest != manifest["config_digest"]:
        raise ValueError(
            f"export_item_index: engine digest {engine.digest} != "
            f"artifact {directory} digest {manifest['config_digest']} "
            "— the index must be computed by the model it ships with"
        )
    if not hasattr(engine.model, "item_embed"):
        raise ValueError(
            f"model {engine.cfg.model!r} has no item tower "
            "(models/__init__.py registry: retrieval=False) — only "
            "two-tower-factored families export an item index"
        )
    n = len(item_rows)
    if n < 1:
        raise ValueError("export_item_index: empty item catalog")
    emb = engine.item_embeddings(item_rows)  # [N, tower_dim]
    ids = (
        np.arange(n, dtype=np.int64)
        if item_ids is None
        else np.asarray(item_ids, dtype=np.int64)
    )
    if len(ids) != n:
        raise ValueError(
            f"export_item_index: {n} rows but {len(ids)} item_ids"
        )
    k = engine.cfg.max_nnz
    keys = np.zeros((n, k), np.int64)
    slots = np.zeros((n, k), np.int32)
    vals = np.zeros((n, k), np.float32)
    nnz = np.zeros(n, np.int32)
    for i, row in enumerate(item_rows):
        rk, rs, rv = row if isinstance(row, tuple) else (row, None, None)
        rk = np.asarray(rk)
        m = min(len(rk), k)
        nnz[i] = m
        keys[i, :m] = rk[:m]
        if rs is not None:
            slots[i, :m] = np.asarray(rs)[:m]
        vals[i, :m] = 1.0 if rv is None else np.asarray(rv)[:m]
    _atomic_save(directory, "item_index.npy", emb.astype(np.float32))
    _atomic_save(directory, "item_ids.npy", ids)
    _atomic_save(directory, "item_keys.npy", keys)
    _atomic_save(directory, "item_slots.npy", slots)
    _atomic_save(directory, "item_vals.npy", vals)
    _atomic_save(directory, "item_nnz.npy", nnz)
    meta = {
        "count": n,
        "dim": int(emb.shape[1]),
        "nnz": int(k),
        "config_digest": engine.digest,
        "servable": engine.servable_digest,
        "created_unix": round(time.time(), 3),
    }
    tmp = os.path.join(directory, ".tmp-" + ITEM_INDEX_META)
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, os.path.join(directory, ITEM_INDEX_META))
    return meta


def load_item_index(directory: str) -> dict | None:
    """The index exported by :func:`export_item_index`, or None when
    the artifact has no index.  Refuses (ValueError) an index whose
    config digest does not match the artifact manifest — that is a
    stale index left behind by a re-export under a different config,
    and serving it would retrieve with the wrong geometry."""
    failpoint("artifact.load")
    path = os.path.join(directory, ITEM_INDEX_META)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        meta = json.load(f)
    manifest = load_manifest(directory)
    if meta.get("config_digest") != manifest["config_digest"]:
        raise ValueError(
            f"{directory}: item index was built for config "
            f"{meta.get('config_digest')!r} but the artifact is "
            f"{manifest['config_digest']!r} — re-run export_item_index "
            "against the current artifact"
        )
    out = dict(meta)
    for name in (
        "item_index", "item_ids", "item_keys", "item_slots",
        "item_vals", "item_nnz",
    ):
        out[name] = np.load(os.path.join(directory, f"{name}.npy"))
    if out["item_index"].shape != (meta["count"], meta["dim"]):
        raise ValueError(
            f"{directory}: item_index.npy shape "
            f"{out['item_index'].shape} does not match meta "
            f"({meta['count']}, {meta['dim']})"
        )
    return out


def load_manifest(directory: str) -> dict:
    """Parse + integrity-check an artifact manifest.  Raises ValueError
    on a missing/foreign/future-format manifest or when the stored
    config digest doesn't match the embedded config (tampering or a
    digest-scheme drift — either way the artifact identity is void)."""
    from xflow_tpu.config import Config

    failpoint("artifact.load")
    path = os.path.join(directory, MANIFEST)
    if not os.path.exists(path):
        raise ValueError(f"{directory}: no artifact manifest ({MANIFEST})")
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT:
        raise ValueError(
            f"{directory}: unsupported artifact format "
            f"{manifest.get('format')!r} (expected {FORMAT})"
        )
    cfg = Config.from_json(manifest["config"])
    if cfg.digest() != manifest.get("config_digest"):
        raise ValueError(
            f"{directory}: manifest config_digest "
            f"{manifest.get('config_digest')!r} does not match the "
            f"embedded config ({cfg.digest()}) — artifact corrupt or "
            "tampered"
        )
    return manifest
