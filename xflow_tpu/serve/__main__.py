"""CLI: ``python -m xflow_tpu.serve <bench|score> ARTIFACT ...``

    score  ARTIFACT --input FILE      pctr per libffm line (stdout/--out)
    bench  ARTIFACT [--requests N]    concurrent single-row load through
                                      the MicroBatcher; prints a JSON
                                      summary with queue/featurize/
                                      device/e2e p50+p99 and logs
                                      serve_load/serve_stats/serve_bench
                                      JSONL rows (--metrics-out) that
                                      ``python -m xflow_tpu.obs
                                      validate`` checks like any other
                                      metrics file

Serving docs: docs/SERVING.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _buckets(text: str | None) -> tuple[int, ...] | None:
    if not text:
        return None
    return tuple(int(b) for b in text.split(","))


def _percentile(vals: list[float], p: float) -> float:
    # one percentile definition repo-wide: obs.registry.Histogram
    from xflow_tpu.obs.registry import Histogram

    h = Histogram(capacity=max(len(vals), 1))
    for v in vals:
        h.observe(v)
    return round(h.percentile(p), 6)


def cmd_score(args) -> int:
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        args.artifact,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        warm=not args.no_warm,
    )
    src = open(args.input) if args.input else sys.stdin
    try:
        lines = [l for l in src.read().splitlines() if l.strip()]
    finally:
        if args.input:
            src.close()
    pctr = engine.score_text(lines)
    out = open(args.out, "w") if args.out else sys.stdout
    try:
        for p in pctr:
            out.write(f"{p:.6f}\n")
    finally:
        if args.out:
            out.close()
    return 0


def cmd_bench(args) -> int:
    from xflow_tpu.obs.schema import validate_rows
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine
    from xflow_tpu.utils.logging import MetricsLogger

    engine = PredictEngine.load(
        args.artifact,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        warm=True,
    )
    cfg = engine.cfg
    logger = None
    if args.metrics_out:
        logger = MetricsLogger(
            args.metrics_out,
            run_header={
                "run_id": f"{int(time.time() * 1000):x}-bench",
                "config_digest": engine.digest,
                "rank": 0,
                "num_hosts": 1,
                "model": cfg.model,
            },
        )
        logger.log("serve_load", {
            "artifact": args.artifact,
            "config_digest": engine.digest,
            "model": cfg.model,
            "buckets": list(engine.buckets),
            "warm_seconds": round(engine.warm_seconds, 6),
            "compiles": engine.compile_count,
        })
    batcher = MicroBatcher(
        engine, max_wait_ms=args.max_wait_ms, metrics_logger=logger
    )
    rng = np.random.default_rng(args.seed)
    nnz = min(args.nnz, cfg.max_nnz)
    rows = [
        (
            rng.integers(0, cfg.table_size, size=nnz).astype(np.int64),
            np.arange(nnz, dtype=np.int32) % max(cfg.max_fields, 1),
            None,
        )
        for _ in range(args.requests)
    ]
    e2e: list[float] = []
    e2e_lock = threading.Lock()

    def worker(my_rows) -> None:
        for row in my_rows:
            t0 = time.perf_counter()
            fut = batcher.submit(*row)
            fut.result()
            dt = time.perf_counter() - t0
            with e2e_lock:
                e2e.append(dt)

    threads = [
        # bounded workload — each worker drains a finite request slice,
        # so the untimed join below ends with it; a wedged engine is
        # the batcher close() join-timeout's job (xf: ignore[XF006])
        threading.Thread(target=worker, args=(rows[i :: args.concurrency],))
        for i in range(args.concurrency)
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seconds = time.perf_counter() - t_start
    stats = batcher.close()
    summary = {
        "requests": args.requests,
        "concurrency": args.concurrency,
        "seconds": round(seconds, 6),
        "requests_per_sec": round(args.requests / max(seconds, 1e-9), 1),
        "e2e_p50": _percentile(e2e, 50),
        "e2e_p99": _percentile(e2e, 99),
        "queue_p50": stats["queue_p50"],
        "queue_p99": stats["queue_p99"],
        "featurize_p50": stats["featurize_p50"],
        "featurize_p99": stats["featurize_p99"],
        "device_p50": stats["device_p50"],
        "device_p99": stats["device_p99"],
        "compiles": engine.compile_count,
    }
    if logger is not None:
        logger.log("serve_bench", summary)
        logger.close()
        from xflow_tpu.obs.schema import load_jsonl

        errors = validate_rows(load_jsonl(args.metrics_out))
        if errors:
            for e in errors:
                print(f"schema violation: {e}", file=sys.stderr)
            return 1
    print(json.dumps(
        dict(summary, buckets=list(engine.buckets),
             batch_fill_mean=stats["batch_fill_mean"]),
        sort_keys=True,
    ))
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m xflow_tpu.serve",
        description="serving toolchain (docs/SERVING.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("artifact", help="artifact dir (serve/artifact.py)")
        sp.add_argument("--num-devices", type=int, default=1)
        sp.add_argument(
            "--buckets", default="",
            help="comma-separated batch-size buckets (default 1,8,64,512)",
        )

    ps = sub.add_parser("score", help="pctr per libffm input line")
    common(ps)
    ps.add_argument("--input", default="", help="libffm file (default stdin)")
    ps.add_argument("--out", default="", help="output file (default stdout)")
    ps.add_argument("--no-warm", action="store_true")

    pb = sub.add_parser("bench", help="concurrent serving latency bench")
    common(pb)
    pb.add_argument("--requests", type=int, default=256)
    pb.add_argument("--concurrency", type=int, default=8)
    pb.add_argument("--max-wait-ms", type=float, default=2.0)
    pb.add_argument("--nnz", type=int, default=16, help="features/request")
    pb.add_argument("--seed", type=int, default=0)
    pb.add_argument("--metrics-out", default="")
    args = p.parse_args(argv)

    if args.cmd == "score":
        return cmd_score(args)
    return cmd_bench(args)


if __name__ == "__main__":
    sys.exit(main())
