"""CLI: ``python -m xflow_tpu.serve <serve|index|cascade|loadgen|bench|score>``

    index   ARTIFACT --input FILE     build the serve-time item index
                                      beside a retrieval artifact from
                                      libffm item rows (item-side
                                      features deduplicated into the
                                      catalog, embedded through the
                                      item tower — serve/artifact.py::
                                      export_item_index)

    cascade RETRIEVAL RANKING --port P
                                      retrieval→ranking cascade tier
                                      (serve/cascade.py): a top-k fleet
                                      over the retrieval artifact's
                                      item index feeding a point-score
                                      fleet over the ranking artifact,
                                      behind one HTTP front end
                                      (/v1/recommend, /v1/topk,
                                      /v1/score; rollout endpoints
                                      take "stage": "retrieval"|
                                      "ranking"); emits `cascade`
                                      JSONL stats windows


    score   ARTIFACT --input FILE     pctr per libffm line (stdout/--out)
    bench   ARTIFACT [--requests N]   closed-loop concurrent load through
                                      one MicroBatcher; prints a JSON
                                      summary with queue/featurize/
                                      device/e2e p50+p99 and logs
                                      serve_load/serve_stats/serve_bench
                                      JSONL rows (--metrics-out) that
                                      ``python -m xflow_tpu.obs
                                      validate`` checks like any other
                                      metrics file
    serve   ARTIFACT --port P         production tier: HTTP front end
                                      (serve/server.py) over a replica
                                      fleet (--replicas) with admission
                                      control and staged rollout
                                      (--canary-frac default); prints
                                      one JSON line with the bound
                                      address, then serves until
                                      SIGTERM/SIGINT — which drain
                                      gracefully through the tier/fleet
                                      close() path (every accepted
                                      request scores, final stats rows
                                      flush)
    loadgen ARTIFACT --qps Q          open-loop zipf traffic generator
                                      (serve/loadgen.py) against an
                                      in-process fleet or --url of a
                                      running tier; logs the serve_bench
                                      SLO row scripts/check_serve_slo.py
                                      gates on

Serving docs: docs/SERVING.md (the "Production tier" section covers
serve/loadgen, rollout states, and the shed policy).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _buckets(text: str | None) -> tuple[int, ...] | None:
    if not text:
        return None
    return tuple(int(b) for b in text.split(","))


def _percentile(vals: list[float], p: float) -> float:
    # one percentile definition repo-wide: obs.registry.Histogram
    from xflow_tpu.obs.registry import Histogram

    h = Histogram(capacity=max(len(vals), 1))
    for v in vals:
        h.observe(v)
    return round(h.percentile(p), 6)


def cmd_score(args) -> int:
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        args.artifact,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        warm=not args.no_warm,
    )
    src = open(args.input) if args.input else sys.stdin
    try:
        lines = [l for l in src.read().splitlines() if l.strip()]
    finally:
        if args.input:
            src.close()
    pctr = engine.score_text(lines)
    out = open(args.out, "w") if args.out else sys.stdout
    try:
        for p in pctr:
            out.write(f"{p:.6f}\n")
    finally:
        if args.out:
            out.close()
    return 0


def cmd_bench(args) -> int:
    from xflow_tpu.obs.schema import validate_rows
    from xflow_tpu.serve.batcher import MicroBatcher
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        args.artifact,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        warm=True,
    )
    cfg = engine.cfg
    logger = _serve_logger(
        args.metrics_out, engine.digest, cfg.model, "bench"
    )
    if logger is not None:
        logger.log("serve_load", {
            "artifact": args.artifact,
            "config_digest": engine.digest,
            "model": cfg.model,
            "buckets": list(engine.buckets),
            "warm_seconds": round(engine.warm_seconds, 6),
            "compiles": engine.compile_count,
        })
    batcher = MicroBatcher(
        engine, max_wait_ms=args.max_wait_ms, metrics_logger=logger
    )
    rng = np.random.default_rng(args.seed)
    nnz = min(args.nnz, cfg.max_nnz)
    rows = [
        (
            rng.integers(0, cfg.table_size, size=nnz).astype(np.int64),
            np.arange(nnz, dtype=np.int32) % max(cfg.max_fields, 1),
            None,
        )
        for _ in range(args.requests)
    ]
    e2e: list[float] = []
    e2e_lock = threading.Lock()

    def worker(my_rows) -> None:
        for row in my_rows:
            t0 = time.perf_counter()
            fut = batcher.submit(*row)
            fut.result(timeout=600.0)
            dt = time.perf_counter() - t0
            with e2e_lock:
                e2e.append(dt)

    threads = [
        # bounded workload — each worker drains a finite request slice,
        # so the untimed join below ends with it; a wedged engine is
        # the batcher close() join-timeout's job (xf: ignore[XF006])
        threading.Thread(target=worker, args=(rows[i :: args.concurrency],))
        for i in range(args.concurrency)
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seconds = time.perf_counter() - t_start
    stats = batcher.close()
    summary = {
        "requests": args.requests,
        "concurrency": args.concurrency,
        "seconds": round(seconds, 6),
        "requests_per_sec": round(args.requests / max(seconds, 1e-9), 1),
        "e2e_p50": _percentile(e2e, 50),
        "e2e_p99": _percentile(e2e, 99),
        "queue_p50": stats["queue_p50"],
        "queue_p99": stats["queue_p99"],
        "featurize_p50": stats["featurize_p50"],
        "featurize_p99": stats["featurize_p99"],
        "device_p50": stats["device_p50"],
        "device_p99": stats["device_p99"],
        "compiles": engine.compile_count,
    }
    if logger is not None:
        logger.log("serve_bench", summary)
        logger.close()
        from xflow_tpu.obs.schema import load_jsonl

        errors = validate_rows(load_jsonl(args.metrics_out))
        if errors:
            for e in errors:
                print(f"schema violation: {e}", file=sys.stderr)
            return 1
    print(json.dumps(
        dict(summary, buckets=list(engine.buckets),
             batch_fill_mean=stats["batch_fill_mean"]),
        sort_keys=True,
    ))
    return 0


def _serve_logger(path: str, digest: str, model: str, tag: str):
    from xflow_tpu.utils.logging import MetricsLogger

    if not path:
        return None
    return MetricsLogger(path, run_header={
        "run_id": f"{int(time.time() * 1000):x}-{tag}",
        "config_digest": digest,
        "rank": 0,
        "num_hosts": 1,
        "model": model,
    })


def _reqtrace_sink(logger, sample: float):
    """Request-scoped trace sink (obs/reqtrace.py) bound to the tier's
    metrics stream; None when metrics are off — tracing without a sink
    to land in would stamp spans nobody can read."""
    from xflow_tpu.obs.reqtrace import ReqTraceSink

    if logger is None:
        return None
    return ReqTraceSink(metrics_logger=logger, sample=sample)


def cmd_serve(args) -> int:
    """The production tier: fleet + HTTP front end + watchdog, alive
    until SIGTERM/SIGINT, then a graceful drain through
    ``ServeTier.close()`` → ``ReplicaFleet.close()`` (every accepted
    request scores; the final serve_stats/serve_shed rows flush)."""
    import signal

    from xflow_tpu.obs.flight import FlightRecorder
    from xflow_tpu.obs.watchdog import Watchdog
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import ServeTier

    flight = FlightRecorder()
    fleet = ReplicaFleet.load(
        args.artifact,
        replicas=args.replicas,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        max_wait_ms=args.max_wait_ms,
        deadline_budget_ms=args.deadline_budget_ms,
        depth_budget=args.depth_budget,
        flight=flight,
        cache_capacity=args.cache_capacity,
    )
    logger = _serve_logger(
        args.metrics_out, fleet.digest, fleet.cfg.model, "serve"
    )
    fleet.metrics_logger = logger
    flight.metrics_logger = logger
    fleet.reqtrace = _reqtrace_sink(logger, args.reqtrace_sample)
    fleet.log_load(args.artifact)
    # chaos fabric (docs/ROBUSTNESS.md): the XFLOW_CHAOS env var arms
    # the serve surface too, with chaos rows in this tier's stream
    from xflow_tpu import chaos

    if chaos.arm_from_env() is not None and logger is not None:
        chaos.attach_logger(logger)
    tier = ServeTier(
        fleet,
        host=args.host,
        port=args.port,
        flight=flight,
        default_canary_frac=args.canary_frac,
        score_timeout_s=fleet.cfg.serve_score_timeout_s,
        socket_timeout_s=fleet.cfg.serve_socket_timeout_s,
    )
    wd = Watchdog(
        flight, serve_s=args.watchdog_serve_s, metrics_logger=logger
    )
    wd.set_pending("serve", fleet.pending)
    wd.set_pending("http", lambda: tier.running)
    # live telemetry plane (obs/live.py, obs/export.py): SLO alert
    # rules evaluated over each stats window, host resource rows per
    # tick, and both surfaced on GET /v1/stats next to the watchdog's
    # health state; GET /metrics exposition comes free with the tier
    from xflow_tpu.obs.export import ResourceSampler
    from xflow_tpu.obs.live import AlertEvaluator

    alerts = AlertEvaluator(metrics_logger=logger)
    sampler = ResourceSampler(
        metrics_logger=logger, registry=fleet.registry
    )
    tier.watchdog = wd
    tier.alerts = alerts
    # binary front end (serve/binary.py): the persistent XFB1
    # transport, sharing the SAME fleet (and therefore the same
    # admission control, cache, and stats windows) as the HTTP tier
    btier = None
    if args.binary_port >= 0:
        from xflow_tpu.serve.binary import BinaryTier

        btier = BinaryTier(
            fleet,
            host=args.host,
            port=args.binary_port,
            flight=flight,
            score_timeout_s=fleet.cfg.serve_score_timeout_s,
            socket_timeout_s=fleet.cfg.serve_socket_timeout_s,
        ).start()

    stop = threading.Event()

    def _drain(signum, frame) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    tier.start()
    wd.start()
    print(json.dumps({
        "serving": tier.address,
        "binary": btier.address if btier is not None else None,
        "digest": fleet.digest,
        "model": fleet.cfg.model,
        "replicas": fleet.replicas,
        "buckets": list(fleet.engines[0].buckets),
        "admission": fleet.policy.describe(),
        "cache_capacity": (
            fleet.cache.capacity if fleet.cache is not None else 0
        ),
    }, sort_keys=True), flush=True)
    # stats-window loop IS the main thread's job until a drain signal
    while not stop.wait(args.stats_every_s):
        out = fleet.emit_stats()
        sampler.sample()
        alerts.observe_rows([
            dict(out["stats"], kind="serve_stats"),
            dict(out["shed"], kind="serve_shed"),
        ])
    wd.stop()
    # binary front end first: it only submits into the fleet, so the
    # tier/fleet close below still drains whatever it admitted
    if btier is not None:
        btier.close()
    final = tier.close()
    if logger is not None:
        logger.close()
    print(json.dumps({"drained": final}, sort_keys=True), flush=True)
    return 0


def cmd_index(args) -> int:
    """Build the serve-time item index beside a retrieval artifact
    from libffm-format lines: each line's ITEM-side features (fields
    >= the artifact's tower_split_field) become one catalog item,
    deduplicated by feature set, embedded through the item tower, and
    frozen via serve.artifact.export_item_index."""
    from xflow_tpu.io.loader import make_parse_fn
    from xflow_tpu.serve.artifact import (
        export_item_index,
        item_catalog_from_block,
    )
    from xflow_tpu.serve.engine import PredictEngine

    engine = PredictEngine.load(
        args.artifact,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        warm=False,
    )
    cfg = engine.cfg
    parse = make_parse_fn(
        cfg.table_size, cfg.hash_mode, cfg.seed,
        prefer_native=cfg.native_parser,
    )
    src = open(args.input, "rb") if args.input else sys.stdin.buffer
    try:
        block = parse(src.read())
    finally:
        if args.input:
            src.close()
    items = item_catalog_from_block(
        block, cfg.tower_split_field, args.max_items
    )
    if not items:
        print(
            "error: no item-side features found (fields >= "
            f"tower_split_field={cfg.tower_split_field})",
            file=sys.stderr,
        )
        return 1
    meta = export_item_index(engine, args.artifact, items)
    print(json.dumps({
        "artifact": args.artifact,
        "items": meta["count"],
        "dim": meta["dim"],
        "servable": meta["servable"],
    }, sort_keys=True))
    return 0


def cmd_cascade(args) -> int:
    """The cascade tier: retrieval top-k fleet + ranking fleet +
    CascadeEngine behind one HTTP front end, alive until
    SIGTERM/SIGINT, then a graceful drain (retrieval first, then
    ranking — in-flight fan-outs land before the ranking queues
    close)."""
    import signal

    from xflow_tpu.serve.cascade import CascadeEngine
    from xflow_tpu.serve.fleet import ReplicaFleet
    from xflow_tpu.serve.server import ServeTier

    retrieval = ReplicaFleet.load(
        args.retrieval,
        replicas=args.replicas,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        max_wait_ms=args.max_wait_ms,
        deadline_budget_ms=args.deadline_budget_ms,
        depth_budget=args.depth_budget,
        topk_k=args.topk_k,
        topk=True,
    )
    ranking = ReplicaFleet.load(
        args.ranking,
        replicas=args.replicas,
        num_devices=args.num_devices,
        buckets=_buckets(args.buckets),
        max_wait_ms=args.max_wait_ms,
        deadline_budget_ms=args.deadline_budget_ms,
        depth_budget=args.depth_budget,
    )
    logger = _serve_logger(
        args.metrics_out, ranking.digest, ranking.cfg.model, "cascade"
    )
    retrieval.metrics_logger = logger
    ranking.metrics_logger = logger
    # ONE sink across both stages: a /recommend request keeps one
    # trace id through retrieval fan-in and the ranking fan-out, so a
    # span tree reads end-to-end (obs/reqtrace.py)
    sink = _reqtrace_sink(logger, args.reqtrace_sample)
    retrieval.reqtrace = sink
    retrieval.reqtrace_stage = "retrieval"
    ranking.reqtrace = sink
    ranking.reqtrace_stage = "ranking"
    cascade = CascadeEngine(
        retrieval, ranking, k=args.k, metrics_logger=logger
    )
    tier = ServeTier(
        ranking,
        host=args.host,
        port=args.port,
        default_canary_frac=args.canary_frac,
        cascade=cascade,
        score_timeout_s=ranking.cfg.serve_score_timeout_s,
        socket_timeout_s=ranking.cfg.serve_socket_timeout_s,
    )
    stop = threading.Event()

    def _drain(signum, frame) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    tier.start()
    print(json.dumps({
        "serving": tier.address,
        "retrieval_digest": retrieval.digest,
        "ranking_digest": ranking.digest,
        "k": cascade.k,
        "topk_k": retrieval.engines[0].topk_k,
        "index_items": int(len(retrieval.engines[0].item_index["item_index"])),
        "replicas": args.replicas,
    }, sort_keys=True), flush=True)
    while not stop.wait(args.stats_every_s):
        cascade.emit_stats()
        retrieval.emit_stats()
        ranking.emit_stats()
    final = tier.close()
    if logger is not None:
        logger.close()
    print(json.dumps({"drained": final}, sort_keys=True), flush=True)
    return 0


def _parse_qos_mix(text: str) -> dict | None:
    """"bidding=0.3,normal=0.5,best_effort=0.2" → class fractions."""
    if not text:
        return None
    mix = {}
    for part in text.split(","):
        name, sep, frac = part.partition("=")
        if not sep:
            raise ValueError(
                f"bad --qos-mix entry {part!r} (want class=frac)"
            )
        mix[name.strip()] = float(frac)
    return mix


def cmd_loadgen(args) -> int:
    from xflow_tpu.obs.schema import load_jsonl, validate_rows
    from xflow_tpu.serve.loadgen import (
        BinaryTarget,
        HttpTarget,
        run_loadgen,
    )

    qos_mix = _parse_qos_mix(args.qos_mix)
    remote_target = None
    if args.url or args.binary_addr:
        # remote mode: the artifact supplies only the key space
        from xflow_tpu.config import Config
        from xflow_tpu.serve.artifact import load_manifest

        manifest = load_manifest(args.artifact)
        digest = manifest["config_digest"]
        model = manifest["model"]
        cfg = Config.from_json(manifest["config"])
        table_size = int(cfg.table_size)
        if args.binary_addr:
            host, _, port = args.binary_addr.rpartition(":")
            depth = (
                args.pipeline_depth
                if args.pipeline_depth is not None
                else cfg.serve_pipeline_depth
            )
            remote_target = BinaryTarget(
                host or "127.0.0.1",
                int(port),
                timeout_s=cfg.serve_client_timeout_s,
                pipeline_depth=depth,
                qos=args.qos or None,
            )
        else:
            remote_target = HttpTarget(
                args.url,
                timeout_s=cfg.serve_client_timeout_s,
                qos=args.qos or None,
            )
        target: object = remote_target
        fleet = None
    else:
        from xflow_tpu.serve.fleet import ReplicaFleet

        fleet = ReplicaFleet.load(
            args.artifact,
            replicas=args.replicas,
            num_devices=args.num_devices,
            buckets=_buckets(args.buckets),
            max_wait_ms=args.max_wait_ms,
            deadline_budget_ms=args.deadline_budget_ms,
            depth_budget=args.depth_budget,
            cache_capacity=args.cache_capacity,
            **({"default_qos": args.qos} if args.qos else {}),
        )
        digest, model = fleet.digest, fleet.cfg.model
        table_size = None
        target = fleet
    logger = _serve_logger(args.metrics_out, digest, model, "loadgen")
    if fleet is not None:
        fleet.metrics_logger = logger
        fleet.reqtrace = _reqtrace_sink(logger, args.reqtrace_sample)
        fleet.log_load(args.artifact)
    remote = bool(args.url or args.binary_addr)
    try:
        summary = run_loadgen(
            target,
            offered_qps=args.qps,
            duration_s=args.duration_s,
            concurrency=args.concurrency,
            nnz=args.nnz,
            zipf_a=args.zipf_a,
            table_size=table_size,
            seed=args.seed,
            metrics_logger=logger,
            # remote tier: no local sink to auto-enable on, so the
            # flag itself arms client-side minting over the XFS2 wire
            trace=(args.reqtrace_sample > 0) if remote else None,
            trace_sample=args.reqtrace_sample,
            qos_mix=qos_mix,
        )
    finally:
        if fleet is not None:
            fleet.close()
        if remote_target is not None and hasattr(remote_target, "close"):
            remote_target.close()
        if logger is not None:
            logger.close()
    if args.metrics_out:
        errors = validate_rows(load_jsonl(args.metrics_out))
        if errors:
            for e in errors:
                print(f"schema violation: {e}", file=sys.stderr)
            return 1
    print(json.dumps(summary, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m xflow_tpu.serve",
        description="serving toolchain (docs/SERVING.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("artifact", help="artifact dir (serve/artifact.py)")
        sp.add_argument("--num-devices", type=int, default=1)
        sp.add_argument(
            "--buckets", default="",
            help="comma-separated batch-size buckets (default 1,8,64,512)",
        )

    ps = sub.add_parser("score", help="pctr per libffm input line")
    common(ps)
    ps.add_argument("--input", default="", help="libffm file (default stdin)")
    ps.add_argument("--out", default="", help="output file (default stdout)")
    ps.add_argument("--no-warm", action="store_true")

    pb = sub.add_parser("bench", help="concurrent serving latency bench")
    common(pb)
    pb.add_argument("--requests", type=int, default=256)
    pb.add_argument("--concurrency", type=int, default=8)
    pb.add_argument("--max-wait-ms", type=float, default=2.0)
    pb.add_argument("--nnz", type=int, default=16, help="features/request")
    pb.add_argument("--seed", type=int, default=0)
    pb.add_argument("--metrics-out", default="")

    def fleet_args(sp):
        sp.add_argument(
            "--replicas", type=int, default=2,
            help="PredictEngine replicas behind the router (clones of "
            "one loaded artifact — shared weights + compiles)",
        )
        sp.add_argument("--max-wait-ms", type=float, default=2.0)
        sp.add_argument(
            "--deadline-budget-ms", type=float, default=50.0,
            help="admission control: shed when the oldest queued "
            "request is older than this",
        )
        sp.add_argument(
            "--depth-budget", type=int, default=256,
            help="admission control: shed when a replica backlog "
            "reaches this depth",
        )
        sp.add_argument(
            "--reqtrace-sample", type=float, default=0.01,
            help="head-sampling rate for request-scoped traces in "
            "[0, 1]; errors, sheds, and the window's slowest-k are "
            "always kept regardless (obs/reqtrace.py)",
        )
        sp.add_argument("--metrics-out", default="")

    pv = sub.add_parser(
        "serve", help="HTTP serving tier (fleet + admission + rollout)"
    )
    common(pv)
    fleet_args(pv)
    pv.add_argument("--host", default="127.0.0.1")
    pv.add_argument("--port", type=int, default=8000)
    pv.add_argument(
        "--binary-port", type=int, default=-1,
        help="also serve the persistent XFB1 binary transport on this "
        "port (0 = ephemeral, -1 = off; serve/binary.py) — same "
        "fleet, admission control, and cache as the HTTP tier",
    )
    pv.add_argument(
        "--cache-capacity", type=int, default=None,
        help="hot-key score cache entries (serve/scache.py; 0 = off; "
        "default = the artifact config's serve_cache_capacity)",
    )
    pv.add_argument(
        "--canary-frac", type=float, default=0.1,
        help="default canary traffic fraction for POST /v1/rollout",
    )
    pv.add_argument(
        "--stats-every-s", type=float, default=10.0,
        help="serve_stats/serve_shed window flush period",
    )
    pv.add_argument("--watchdog-serve-s", type=float, default=10.0)

    pi = sub.add_parser(
        "index",
        help="build the serve-time item index beside a retrieval "
        "artifact from libffm item rows (docs/SERVING.md)",
    )
    common(pi)
    pi.add_argument(
        "--input", default="",
        help="libffm file of item rows (default stdin); item-side "
        "features (fields >= the artifact's tower_split_field) are "
        "deduplicated into the catalog",
    )
    pi.add_argument(
        "--max-items", type=int, default=0,
        help="cap the catalog size (0 = no cap)",
    )

    pc = sub.add_parser(
        "cascade",
        help="retrieval→ranking cascade tier (docs/SERVING.md)",
    )
    pc.add_argument(
        "retrieval",
        help="retrieval artifact dir (two-tower family with an item "
        "index — serve.artifact.export_item_index)",
    )
    pc.add_argument(
        "ranking", help="ranking artifact dir (any point-score family)"
    )
    pc.add_argument("--num-devices", type=int, default=1)
    pc.add_argument(
        "--buckets", default="",
        help="comma-separated batch-size buckets (default 1,8,64,512)",
    )
    fleet_args(pc)
    pc.add_argument("--host", default="127.0.0.1")
    pc.add_argument("--port", type=int, default=8000)
    pc.add_argument(
        "--k", type=int, default=8,
        help="candidates retrieved and ranked per request",
    )
    pc.add_argument(
        "--topk-k", type=int, default=None,
        help="compiled top-k width on the retrieval engines "
        "(default 16, capped at the index size); per-request k "
        "slices it",
    )
    pc.add_argument("--canary-frac", type=float, default=0.1)
    pc.add_argument("--stats-every-s", type=float, default=10.0)

    pl = sub.add_parser(
        "loadgen", help="open-loop zipf load generator (SLO rows)"
    )
    common(pl)
    fleet_args(pl)
    pl.add_argument(
        "--url", default="",
        help="target a RUNNING tier instead of an in-process fleet "
        "(the artifact then only supplies the key space)",
    )
    pl.add_argument(
        "--binary-addr", default="",
        help="target a RUNNING binary tier at HOST:PORT over the "
        "pipelined XFB1 transport (serve/loadgen.py::BinaryTarget) "
        "instead of HTTP",
    )
    pl.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="max in-flight XFB1 frames per connection (binary "
        "transport; default = the artifact config's "
        "serve_pipeline_depth)",
    )
    pl.add_argument(
        "--qos", default="",
        help="QoS admission class for ALL offered traffic "
        "(bidding|normal|best_effort; default = the tier default)",
    )
    pl.add_argument(
        "--qos-mix", default="",
        help="mixed-class traffic, e.g. "
        "'bidding=0.3,normal=0.5,best_effort=0.2' — classes "
        "interleave at these fractions; the summary carries "
        "qos_offered/qos_shed per class",
    )
    pl.add_argument(
        "--cache-capacity", type=int, default=None,
        help="hot-key score cache entries for the in-process fleet "
        "(0 = off; default = the artifact config knob)",
    )
    pl.add_argument("--qps", type=float, default=500.0)
    pl.add_argument("--duration-s", type=float, default=10.0)
    pl.add_argument("--concurrency", type=int, default=8)
    pl.add_argument("--nnz", type=int, default=8)
    pl.add_argument("--zipf-a", type=float, default=1.3)
    pl.add_argument("--seed", type=int, default=0)

    args = p.parse_args(argv)

    if args.cmd == "score":
        return cmd_score(args)
    if args.cmd == "serve":
        return cmd_serve(args)
    if args.cmd == "index":
        return cmd_index(args)
    if args.cmd == "cascade":
        return cmd_cascade(args)
    if args.cmd == "loadgen":
        return cmd_loadgen(args)
    return cmd_bench(args)


if __name__ == "__main__":
    sys.exit(main())
