"""Serving subsystem (ISSUE 2): exportable inference artifacts and a
low-latency scoring tier that needs no Trainer, no loader, and no
optimizer state.

Production ads stacks separate training from online scoring (PAPERS.md:
Distributed Hierarchical GPU Parameter Server, arxiv 2003.05622;
Scalable ML Training Infra for Online Ads at Google, arxiv 2501.10546).
Here that split is three layers:

* ``artifact`` — ``export_artifact(trainer, dir)`` freezes inference
  weights (params only — FTRL n/z stay behind), the hot-table remap,
  and a digest-stamped manifest, in the checkpoint row-range shard
  format (utils/checkpoint.py) so multi-host exports need no gather;
* ``engine`` — ``PredictEngine``: loads an artifact (or wraps a live
  trainer state), compiles the predict step once per fixed batch-size
  bucket (AOT — concurrent traffic never triggers fresh XLA compiles),
  and scores padded request batches;
* ``batcher`` — ``MicroBatcher``: coalesces concurrent single-row
  requests into one bucketed device call under a max-wait deadline,
  with atomic hot-swap of a newer artifact mid-serve and per-request
  queue/featurize/device latency histograms (obs registry; JSONL kinds
  in obs/schema.py).

The production tier stacks three more layers on those (docs/SERVING.md
"Production tier"):

* ``fleet`` — ``ReplicaFleet``: N engine replicas (clones sharing one
  artifact's weights and AOT executables) behind round-robin routing,
  admission control / typed load shedding (:class:`ShedError`), and
  digest-guarded staged rollout (canary traffic split → health gate →
  atomic fleet-wide swap);
* ``server`` — ``ServeTier``: dependency-free concurrent HTTP front
  end (stdlib ``ThreadingHTTPServer``) with JSON + packed-binary score
  endpoints, typed 429 backpressure, rollout endpoints, and graceful
  drain through the fleet's close() path;
* ``loadgen`` — ``run_loadgen``: open-loop zipf traffic with SLO
  accounting (``serve_bench`` rows gated by
  scripts/check_serve_slo.py).

And the candidate-generation half of a real recommender stack rides
the same fleets (docs/SERVING.md "Retrieval→ranking cascade"):

* ``artifact.export_item_index`` — freezes a two-tower model's item
  embeddings (+ the candidates' feature planes) into a serve-time
  index beside the artifact; ``PredictEngine.topk`` scores it by dot
  product, AOT-compiled per bucket like predict;
* ``cascade`` — ``CascadeEngine``: routes a request through a
  retrieval fleet's top-k endpoint and feeds the candidates to a
  ranking fleet's score endpoint, with front-door admission control,
  per-stage latency/candidate-count ``cascade`` JSONL rows, and
  independent staged rollout of either stage.

CLI: ``python -m xflow_tpu.serve serve|cascade|loadgen|bench|score``
(docs/SERVING.md).
"""

from xflow_tpu.serve.artifact import (
    export_artifact,
    export_item_index,
    load_item_index,
    load_manifest,
)
from xflow_tpu.serve.cascade import CascadeEngine
from xflow_tpu.serve.batcher import MicroBatcher
from xflow_tpu.serve.engine import DEFAULT_BUCKETS, PredictEngine
from xflow_tpu.serve.fleet import AdmissionPolicy, ReplicaFleet, ShedError
from xflow_tpu.serve.loadgen import run_loadgen
from xflow_tpu.serve.server import ServeTier

__all__ = [
    "export_artifact",
    "export_item_index",
    "load_item_index",
    "load_manifest",
    "CascadeEngine",
    "PredictEngine",
    "MicroBatcher",
    "DEFAULT_BUCKETS",
    "ReplicaFleet",
    "AdmissionPolicy",
    "ShedError",
    "ServeTier",
    "run_loadgen",
]
