"""PredictEngine — the low-latency scoring tier.

Loads a frozen artifact (serve/artifact.py) or wraps a live trainer
state, with **no Trainer, no ShardLoader, no optimizer state**: the
engine owns a mesh, the model's predict computation, and param-only
tables.  Two properties make it serving-grade:

* **Shape-bucketed AOT compilation.**  ``XFlow.predict_batch``
  historically re-traced/re-compiled for every distinct batch shape —
  deadly under concurrent traffic where request batches are all sizes.
  The engine snaps every request batch onto a small fixed set of padded
  batch-size buckets (default 1/8/64/512, rounded up to mesh-divisible
  sizes) and compiles the predict step **ahead of time, exactly once
  per bucket** (``jax.jit(...).lower(...).compile()``), warmed at load.
  ``compile_count`` is the hook: after ``warm()`` it equals
  ``len(buckets)`` and MUST stay there under any traffic mix — a test
  regression here means latency cliffs in production.

* **Digest-checked identity.**  The engine refuses an artifact whose
  manifest digest doesn't match its embedded config, and refuses to
  load when the caller's expected config digests differently — scoring
  through the wrong geometry fails loudly at load, not silently with
  garbage pctr.

The hot-table remap (io/freq.py) is folded in: artifacts carry it and
``predict`` applies it to raw hash-space request keys via the shared
io/batch.py::remap_batch, so external callers never see the permuted
key space.
"""

from __future__ import annotations

import os
import time
from typing import Any, Iterable, Sequence

import numpy as np

import jax

from xflow_tpu.chaos import failpoint
from xflow_tpu.config import Config
from xflow_tpu.io.batch import Batch, pad_batch_rows, remap_batch
from xflow_tpu.obs import NULL_OBS
from xflow_tpu.parallel.mesh import make_mesh, replicated, table_sharding

DEFAULT_BUCKETS = (1, 8, 64, 512)
# default top-k compile width for retrieval engines (attach_item_index):
# the executable is compiled ONCE for this k (capped at the index
# size); smaller request ks slice the result on the host, so mixed-k
# traffic never compiles
DEFAULT_TOPK = 16


def _slice_rows(batch: Batch, start: int, stop: int) -> Batch:
    return Batch(
        keys=batch.keys[start:stop],
        slots=batch.slots[start:stop],
        vals=batch.vals[start:stop],
        mask=batch.mask[start:stop],
        labels=batch.labels[start:stop],
        weights=batch.weights[start:stop],
        hot_keys=batch.hot_keys[start:stop],
        hot_slots=batch.hot_slots[start:stop],
        hot_vals=batch.hot_vals[start:stop],
        hot_mask=batch.hot_mask[start:stop],
    )


class PredictEngine:
    """Compiled, bucketed predict over a frozen (or live) model state.

    Construct directly from an in-memory state (the ``XFlow.predict_batch``
    path wraps the live trainer state this way) or via ``load`` from an
    exported artifact.  ``state`` may be a full training state — it is
    stripped to param-only tables so the compiled executables never
    carry optimizer aux arrays.
    """

    def __init__(
        self,
        cfg: Config,
        state: dict[str, Any],
        remap: np.ndarray | None = None,
        mesh=None,
        buckets: Sequence[int] | None = None,
        obs=None,
        digest: str | None = None,
        warm: bool = False,
    ):
        from xflow_tpu.models import make_model
        from xflow_tpu.parallel.step import TrainStep

        self.cfg = cfg
        self.digest = digest if digest is not None else cfg.digest()
        self.mesh = mesh if mesh is not None else make_mesh(1)
        ndev = self.mesh.devices.size
        if cfg.table_size % ndev:
            raise ValueError(
                f"table_size {cfg.table_size} not divisible by the "
                f"serving mesh's {ndev} devices"
            )
        if cfg.hot_size_log2 and remap is None:
            raise ValueError(
                "model was trained with a hot table but no remap was "
                "provided — raw request keys cannot be translated"
            )
        self.model = make_model(cfg)
        # The predict path never touches the optimizer; TrainStep is
        # reused purely for its wire/gather/logit machinery.  Serving
        # pins the dictionary wire OFF (Config.wire_dedup is a
        # training-feed lever): its plane capacities are content-sized
        # (io/compact.py plane_cap), which would key the AOT executable
        # cache on per-request nnz totals and break the
        # one-compile-per-bucket guarantee compile_count enforces.
        # Request batches are tiny — the plain compact wire is already
        # ~free at serving sizes; the hot-impl platform pick (ops/hot.py)
        # still applies to the featurize->predict path.  The override
        # rides the STEP's config copy (self.cfg — the artifact's
        # digest-locked identity — is untouched) so a wire_dedup='on'
        # training config still serves on any mesh, where TrainStep's
        # single-device eligibility check would otherwise refuse it.
        # store_mode is pinned to 'dense' the same way: a tiered
        # artifact is exported as the FOLDED logical [T, D] table
        # (serve/artifact.py), so serving always sees a dense store —
        # and must not build the trainer's hot tier / cold store /
        # promotion worker.
        self.step = TrainStep(
            self.model,
            None,
            cfg.replace(wire_dedup="off", store_mode="dense"),
            self.mesh,
        )
        self.remap = remap
        self.obs = obs if obs is not None else NULL_OBS
        self.step.obs = self.obs
        # Bucket sizes must divide over the mesh's batch axis: round
        # each up to a multiple of ndev, dedupe, sort.
        raw = tuple(buckets) if buckets else DEFAULT_BUCKETS
        if any(b < 1 for b in raw):
            raise ValueError(f"bucket sizes must be >= 1, got {raw}")
        self.buckets = tuple(
            sorted({-(-b // ndev) * ndev for b in raw})
        )
        self.state = self._strip_state(state)
        # Servable identity for the continuous-training delta chain
        # (docs/CONTINUOUS.md): (config digest, train step), shared
        # with full exports and deltas via serve/artifact.py::
        # servable_digest — apply_delta refuses a delta whose base is
        # not this.  Resolved LAZILY: the step scalar's device_get
        # would otherwise serialize every live-state update_state()
        # (XFlow.predict_batch calls it per batch) against pending
        # dispatch.
        self._servable_step: int | None = None
        # AOT executables keyed by (batch_rows, cold_nnz, hot_nnz) —
        # canonical traffic only ever sees len(buckets) keys.  The dict
        # may be SHARED across ``clone()`` replicas: executables are
        # immutable once built, so ``compile_count`` is derived from it
        # and counts compiles fleet-wide, exactly what the
        # no-recompile-under-any-traffic guarantee wants to watch.
        self._compiled: dict[tuple[int, int, int], Any] = {}
        # retrieval-leg jit bindings (TrainStep idiom): _run_aot lowers
        # THESE per bucket into _compiled (never retraces at serve
        # time), and the explicit binding makes the impls visible to
        # the static memory pass (shapeflow jit-entry discovery →
        # XF014 budgets in memory-budget.json)
        self.topk_jit = jax.jit(self._topk_impl)
        self.item_embed_jit = jax.jit(self._item_embed_impl)
        self.warm_seconds = 0.0
        self._parse_fn = None
        # serve-time item index (retrieval families, docs/SERVING.md
        # "Retrieval→ranking cascade"): attached by ``load`` from the
        # artifact's item_index.* files or by ``attach_item_index``;
        # ``topk`` refuses until one is attached
        self.item_index: dict | None = None
        self._index_arr = None
        self.topk_k = 0
        # per-call device split (ISSUE 16): {"h2d": s, "execute": s}
        # of the LAST prepared call.  Written and read on the one
        # batcher worker thread that drives this engine clone, so the
        # batch span (obs/reqtrace.py) can carve its device phase
        # without a lock.
        self.last_device_phases: dict | None = None
        if warm:
            self.warm()

    @property
    def compile_count(self) -> int:
        return len(self._compiled)

    @property
    def servable_step(self) -> int:
        """Train step of the served state (one cached scalar fetch,
        booked — XF002)."""
        if self._servable_step is None:
            with self.obs.phase("serve_state_sync"):
                self._servable_step = int(
                    jax.device_get(self.state["step"])
                )
        return self._servable_step

    @servable_step.setter
    def servable_step(self, step: int) -> None:
        self._servable_step = int(step)

    @property
    def servable_digest(self) -> str:
        """Identity of the model VERSION being served — (config digest,
        train step), the continuous-training chain anchor
        (serve/artifact.py::servable_digest).  Distinct from
        ``digest``: that is the config/geometry identity (unchanged by
        a delta), this advances with every applied refresh."""
        from xflow_tpu.serve.artifact import servable_digest

        return servable_digest(self.digest, self.servable_step)

    def apply_delta(self, directory: str) -> "PredictEngine":
        """Fold an incremental delta export (stream/delta.py) onto
        this servable; returns a NEW engine at the delta's step with
        shared AOT executables (zero recompiles) — this engine keeps
        serving untouched, so fleets canary the result through the
        staged-rollout gate before committing traffic to it."""
        from xflow_tpu.stream.delta import apply_delta

        return apply_delta(self, directory)

    # -- construction ------------------------------------------------------

    @classmethod
    def load(
        cls,
        directory: str,
        config: Config | None = None,
        num_devices: int = 1,
        buckets: Sequence[int] | None = None,
        obs=None,
        warm: bool = True,
        topk_k: int | None = None,
    ) -> "PredictEngine":
        """Load an exported artifact.  ``config``, when given, is the
        caller's expectation: its digest must equal the artifact's or
        the load is refused (never score through the wrong model).
        ``num_devices`` sizes the serving mesh (default 1 — the lean
        scoring tier; the row-range shard files assemble onto any
        mesh).  An item index beside the artifact (export_item_index)
        is attached automatically, arming the ``topk`` mode compiled
        for ``topk_k`` results (default ``DEFAULT_TOPK``, capped at
        the index size)."""
        from xflow_tpu.serve.artifact import (
            REMAP_FILE,
            load_item_index,
            load_manifest,
        )
        from xflow_tpu.utils.checkpoint import RangeReader

        # chaos site: artifact-load fault — the manifest/digest refusal
        # chain below is what it exercises (XF018)
        failpoint("artifact.load")
        manifest = load_manifest(directory)
        cfg = Config.from_json(manifest["config"])
        digest = manifest["config_digest"]
        if config is not None and config.digest() != digest:
            raise ValueError(
                f"artifact {directory} was exported from config "
                f"{digest}, but the expected config digests to "
                f"{config.digest()} — refusing to serve a mismatched "
                "model"
            )
        mesh = make_mesh(num_devices)
        sharding = table_sharding(mesh)
        import jax.numpy as jnp

        from xflow_tpu.models import make_model

        tables: dict[str, Any] = {}
        for spec in make_model(cfg).tables():
            key = f"{spec.name}.param"
            meta = manifest["arrays"].get(key)
            if meta is None:
                raise ValueError(f"artifact {directory} missing {key}")
            shape = tuple(meta["shape"])
            reader = RangeReader(
                directory, key, shape, np.dtype(meta["dtype"])
            )
            tables[spec.name] = {
                "param": jax.make_array_from_callback(
                    shape, sharding, reader.read
                )
            }
        dense: dict[str, Any] = {}
        for dname in manifest.get("dense", []):
            host = np.load(os.path.join(directory, f"dense.{dname}.npy"))
            dense[dname] = jax.device_put(host, replicated(mesh))
        remap = None
        if manifest.get("remap"):
            remap = np.load(os.path.join(directory, REMAP_FILE))
        state = {
            "tables": tables,
            "dense": dense,
            "step": jnp.asarray(manifest["step"], jnp.int32),
        }
        engine = cls(
            cfg,
            state,
            remap=remap,
            mesh=mesh,
            buckets=buckets,
            obs=obs,
            digest=digest,
            warm=False,  # warm AFTER the index attach so topk buckets warm too
        )
        index = load_item_index(directory)
        if index is not None:
            engine.attach_item_index(index, topk_k=topk_k)
        if warm:
            engine.warm()
        return engine

    def clone(self) -> "PredictEngine":
        """A replica view over the SAME weights and the SAME compiled
        executables — how serve/fleet.py fans one loaded artifact out
        to N replicas without paying N× the XLA compiles or N× the
        table HBM.

        What is shared: ``state`` (device arrays — immutable on the
        predict path), ``_compiled`` (AOT executables are immutable
        once built; a rare concurrent non-canonical-shape miss at worst
        compiles twice and last-write-wins), mesh, remap, digest.  What
        is NOT shared: the ``TrainStep`` wire machinery — ``put_batch``
        keeps per-instance host staging, and each fleet replica is
        driven by its own MicroBatcher worker thread, so sharing the
        step would race."""
        replica = PredictEngine(
            self.cfg,
            self.state,
            remap=self.remap,
            mesh=self.mesh,
            buckets=self.buckets,
            obs=self.obs,
            digest=self.digest,
            warm=False,
        )
        replica.state = self.state  # share, don't re-strip-copy
        replica._compiled = self._compiled
        replica._servable_step = self._servable_step
        replica.warm_seconds = self.warm_seconds
        # item index: host planes and the device scan operand are
        # immutable once attached — shared like the weights
        replica.item_index = self.item_index
        replica._index_arr = self._index_arr
        replica.topk_k = self.topk_k
        return replica

    @staticmethod
    def _strip_state(state: dict[str, Any]) -> dict[str, Any]:
        """Param-only view of a (possibly full training) state: the
        compiled executables should never ship FTRL n/z."""
        return {
            "tables": {
                name: {"param": t["param"]}
                for name, t in state["tables"].items()
            },
            "dense": state["dense"],
            "step": state["step"],
        }

    def update_state(self, state: dict[str, Any]) -> None:
        """Swap in newer weights (same shapes/shardings — e.g. the live
        trainer state after more steps).  The AOT executables take the
        state as an argument, so no recompilation happens."""
        self.state = self._strip_state(state)
        self._servable_step = None  # re-resolve lazily on next use

    # -- warmup / compilation ----------------------------------------------

    def warm(self) -> float:
        """Compile every bucket now (one all-padding batch each) so the
        first real request never pays an XLA compile; returns and
        records the warmup seconds.  Retrieval engines (item index
        attached) warm the top-k executables the same way — after
        warm, ``compile_count`` covers BOTH modes and must stay there
        under any single-row/top-k traffic mix."""
        t0 = time.perf_counter()
        for b in self.buckets:
            self.predict(self._empty_batch(b))
            if self._index_arr is not None:
                self.topk(self._empty_batch(b))
        self.warm_seconds = time.perf_counter() - t0
        return self.warm_seconds

    def _empty_batch(self, rows: int) -> Batch:
        k = self.cfg.max_nnz
        return Batch(
            keys=np.zeros((rows, k), np.int32),
            slots=np.zeros((rows, k), np.int32),
            vals=np.zeros((rows, k), np.float32),
            mask=np.zeros((rows, k), np.float32),
            labels=np.zeros(rows, np.float32),
            weights=np.zeros(rows, np.float32),
        )

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (the largest bucket for oversized
        requests — predict() chunks those)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    # -- featurize ---------------------------------------------------------

    def featurize_raw(self, rows: Sequence) -> Batch:
        """Build a RAW-key-space Batch from single-row requests —
        feed it to ``predict`` (which remaps/pads).  Each row is either
        a 1-D key array or a ``(keys, slots, vals)`` tuple (slots/vals
        may be None → 0 / 1.0, the hash-mode convention).  Features
        beyond ``max_nnz`` are truncated, like the training loader."""
        n = len(rows)
        k = self.cfg.max_nnz
        keys = np.zeros((n, k), np.int32)
        slots = np.zeros((n, k), np.int32)
        vals = np.zeros((n, k), np.float32)
        mask = np.zeros((n, k), np.float32)
        for i, row in enumerate(rows):
            if isinstance(row, tuple):
                rk, rs, rv = row
            else:
                rk, rs, rv = row, None, None
            rk = np.asarray(rk)
            m = min(len(rk), k)
            keys[i, :m] = rk[:m]
            if rs is not None:
                slots[i, :m] = np.asarray(rs)[:m]
            vals[i, :m] = 1.0 if rv is None else np.asarray(rv)[:m]
            mask[i, :m] = 1.0
        return Batch(
            keys=keys, slots=slots, vals=vals, mask=mask,
            labels=np.zeros(n, np.float32),
            weights=np.ones(n, np.float32),
        )

    def featurize(self, rows: Sequence) -> Batch:
        """``featurize_raw`` + prepare (remap/steer) + pad to the
        covering bucket: the Batch is ready for ``predict_prepared``
        (the batcher's featurize leg).  ``rows`` must fit the largest
        bucket — callers with bigger batches use ``predict``, which
        chunks.  Never feed the result to ``predict``: that would
        apply the remap twice."""
        n = len(rows)
        if n > self.buckets[-1]:
            raise ValueError(
                f"featurize: {n} rows exceed the largest bucket "
                f"{self.buckets[-1]} — use predict(featurize_raw(rows))"
            )
        return pad_batch_rows(
            self._prepare(self.featurize_raw(rows)), self.bucket_for(n)
        )

    def score_text(self, lines: Iterable[str]) -> np.ndarray:
        """pctr for libffm-format text lines (``label\\tfgid:fid:val``,
        label ignored) — the CLI ``score`` and C-ABI ``XFEngineScore``
        featurize path.  Uses the training parse fn (same hashing/seed,
        from the artifact config) but NO ShardLoader."""
        from xflow_tpu.io.batch import pack_batch
        from xflow_tpu.io.loader import make_parse_fn

        if self._parse_fn is None:
            cfg = self.cfg
            self._parse_fn = make_parse_fn(
                cfg.table_size,
                cfg.hash_mode,
                cfg.seed,
                prefer_native=cfg.native_parser,
            )
        data = "".join(
            line if line.endswith("\n") else line + "\n" for line in lines
        ).encode()
        block = self._parse_fn(data)
        n = block.num_samples
        if n == 0:
            return np.zeros(0, np.float32)
        out = []
        cap = self.buckets[-1]
        for s in range(0, n, cap):
            e = min(s + cap, n)
            raw = pack_batch(block, s, e, e - s, self.cfg.max_nnz)
            out.append(self.predict(raw))
        return np.concatenate(out)

    # -- retrieval: item index + top-k --------------------------------------

    def attach_item_index(
        self, index: dict, topk_k: int | None = None
    ) -> None:
        """Arm the top-k mode with an item-embedding index
        (serve/artifact.py::load_item_index's dict, or any dict with
        ``item_index`` [N, D] / ``item_ids`` [N] plus the feature
        planes).  The scan operand goes to the device once,
        replicated; ``topk_k`` fixes the compiled result width
        (DEFAULT_TOPK, capped at N)."""
        from xflow_tpu.parallel.mesh import replicated

        if not hasattr(self.model, "user_embed"):
            raise ValueError(
                f"model {self.cfg.model!r} has no user tower "
                "(models/__init__.py registry: retrieval=False) — "
                "top-k retrieval needs a two-tower-factored family"
            )
        emb = np.asarray(index["item_index"], np.float32)
        if emb.ndim != 2 or not len(emb):
            raise ValueError(
                f"item index must be [N, index_dim], got {emb.shape}"
            )
        want = getattr(self.model, "index_dim", None)
        if want is not None and emb.shape[1] != want:
            raise ValueError(
                f"item index rows are {emb.shape[1]} wide but model "
                f"{self.cfg.model!r} scans {want} lanes (tower_dim "
                f"{self.cfg.tower_dim} + 2 bias lanes) — the index was "
                "exported from a different tower geometry; re-run "
                "export_item_index"
            )
        # own copy + the precomputed id sort order: the cascade's
        # per-request id->row resolution must not pay an O(N log N)
        # argsort over the catalog on the retrieval worker thread
        self.item_index = dict(index)
        self.item_index["ids_order"] = np.argsort(
            np.asarray(index["item_ids"]), kind="stable"
        )
        self._index_arr = jax.device_put(emb, replicated(self.mesh))
        self.topk_k = min(
            topk_k if topk_k is not None else DEFAULT_TOPK, len(emb)
        )
        if self.topk_k < 1:
            raise ValueError("topk_k must be >= 1")

    def _topk_impl(self, state, index, arrays):
        """User-tower pass + dot-product scan + device top-k — the
        whole retrieval scoring path as ONE jitted program (AOT per
        bucket like predict).  ``index`` [N, D] rides as an argument,
        so a rollout's new index needs zero recompiles."""
        batch = self.step._expand_wire(arrays)
        for k in ("cold_uidx", "cold_tail_keys", "cold_dict_keys"):
            batch.pop(k, None)  # no scatter to plan for
        rows = self.step._gather_model_rows(state["tables"], batch)
        u = self.model.user_embed(
            rows, self.step._model_view(batch), state["dense"]
        )  # [B, D]
        scores = u @ index.T  # [B, N]
        vals, idx = jax.lax.top_k(scores, self.topk_k)
        return vals, idx, u

    def _item_embed_impl(self, state, arrays):
        """Item-tower pass [B, D] — export_item_index's batch leg."""
        batch = self.step._expand_wire(arrays)
        for k in ("cold_uidx", "cold_tail_keys", "cold_dict_keys"):
            batch.pop(k, None)
        rows = self.step._gather_model_rows(state["tables"], batch)
        return self.model.item_embed(
            rows, self.step._model_view(batch), state["dense"]
        )

    def _run_aot(self, tag: str, jitted, batch: Batch, extra=()):
        """Compile-once-per-bucket execution shared by the topk and
        item-embed legs (predict_prepared keeps its own body — its
        multi-host gather and compact re-validation don't apply
        here).  ``extra`` arrays ride as leading executable arguments
        after state."""
        key = (tag, self.topk_k, batch.batch_size, batch.max_nnz,
               batch.hot_nnz)
        t_call = time.perf_counter()
        arrays = self.step.put_batch(batch, predict=True)
        t_h2d = time.perf_counter()
        exe = self._compiled.get(key)
        if exe is None:
            with self.obs.phase("serve_compile"):
                exe = jitted.lower(
                    self.state, *extra, arrays
                ).compile()
            self._compiled[key] = exe
            self.obs.counter("serve.compiles")
        with self.obs.phase("serve_execute"):
            out = exe(self.state, *extra, arrays)
            out = jax.tree.map(
                lambda a: np.asarray(jax.device_get(a)), out
            )
        self.last_device_phases = {
            "h2d": t_h2d - t_call,
            "execute": time.perf_counter() - t_h2d,
        }
        if self.obs.flight is not None:
            self.obs.flight.note_serve(f"{tag}:b{batch.batch_size}")
        return out

    def topk_prepared(
        self, batch: Batch
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(item_ids [B, k], scores [B, k], user_emb [B, D]) for one
        already-prepared bucket-sized batch — the batcher's top-k leg.
        ``user_emb`` is returned so parity checks (the cascade smoke
        gate's numpy full-scan argsort) can verify the device scan
        independently."""
        if self._index_arr is None:
            raise ValueError(
                "top-k refused: no item index attached — export one "
                "with serve.artifact.export_item_index (retrieval "
                "families only) or attach_item_index(...)"
            )
        vals, idx, u = self._run_aot(
            "topk", self.topk_jit, batch, extra=(self._index_arr,)
        )
        ids = self.item_index["item_ids"][idx]
        return ids, vals, u

    def topk(
        self, batch: Batch, k: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """(item_ids [B, k], scores [B, k]) for an externally built
        raw-key-space batch of USER-side features.  Any batch size
        (pad/chunk like ``predict``); any ``k <= topk_k`` slices the
        one compiled result width — mixed-k traffic never compiles."""
        kk = self.topk_k if k is None else int(k)
        if kk < 1 or kk > self.topk_k:
            raise ValueError(
                f"k={kk} outside (0, topk_k={self.topk_k}] — the "
                "engine compiles ONE top-k width; raise topk_k at "
                "load/attach time for deeper candidate sets"
            )
        n = batch.batch_size
        batch = self._prepare(batch)
        cap = self.buckets[-1]
        ids_out, score_out = [], []
        for s in range(0, n, cap):
            e = min(s + cap, n)
            chunk = pad_batch_rows(
                _slice_rows(batch, s, e), self.bucket_for(e - s)
            )
            ids, vals, _ = self.topk_prepared(chunk)
            ids_out.append(ids[: e - s, :kk])
            score_out.append(vals[: e - s, :kk])
        return np.concatenate(ids_out), np.concatenate(score_out)

    def item_embeddings(self, rows: Sequence) -> np.ndarray:
        """Item-tower embeddings [len(rows), model.index_dim] (the
        tower_dim core lanes + the two bias-augmentation lanes,
        models/two_tower.py) for featurize_raw-protocol catalog rows —
        export_item_index's compute leg, bucket-chunked through the
        same AOT path."""
        if not hasattr(self.model, "item_embed"):
            raise ValueError(
                f"model {self.cfg.model!r} has no item tower "
                "(registry: retrieval=False)"
            )
        cap = self.buckets[-1]
        out = []
        for s in range(0, len(rows), cap):
            chunk = rows[s : s + cap]
            b = pad_batch_rows(
                self._prepare(self.featurize_raw(chunk)),
                self.bucket_for(len(chunk)),
            )
            emb = self._run_aot("item_embed", self.item_embed_jit, b)
            out.append(emb[: len(chunk)])
        return np.concatenate(out)

    # -- predict -----------------------------------------------------------

    def _prepare(self, batch: Batch) -> Batch:
        """Canonicalize an external raw-key-space batch: widen the cold
        section so the total feature width matches the training
        geometry (narrower batches get zero-mask columns — no new
        compile shapes), then apply the hot remap + steering.

        Batches WIDER than the training geometry keep their width
        (truncating would silently drop features the training path
        kept) and compile one extra executable per distinct width —
        counted in ``serve.noncanonical_shape``.  The batcher/featurize
        tier only ever produces canonical widths, so the no-recompile
        guarantee holds for serving traffic; a direct ``predict``
        caller who wants it too must match ``cfg.max_nnz``."""
        cfg = self.cfg
        if batch.hot_nnz and not cfg.hot_size:
            raise ValueError(
                "batch carries hot planes but the model has no hot table"
            )
        total = batch.hot_nnz + batch.max_nnz
        if total > cfg.max_nnz:
            self.obs.counter("serve.noncanonical_shape")
        if total < cfg.max_nnz:
            pad = cfg.max_nnz - total
            b = batch.batch_size
            z_i = np.zeros((b, pad), np.int32)
            z_f = np.zeros((b, pad), np.float32)
            batch = Batch(
                keys=np.concatenate([batch.keys, z_i], axis=1),
                slots=np.concatenate([batch.slots, z_i], axis=1),
                vals=np.concatenate([batch.vals, z_f], axis=1),
                mask=np.concatenate([batch.mask, z_f], axis=1),
                labels=batch.labels,
                weights=batch.weights,
                hot_keys=batch.hot_keys,
                hot_slots=batch.hot_slots,
                hot_vals=batch.hot_vals,
                hot_mask=batch.hot_mask,
            )
        return remap_batch(batch, self.remap, cfg.hot_size, cfg.hot_nnz)

    def predict(self, batch: Batch) -> np.ndarray:
        """pctr for one externally built Batch (raw hash key space —
        the remap is applied here).  Any batch size: rows pad up to the
        smallest covering bucket; oversized batches chunk by the
        largest bucket.  Returns exactly ``batch.batch_size`` values."""
        n = batch.batch_size
        batch = self._prepare(batch)
        cap = self.buckets[-1]
        if n <= cap:
            padded = pad_batch_rows(batch, self.bucket_for(n))
            return self.predict_prepared(padded)[:n]
        out = []
        for s in range(0, n, cap):
            e = min(s + cap, n)
            chunk = pad_batch_rows(
                _slice_rows(batch, s, e), self.bucket_for(e - s)
            )
            out.append(self.predict_prepared(chunk)[: e - s])
        return np.concatenate(out)

    def predict_prepared(self, batch: Batch) -> np.ndarray:
        """Run one already-prepared, bucket-sized batch on the device;
        returns pctr for every row (padding included).  This is the
        'device' leg of the batcher's latency accounting: h2d +
        execute + fetch."""
        key = (batch.batch_size, batch.max_nnz, batch.hot_nnz)
        if self.step.compact_wire:
            # TrainStep validates compact-wire invariants only on its
            # FIRST batch (fine for uniform loader traffic); serving
            # traffic is heterogeneous, so a value-carrying request
            # after warmup would otherwise have its vals silently
            # replaced by 1.0 — validate every batch (O(B·K) numpy,
            # noise next to the device call at serving batch sizes).
            from xflow_tpu.parallel.step import validate_compact_batch

            validate_compact_batch(batch)
        t_call = time.perf_counter()
        arrays = self.step.put_batch(batch)  # books the 'h2d' phase
        t_h2d = time.perf_counter()
        exe = self._compiled.get(key)
        if exe is None:
            with self.obs.phase("serve_compile"):
                exe = (
                    jax.jit(self.step._predict_impl)
                    .lower(self.state, arrays)
                    .compile()
                )
            self._compiled[key] = exe
            self.obs.counter("serve.compiles")
        with self.obs.phase("serve_execute"):
            garr = exe(self.state, arrays)
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                garr = multihost_utils.global_array_to_host_local_array(
                    garr, self.mesh, self.step._bsharding.spec
                )
            out = np.asarray(jax.device_get(garr))
        self.last_device_phases = {
            "h2d": t_h2d - t_call,
            "execute": time.perf_counter() - t_h2d,
        }
        if self.obs.flight is not None:
            # serve-channel heartbeat (obs/flight.py): one device call
            # completed — the watchdog's "is scoring moving?" signal,
            # tagged with the bucket it ran in (forensics for "which
            # shape was in flight when serving wedged")
            self.obs.flight.note_serve(f"execute:b{key[0]}")
        return out
