"""Micro-batching request queue — single-row scoring at device-batch
efficiency.

Online CTR traffic arrives as independent single-row requests, but the
device wants bucketed batches (serve/engine.py).  The MicroBatcher
bridges them: requests enqueue with a timestamp; a worker thread
coalesces everything that arrives within a ``max_wait_ms`` deadline
(capped at the engine's largest bucket) into ONE featurize + ONE
bucketed device call, then resolves each request's Future.  Tail
latency is bounded by ``max_wait_ms`` + one device call; throughput
approaches the bucketed batch rate as concurrency rises.

Latency accounting (ISSUE 2): per-request queue (enqueue→dequeue),
featurize (request→Batch assembly), and device (h2d+execute+fetch)
seconds land in obs registry histograms; ``emit_stats``/``close``
flush a ``serve_stats`` JSONL row (obs/schema.py) with p50/p99 per
phase and the coalescing ratio.

Hot swap: ``swap(new_engine)`` atomically replaces the engine between
batches — the in-flight batch finishes on the old one, the next batch
scores on the new one; zero dropped or mixed requests.  Digest-guarded:
a replacement exported from a different config is refused unless
``force=True`` (rolling out a new model GEOMETRY is a redeploy, not a
hot swap)."""

from __future__ import annotations

import queue
import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future
from typing import Any

import numpy as np

from xflow_tpu.chaos import failpoint
from xflow_tpu.obs.registry import MetricsRegistry, Snapshot

_STOP = object()


def stats_row_from_snapshot(snap: Snapshot) -> dict:
    """Build a ``serve_stats`` record body from one registry snapshot.

    Shared by ``MicroBatcher.emit_stats`` (one batcher, its own
    registry) and ``serve/fleet.py`` (N batchers pooling ONE registry —
    the fleet snapshots once and owns the row, so per-replica resets
    never tear the window)."""

    def pct(name: str, p: str) -> float:
        return round(snap.hists.get(name, {}).get(p, 0.0), 6)

    return {
        "requests": int(snap.counters.get("serve.requests", 0)),
        "batches": int(snap.counters.get("serve.batches", 0)),
        "swaps": int(snap.counters.get("serve.swaps", 0)),
        "shed_total": int(snap.counters.get("serve.shed_total", 0)),
        "batch_fill_mean": round(
            snap.hists.get("serve.batch_size", {}).get("mean", 0.0), 3
        ),
        "queue_p50": pct("serve.queue_seconds", "p50"),
        "queue_p99": pct("serve.queue_seconds", "p99"),
        "featurize_p50": pct("serve.featurize_seconds", "p50"),
        "featurize_p99": pct("serve.featurize_seconds", "p99"),
        "device_p50": pct("serve.device_seconds", "p50"),
        "device_p99": pct("serve.device_seconds", "p99"),
    }


class MicroBatcher:
    def __init__(
        self,
        engine,
        max_wait_ms: float = 2.0,
        max_batch: int | None = None,
        registry: MetricsRegistry | None = None,
        metrics_logger=None,
        flight=None,
        emit_on_close: bool = True,
        topk: bool = False,
        cache=None,
    ):
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        self._engine = engine
        # hot-key score cache (serve/scache.py): the worker INSERTS
        # scored rows here, keyed by the scoring engine's OWN servable
        # digest — bitwise-correct by construction (the cached value IS
        # what that engine returned).  Lookups happen upstream
        # (serve/fleet.py submit); the cache itself digest-guards
        # inserts, so a batch scored on a pre-rollout engine that
        # resolves after the commit is dropped, not cached stale.
        # topk batchers never cache (tuple results, not scalar pctrs).
        self._cache = None if topk else cache
        # top-k mode (retrieval fleets, docs/SERVING.md cascade): the
        # worker coalesces exactly like score mode but runs the
        # engine's topk leg; each Future resolves to (item_ids [k],
        # scores [k]) instead of a float.  One batcher serves ONE mode
        # — a cascade runs a topk retrieval fleet in front of a score
        # ranking fleet, so modes never mix inside a coalesced batch.
        self._topk = topk
        if topk and getattr(engine, "topk_k", 0) < 1:
            raise ValueError(
                "topk batcher needs an engine with an item index "
                "attached (PredictEngine.attach_item_index)"
            )
        # obs/flight.py heartbeat sink: one note_serve per coalesced
        # batch; a watchdog with set_pending("serve", self.pending)
        # then classifies silence-with-backlog as serve_queue_stall
        self._flight = flight
        self._busy = False
        self._max_wait = max_wait_ms / 1000.0
        self._max_batch = (
            max_batch if max_batch is not None else engine.buckets[-1]
        )
        if self._max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        # a coalesced batch must fit the engine's largest bucket
        # (featurize pads onto ONE bucket, it never chunks)
        self._max_batch = min(self._max_batch, engine.buckets[-1])
        self.registry = registry if registry is not None else MetricsRegistry()
        self.metrics_logger = metrics_logger
        # False when the registry is pooled across replicas
        # (serve/fleet.py): the fleet snapshots ONCE and owns the final
        # serve_stats row — a per-batcher emit on close would reset the
        # shared window out from under the other replicas
        self._emit_on_close = emit_on_close
        self._q: queue.Queue = queue.Queue()
        # FIFO of (enqueue stamp, trace span|None) mirroring _q
        # (admission-control feed): submit appends under _submit_lock,
        # the worker pops one per dequeued request — depth()/
        # queue_age_s()/oldest_trace() read the backlog without
        # touching the queue internals
        self._enq: deque[tuple[float, Any]] = deque()
        self._swap_lock = threading.Lock()
        self._submit_lock = threading.Lock()
        self._closed = False
        self._final_stats: dict | None = None
        self._drained = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="xflow-serve-batcher", daemon=True
        )
        self._thread.start()

    @property
    def engine(self):
        # under the swap lock for XF008 discipline: every access to the
        # swappable reference goes through one guard.  Callers that
        # need several fields of ONE engine must still capture a single
        # reference (as _run_batch does) — two property reads can
        # legitimately straddle a swap().
        with self._swap_lock:
            return self._engine

    # -- request side ------------------------------------------------------

    def submit(self, keys, slots=None, vals=None, trace=None) -> Future:
        """Enqueue one scoring request (raw hash-space features; vals
        default to 1.0 — the hash-mode convention) and return a Future
        resolving to its pctr.  ``trace`` is an optional opened
        ``obs.reqtrace.RequestSpan``: the worker stamps its
        seal/dequeue/featurize boundaries and completes it when the
        Future resolves (obs/reqtrace.py)."""
        # the closed-check + put is atomic w.r.t. close(), so every
        # accepted request is enqueued BEFORE the _STOP sentinel and is
        # guaranteed to be scored — no Future can sit behind _STOP
        # forever
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            fut: Future = Future()
            t = time.perf_counter()
            if trace is not None:
                trace.t_enq = t
            self._enq.append((t, trace))
            self._q.put(((keys, slots, vals), fut, t, trace))
        return fut

    def score(self, keys, slots=None, vals=None) -> float:
        return float(self.submit(keys, slots, vals).result())

    def pending(self) -> bool:
        """Work is queued or in flight — the watchdog's serve-channel
        gate (an idle batcher's silence is healthy, a backed-up one's
        is a stall).  ``_busy`` is read under the same lock that
        guards its writes (XF008: the watchdog monitor thread calls
        this while the worker flips the flag)."""
        with self._submit_lock:
            busy = self._busy
        return busy or not self._q.empty()

    def depth(self) -> int:
        """Requests accepted but not yet picked up by the worker — the
        admission-control backlog gauge (serve/fleet.py sheds on it).
        Excludes the batch currently in flight; ``pending()`` covers
        that.  Lock-safe: read under the same lock ``submit`` appends
        and the worker pops under."""
        with self._submit_lock:
            return len(self._enq)

    def queue_age_s(self, now: float | None = None) -> float:
        """Seconds the OLDEST still-queued request has waited (0.0 when
        the backlog is empty).  The admission-control deadline gauge: a
        new request admitted now queues behind this one, so its age is
        a floor on the newcomer's queue time."""
        if now is None:
            now = time.perf_counter()
        with self._submit_lock:
            if not self._enq:
                return 0.0
            return now - self._enq[0][0]

    def oldest_trace(self) -> int | None:
        """Trace id of the OLDEST still-queued request (None when the
        backlog is empty or its head request is untraced).  Feeds the
        serve-channel flight heartbeat below, and through it the
        watchdog's ``serve_queue_stall`` health rows — so a flight
        dump names the stuck request, not just the stuck channel."""
        with self._submit_lock:
            if not self._enq:
                return None
            span = self._enq[0][1]
        return span.trace_id if span is not None else None

    def note_shed(self, cause: str) -> None:
        """Book one admission-control rejection against this batcher's
        registry — the shed request never enters the queue, so the
        worker never sees it; the stats row carries the total (the
        per-CAUSE split lives in the fleet's ``serve_shed`` row, the
        one source of by-cause truth)."""
        del cause  # part of the call contract; fleet books the split
        self.registry.counter_add("serve.shed_total")

    # -- lifecycle ---------------------------------------------------------

    def swap(self, engine, force: bool = False) -> None:
        """Atomically replace the serving engine (newer artifact).  The
        in-flight batch completes on the old engine; every later batch
        scores on the new one."""
        with self._swap_lock:
            # digest check INSIDE the lock: two racing swaps must not
            # both pass the check against the same old engine and then
            # install in arbitrary order (XF008 check-then-act)
            if not force and engine.digest != self._engine.digest:
                raise ValueError(
                    f"hot-swap refused: new engine digest {engine.digest} "
                    f"!= serving digest {self._engine.digest} (different "
                    "config/geometry — pass force=True only if you mean it)"
                )
            self._engine = engine
        self.registry.counter_add("serve.swaps")

    def emit_stats(self) -> dict:
        """Snapshot-and-reset the latency window into a ``serve_stats``
        record (logged to the metrics JSONL when a logger is attached);
        returns the record."""
        snap = self.registry.snapshot(reset=True)
        row = stats_row_from_snapshot(snap)
        if self.metrics_logger is not None:
            self.metrics_logger.log("serve_stats", row)
        return row

    def close(self, join_timeout: float = 60.0) -> dict:
        """Drain the queue, stop the worker, flush ONE final
        ``serve_stats`` row; returns it.  Idempotent AND thread-safe:
        concurrent/later closers block on the drain event until the
        first closer has published the final row, so every caller gets
        the same stats (a bare ``first`` flag would let a second closer
        read ``_final_stats`` before the first finished joining).

        The worker join is BOUNDED (XF006): a device call wedged
        mid-batch must not hang close() forever — after
        ``join_timeout`` the leak is surfaced (warning + ``health``
        row for ``obs doctor``) and the stats flush from whatever
        drained."""
        with self._submit_lock:
            first = not self._closed
            if first:
                self._closed = True
                self._q.put(_STOP)
        if first:
            try:
                self._thread.join(timeout=join_timeout)
                if self._thread.is_alive():
                    warnings.warn(
                        "MicroBatcher worker thread outlived its "
                        f"close() join ({join_timeout:.1f}s) — a device "
                        "call is likely wedged; stats below cover only "
                        "what drained",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    if self.metrics_logger is not None:
                        from xflow_tpu.obs.schema import health_row

                        self.metrics_logger.log("health", health_row(
                            cause="serve_worker_leak",
                            channel="serve",
                            silence_seconds=join_timeout,
                            threshold_seconds=join_timeout,
                            detail="worker outlived close() join",
                        ))
                self._final_stats = (
                    self.emit_stats() if self._emit_on_close else {}
                )
            finally:
                # set even on failure: a raising first closer must not
                # leave concurrent closers blocked forever (they fail
                # the assert below instead)
                self._drained.set()
        else:
            # bounded by construction: the FIRST closer sets _drained in
            # a finally even when draining raises, and its own joins are
            # join_timeout-bounded (xf: ignore[XF017])
            self._drained.wait()
        assert self._final_stats is not None
        return self._final_stats

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- worker ------------------------------------------------------------

    def _loop(self) -> None:
        stopping = False
        while not stopping:
            # sentinel-drain worker loop: close() always enqueues _STOP
            # (XF006-gated lifecycle), so the dequeue is never abandoned
            # (xf: ignore[XF017])
            item = self._q.get()
            if item is _STOP:
                return
            # busy from the FIRST dequeue: a request riding the
            # coalescing wait below is in flight even though the queue
            # may be empty — pending() must not read it as idle.  Each
            # dequeued request also retires its enqueue stamp so
            # depth()/queue_age_s() track only the waiting backlog.
            with self._submit_lock:
                self._busy = True
                if self._enq:
                    self._enq.popleft()
            try:
                reqs = [item]
                deadline = time.perf_counter() + self._max_wait
                while len(reqs) < self._max_batch:
                    timeout = deadline - time.perf_counter()
                    if timeout <= 0:
                        # deadline passed: take whatever is already
                        # queued, but don't wait for more
                        timeout = 0.0
                    try:
                        nxt = self._q.get(timeout=timeout) if timeout else (
                            self._q.get_nowait()
                        )
                    except queue.Empty:
                        break
                    if nxt is _STOP:
                        stopping = True
                        break
                    with self._submit_lock:
                        if self._enq:
                            self._enq.popleft()
                    reqs.append(nxt)
                self._run_batch(reqs)
            finally:
                with self._submit_lock:
                    self._busy = False
                if self._flight is not None:
                    # the heartbeat names the oldest still-queued
                    # request (ISSUE 16): the watchdog copies this
                    # detail into serve_queue_stall health rows, so a
                    # stall points at a concrete trace id
                    tid = self.oldest_trace()
                    self._flight.note_serve(
                        "batch" if tid is None
                        else f"batch oldest_trace={tid:016x}"
                    )

    def _run_batch(self, reqs: list) -> None:
        # the batch is SEALED here: no later arrival joins it.  The
        # engine is captured ONCE under the swap lock, so every member
        # scores on one digest — a batch span can never mix trace ids
        # across a rollout swap by construction.
        t_seal = time.perf_counter()
        with self._swap_lock:
            engine = self._engine
        t_deq = time.perf_counter()
        reg = self.registry
        spans = [s for _, _, _, s in reqs if s is not None]
        sink = spans[0].sink if spans else None
        bid = sink.next_batch_id() if sink is not None else None
        for _, _, t_enq, span in reqs:
            reg.observe("serve.queue_seconds", t_deq - t_enq)
            if span is not None:
                span.t_seal = t_seal
                span.t_deq = t_deq
                span.batch_id = bid
                span.digest = engine.digest
        try:
            t0 = time.perf_counter()
            # chaos site: a replica whose scoring raises — the batch's
            # futures resolve with the error (below) and the fleet's
            # eviction policy takes it out of routing (serve/fleet.py)
            failpoint("serve.replica_score")
            batch = engine.featurize([row for row, _, _, _ in reqs])
            t1 = time.perf_counter()
            for span in spans:
                span.t_feat = t1
            if self._topk:
                ids, scores, _ = engine.topk_prepared(batch)
            else:
                pctr = engine.predict_prepared(batch)[: len(reqs)]
            t2 = time.perf_counter()
        except BaseException as e:  # resolve, never wedge the callers
            if sink is not None:
                sink.note_batch(
                    bid,
                    [s.trace_id for s in spans],
                    engine.digest,
                    0,
                    {},
                    status="error",
                )
            for _, fut, _, span in reqs:
                # span first: the error record must exist by the time
                # the caller observes the failed Future
                if span is not None:
                    span.sink.complete(span, "error", detail=repr(e))
                fut.set_exception(e)
            return
        # featurize/device are shared per batch: every coalesced request
        # EXPERIENCED the whole batch's featurize+device wall, so each
        # observes the full value — that is its latency, not an
        # amortized share.
        feat, dev = t1 - t0, t2 - t1
        # featurize padded onto ONE bucket, so the prepared batch's row
        # count IS the bucket that served these requests — the
        # per-bucket e2e histograms (queue+featurize+device) feed the
        # load generator's p50/p99-per-bucket report (serve/loadgen.py)
        bucket = getattr(batch, "batch_size", len(reqs))
        if sink is not None:
            phases = {"featurize": feat, "device": dev}
            # engine's per-call device split (h2d vs execute) — same
            # worker thread, so this is the call we just made
            split = getattr(engine, "last_device_phases", None)
            if split:
                phases.update(split)
            # batch span BEFORE the member resolutions: a caller that
            # saw its result can already find the complete tree
            sink.note_batch(
                bid,
                [s.trace_id for s in spans],
                engine.digest,
                bucket,
                phases,
            )
        cache = self._cache
        cache_digest = (
            getattr(engine, "servable_digest", None)
            if cache is not None and not self._topk
            else None
        )
        for i, (row, fut, t_enq, span) in enumerate(reqs):
            reg.observe("serve.featurize_seconds", feat)
            reg.observe("serve.device_seconds", dev)
            reg.observe(f"serve.e2e.b{bucket}", t2 - t_enq)
            if span is not None:
                span.bucket = bucket
                span.sink.complete(span)
            if cache_digest is not None:
                # insert BEFORE resolving the Future: a caller that
                # saw its score can already hit the cache with it
                cache.insert(cache_digest, *row, float(pctr[i]))
            if self._topk:
                # the scoring engine's index rides along: candidate
                # ids are only meaningful against the index that
                # produced them, and during a rollout canary different
                # replicas serve different indexes — a consumer that
                # read "the fleet's" index instead would resolve ids
                # against the wrong catalog (serve/cascade.py)
                fut.set_result((ids[i], scores[i], engine.item_index))
            else:
                fut.set_result(float(pctr[i]))
        reg.counter_add("serve.requests", len(reqs))
        reg.counter_add("serve.batches", 1.0)
        reg.observe("serve.batch_size", float(len(reqs)))
