"""Retrieval→ranking cascade — candidate generation feeding the ranker.

A production recommender answers "what should we show this user?", not
"what is the pctr of this (user, item) pair?" — the point-scoring tier
(PRs 2/10) is only the second half.  The CascadeEngine composes the
full shape (arXiv:2501.10546, PAPERS.md):

    request (user features, k)
      → admission check at the front door
      → RETRIEVAL fleet: top-k item candidates from the serve-time
        item-embedding index (ReplicaFleet in topk mode over a
        two-tower artifact — one user-tower pass + dot scan)
      → RANKING fleet: point-score each candidate as a full
        user+item feature row (any point-score family; the DCN
        explicit-cross ranker is the built-for-it one)
      → results ranked by pctr

Both stages are ordinary :class:`~xflow_tpu.serve.fleet.ReplicaFleet`
instances — replication, admission control, replica health, and staged
rollout all apply PER STAGE, independently: canary a new ranker while
the retriever serves untouched, or roll the retriever (a new index
rides the artifact) behind an unchanged ranker, each through the
existing digest-guarded canary gate.

Threading: the cascade owns NO threads.  ``submit`` enqueues on the
retrieval fleet and chains completions — the retrieval replica's
worker thread fans the candidates out to the ranking fleet (enqueue
only, never blocking on results), and the LAST ranking completion
resolves the caller's Future.  All mutable cascade state (stats
counters) lives under ``self._lock``, never held across a submit.

Observability: per-stage latency and candidate-count accounting in one
``cascade`` JSONL row per stats window (obs/schema.py) — retrieval
p50/p99 vs ranking p50/p99 so a slow cascade blames the right fleet
(``obs doctor`` reads exactly that), plus candidate starvation
(retrieval returning fewer than the requested k — an index smaller
than k, never silent).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from xflow_tpu.obs.registry import MetricsRegistry
from xflow_tpu.serve.fleet import ReplicaFleet, ShedError


class CascadeEngine:
    def __init__(
        self,
        retrieval: ReplicaFleet,
        ranking: ReplicaFleet,
        k: int = 8,
        metrics_logger=None,
        registry: MetricsRegistry | None = None,
    ):
        if not getattr(retrieval, "topk", False):
            raise ValueError(
                "the retrieval stage must be a top-k fleet "
                "(ReplicaFleet(..., topk=True) over a retrieval "
                "artifact with an item index)"
            )
        if getattr(ranking, "topk", False):
            raise ValueError(
                "the ranking stage must be a point-score fleet, not "
                "top-k"
            )
        cap = retrieval.engines[0].topk_k
        if not 1 <= k <= cap:
            raise ValueError(
                f"cascade k={k} outside [1, retrieval topk_k={cap}] — "
                "the retrieval engines compile ONE top-k width; load "
                "them with a larger topk_k for deeper candidate sets"
            )
        self.retrieval = retrieval
        self.ranking = ranking
        self.k = int(k)
        self.metrics_logger = metrics_logger
        self.registry = registry if registry is not None else MetricsRegistry()
        self._lock = threading.Lock()
        self._requests = 0
        self._errors = 0
        self._shed = 0
        self._starved = 0
        self._closed = False

    # -- request side -------------------------------------------------------

    def _front_door(self) -> None:
        """Admission control BEFORE any stage sees the request: the
        retrieval fleet's own door guards stage 1 inside submit();
        here the RANKING backlog is checked too — admitting a request
        whose k candidate scores would only pile onto a past-deadline
        ranking queue wastes retrieval capacity on work the ranker
        will shed anyway."""
        rk = self.ranking
        depth_cap = rk.policy.depth_budget * len(rk.batchers)
        if rk.depth() >= depth_cap or (
            rk.queue_age_s() > rk.policy.deadline_budget_s
        ):
            with self._lock:
                self._shed += 1
            self.registry.counter_add("cascade.shed")
            raise ShedError(
                "ranking_backlog",
                rk.depth(),
                rk.queue_age_s(),
                f"front door: ranking {rk.policy.describe()} x"
                f"{len(rk.batchers)} replicas",
            )

    def submit(
        self, keys, slots=None, vals=None, k: int | None = None,
        trace=None,
    ) -> Future:
        """One cascade request: USER-side features in the
        featurize_raw protocol; resolves to ``{"items": [k'], "pctr":
        [k'], "retrieval_scores": [k']}`` ranked by pctr descending.
        Raises :class:`ShedError` at the front door (ranking backlog)
        or from the retrieval stage's admission control; ranking-stage
        sheds resolve the Future with the ShedError.

        ``trace`` is an optional ``obs.reqtrace.TraceContext``: ONE
        trace id spans both stages — the retrieval span and every
        candidate's ranking span carry it, so a flushed window shows
        the whole fan-out as one tree (obs/reqtrace.py).  When the
        retrieval fleet traces and no context was carried in, one is
        minted here so in-process cascade callers correlate too."""
        kk = self.k if k is None else int(k)
        if kk < 1:
            raise ValueError(f"k must be >= 1, got {kk}")
        # no upper-bound refusal here: a retrieval rollout can shrink
        # the index/topk width under live traffic — the cascade serves
        # best-effort (fewer candidates than requested) and counts it
        # as starvation instead of failing requests
        with self._lock:
            if self._closed:
                raise RuntimeError("CascadeEngine is closed")
            self._requests += 1
        sink = getattr(self.retrieval, "reqtrace", None)
        if trace is None and sink is not None:
            trace = sink.mint()
        self._front_door()
        t0 = time.perf_counter()
        out: Future = Future()
        try:
            rfut = self.retrieval.submit(keys, slots, vals, trace=trace)
        except ShedError:
            with self._lock:
                self._shed += 1
            self.registry.counter_add("cascade.shed")
            raise
        user_row = (np.asarray(keys), slots, vals)
        rfut.add_done_callback(
            lambda f: self._on_retrieved(f, out, user_row, kk, t0, trace)
        )
        return out

    def recommend(
        self, keys, slots=None, vals=None, k: int | None = None,
        timeout: float | None = 60.0, trace=None,
    ) -> dict:
        return self.submit(keys, slots, vals, k=k, trace=trace).result(
            timeout
        )

    def _fail(self, out: Future, exc: BaseException) -> None:
        with self._lock:
            self._errors += 1
        self.registry.counter_add("cascade.errors")
        out.set_exception(exc)

    def _on_retrieved(
        self, rfut: Future, out: Future, user_row, k: int, t0: float,
        trace=None,
    ) -> None:
        """Stage-1 completion (retrieval replica worker thread): book
        the stage latency, assemble user+candidate ranking rows, fan
        them out to the ranking fleet — enqueue only; stage-2
        completions resolve ``out``."""
        t1 = time.perf_counter()
        err = rfut.exception()
        if err is not None:
            self._fail(out, err)
            return
        self.registry.observe("cascade.retrieval_seconds", t1 - t0)
        # the index rides the result (serve/batcher.py): candidate ids
        # resolve against the EXACT index that produced them — during
        # a retrieval canary, replicas serve different indexes, so
        # reading "the fleet's" index here would mismatch.  rfut is
        # already resolved: this runs in its done-callback after the
        # .exception() check above (xf: ignore[XF017])
        ids, scores, index = rfut.result()
        ids, scores = ids[:k], scores[:k]
        by_id = index["item_ids"]
        # item_ids -> index rows: the precomputed sorted order from
        # attach_item_index (per-request argsort over a production
        # catalog would serialize O(N log N) onto the retrieval
        # worker); ids came FROM this index, but verify the
        # round-trip anyway and drop any mismatch — never silently
        # rank the wrong item's features
        order = index.get("ids_order")
        if order is None:
            order = np.argsort(by_id, kind="stable")
        pos = np.clip(
            np.searchsorted(by_id, ids, sorter=order), 0, len(by_id) - 1
        )
        rows_idx = order[pos]
        ok = by_id[rows_idx] == ids
        if not ok.all():
            ids, scores, rows_idx = ids[ok], scores[ok], rows_idx[ok]
        if len(ids) < k:
            # candidate starvation — an index smaller than k, or
            # round-trip drops: served best-effort, counted loudly
            # (obs doctor's candidate_starvation diagnosis)
            with self._lock:
                self._starved += 1
            self.registry.counter_add("cascade.starved")
        self.registry.observe("cascade.k_returned", float(len(ids)))
        if not len(ids):
            self._fail(out, RuntimeError(
                "retrieval returned zero candidates"
            ))
            return
        ukeys, uslots, uvals = user_row
        n_user = len(ukeys)
        uslots = (
            np.zeros(n_user, np.int32) if uslots is None
            else np.asarray(uslots, np.int32)
        )
        uvals = (
            np.ones(n_user, np.float32) if uvals is None
            else np.asarray(uvals, np.float32)
        )
        pctr = np.zeros(len(ids), np.float32)
        remaining = [len(ids)]
        resolved = [False]  # out resolves exactly once (first error
        rlock = threading.Lock()  # OR last success — never both)

        def resolve_once() -> bool:
            with rlock:
                if resolved[0]:
                    return False
                resolved[0] = True
                return True

        def on_ranked(i: int, fut: Future) -> None:
            rerr = fut.exception()
            if rerr is not None:
                if resolve_once():
                    self._fail(out, rerr)
                return
            # fut is already resolved: done-callback after the
            # .exception() check above (xf: ignore[XF017])
            pctr[i] = fut.result()
            with rlock:
                remaining[0] -= 1
                last = remaining[0] == 0 and not resolved[0]
                if last:
                    resolved[0] = True
            if last:
                t2 = time.perf_counter()
                self.registry.observe("cascade.rank_seconds", t2 - t1)
                self.registry.observe("cascade.e2e_seconds", t2 - t0)
                rank = np.argsort(-pctr, kind="stable")
                out.set_result({
                    "items": [int(ids[j]) for j in rank],
                    "pctr": [round(float(pctr[j]), 6) for j in rank],
                    "retrieval_scores": [
                        round(float(scores[j]), 6) for j in rank
                    ],
                })

        for i, ridx in enumerate(rows_idx):
            m = int(index["item_nnz"][ridx])
            row = (
                np.concatenate([ukeys, index["item_keys"][ridx, :m]]),
                np.concatenate([uslots, index["item_slots"][ridx, :m]]),
                np.concatenate([uvals, index["item_vals"][ridx, :m]]),
            )
            try:
                # same trace id as the retrieval span: the ranking
                # fan-out IS this request's second stage
                rk_fut = self.ranking.submit(*row, trace=trace)
            except (ShedError, RuntimeError) as e:
                with self._lock:
                    self._shed += 1
                self.registry.counter_add("cascade.shed")
                if resolve_once():  # a prior candidate may have failed first
                    self._fail(out, e)
                return
            rk_fut.add_done_callback(
                lambda f, i=i: on_ranked(i, f)
            )

    # -- stats / lifecycle --------------------------------------------------

    def _counters_locked(self) -> dict:
        return {
            "requests": self._requests,
            "errors": self._errors,
            "shed_total": self._shed,
            "starved": self._starved,
        }

    def _row_from(self, counters: dict, snap) -> dict:
        def pct(name: str, p: str) -> float:
            return round(snap.hists.get(name, {}).get(p, 0.0), 6)

        kh = snap.hists.get("cascade.k_returned", {})
        return {
            **counters,
            "k": self.k,
            "k_returned_mean": round(kh.get("mean", 0.0), 3),
            "retrieval_p50": pct("cascade.retrieval_seconds", "p50"),
            "retrieval_p99": pct("cascade.retrieval_seconds", "p99"),
            "rank_p50": pct("cascade.rank_seconds", "p50"),
            "rank_p99": pct("cascade.rank_seconds", "p99"),
            "e2e_p50": pct("cascade.e2e_seconds", "p50"),
            "e2e_p99": pct("cascade.e2e_seconds", "p99"),
        }

    def emit_stats(self) -> dict:
        """Flush one cascade window as a ``cascade`` JSONL row
        (obs/schema.py); window counters reset.  The per-stage fleets
        keep their own serve_stats/serve_shed windows — this row is
        the CROSS-stage view (per-stage latency attribution +
        candidate accounting) those cannot express."""
        snap = self.registry.snapshot(reset=True)
        with self._lock:
            counters = self._counters_locked()
            self._requests = 0
            self._errors = 0
            self._shed = 0
            self._starved = 0
        row = self._row_from(counters, snap)
        if self.metrics_logger is not None:
            self.metrics_logger.log("cascade", row)
        return row

    def stats(self) -> dict:
        """Non-destructive live view (the /v1/stats cascade block)."""
        snap = self.registry.snapshot(reset=False)
        with self._lock:
            counters = self._counters_locked()
        return dict(
            self._row_from(counters, snap),
            retrieval={
                "digest": self.retrieval.digest,
                "replicas": self.retrieval.replicas,
                "depth": self.retrieval.depth(),
                "rollout": self.retrieval.rollout_state(),
                "topk_k": self.retrieval.engines[0].topk_k,
                # shape, not the meta "count" key — attach_item_index
                # accepts bare dicts without export metadata
                "index_items": int(len(
                    self.retrieval.engines[0].item_index["item_index"]
                )),
            },
            ranking={
                "digest": self.ranking.digest,
                "replicas": self.ranking.replicas,
                "depth": self.ranking.depth(),
                "rollout": self.ranking.rollout_state(),
            },
        )

    def pending(self) -> bool:
        return self.retrieval.pending() or self.ranking.pending()

    def close(self) -> dict:
        """Drain both stages (retrieval first — its in-flight
        completions fan out to the ranking queues, which must still
        accept them — then ranking), then flush the final cascade
        window.  Idempotent."""
        with self._lock:
            first = not self._closed
            self._closed = True
        if not first:
            return {}
        self.retrieval.close()
        self.ranking.close()
        return self.emit_stats()

    def __enter__(self) -> "CascadeEngine":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
