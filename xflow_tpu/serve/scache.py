"""Digest-keyed hot-key score cache — the zipf-skew throughput
multiplier in front of the MicroBatcher (ISSUE 20; ROADMAP item 5).

Ads traffic is zipf-shaped: the loadgen models it (serve/loadgen.py
``zipf_rows``) because the real feature stream is dominated by a small
hot set of (user, ad) feature rows.  Scoring is deterministic per
model version, so a row already scored by the CURRENT servable is pure
repeat work — a bounded LRU in front of the batcher turns the hot
set's repeat fraction directly into throughput, at zero device cost.

Correctness contract (the whole point of the design):

* **Keys are (servable_digest, row content).**  The servable digest
  (serve/artifact.py::servable_digest — config digest @ step) advances
  on every committed rollout INCLUDING zero-recompile delta refreshes,
  so a cached score can only ever be returned for the exact model
  version that produced it.  Row content is the raw little-endian
  bytes of (keys, slots, vals) — byte-equality, not a hash, so a
  collision can never serve a wrong score.
* **Inserts are digest-guarded.**  ``set_current(digest)`` pins the
  one digest the cache accepts; an insert carrying any other digest is
  dropped.  This closes the rollout straggler hole: a batch scored on
  the OLD engine that resolves AFTER the commit would otherwise
  re-pollute the cache under a digest that was just evicted.  The
  fleet calls ``set_current`` inside the same critical section that
  swaps ``fleet.servable`` (serve/fleet.py commit/abort), so there is
  no window where lookups and inserts disagree about the current
  version.
* **Invalidation is eviction, not just mis-keying.**  Digest keying
  makes a swap invalidation *by construction* (new lookups miss), but
  the old generation's entries would still occupy LRU capacity until
  traffic churned them out — across repeated rollouts that is a slow
  leak of hit rate, not memory safety.  ``set_current`` therefore
  EXPLICITLY evicts every entry not under the new digest, atomically
  with the pin.

Thread model: one lock around an ``OrderedDict`` (XF008 — every
mutable field behind it); no threads of its own, no blocking calls
under the lock.  Hit/miss/eviction counters are booked both locally
(windowed, flushed into ``serve_stats`` rows by the fleet) and into
the fleet's shared MetricsRegistry (``serve.cache_hit`` /
``serve.cache_miss``), so the `/metrics` exposition exports them
live (obs/export.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np


def row_key(keys, slots, vals) -> tuple:
    """Canonical content key for one featurize_raw-protocol row: the
    raw little-endian bytes of each component (None stays None — a
    defaulted component and an explicit zeros/ones component are
    DIFFERENT keys, which costs a miss, never a wrong hit)."""
    kb = np.asarray(keys).astype("<i8", copy=False).tobytes()
    sb = (
        None if slots is None
        else np.asarray(slots).astype("<i4", copy=False).tobytes()
    )
    vb = (
        None if vals is None
        else np.asarray(vals).astype("<f4", copy=False).tobytes()
    )
    return (kb, sb, vb)


class ScoreCache:
    """Bounded LRU of (servable_digest, row content) -> pctr."""

    def __init__(self, capacity: int, registry=None):
        if capacity < 1:
            raise ValueError("ScoreCache capacity must be >= 1")
        self.capacity = capacity
        self.registry = registry
        self._lock = threading.Lock()
        self._d: OrderedDict[tuple, float] = OrderedDict()
        self._current: str | None = None
        self._bytes = 0
        # window counters (flushed into serve_stats by the fleet)
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._invalidations = 0
        self._inserts_dropped = 0

    @staticmethod
    def _entry_bytes(key: tuple) -> int:
        _, kb, sb, vb = key
        return (
            len(kb)
            + (len(sb) if sb is not None else 0)
            + (len(vb) if vb is not None else 0)
            + 8  # the float score
        )

    def set_current(self, digest: str) -> int:
        """Pin ``digest`` as the one servable version the cache serves
        and accepts; EVICT every entry under any other digest (bounded
        memory across repeated rollouts — see module docstring).
        Returns the number of entries evicted."""
        with self._lock:
            if digest == self._current:
                return 0
            # the FIRST pin (fleet construction) is not an
            # invalidation — only a generation swap is, so doctor's
            # churn check counts rollouts, not fleet starts
            if self._current is not None:
                self._invalidations += 1
            self._current = digest
            stale = [k for k in self._d if k[0] != digest]
            for k in stale:
                self._bytes -= self._entry_bytes(k)
                del self._d[k]
            if stale:
                self._evictions += len(stale)
            return len(stale)

    def lookup(self, digest: str, keys, slots, vals) -> float | None:
        """Cached score for this row under ``digest``, or None.  A
        lookup against a non-current digest always misses (the caller
        read ``fleet.servable`` a beat before a commit landed — the
        miss routes it to the batcher, which scores it on whatever
        engine is then serving: correct either way)."""
        k = (digest, *row_key(keys, slots, vals))
        with self._lock:
            score = self._d.get(k)
            if score is None or digest != self._current:
                self._misses += 1
                hit = False
            else:
                self._d.move_to_end(k)
                self._hits += 1
                hit = True
        if self.registry is not None:
            self.registry.counter_add(
                "serve.cache_hit" if hit else "serve.cache_miss"
            )
        return score if hit else None

    def insert(self, digest: str, keys, slots, vals,
               score: float) -> bool:
        """Insert one scored row; dropped (False) when ``digest`` is
        not the pinned current version — the rollout-straggler guard.
        Evicts LRU entries past capacity."""
        k = (digest, *row_key(keys, slots, vals))
        with self._lock:
            if digest != self._current:
                self._inserts_dropped += 1
                return False
            if k in self._d:
                self._d.move_to_end(k)
                self._d[k] = float(score)
                return True
            self._d[k] = float(score)
            self._bytes += self._entry_bytes(k)
            while len(self._d) > self.capacity:
                old, _ = self._d.popitem(last=False)
                self._bytes -= self._entry_bytes(old)
                self._evictions += 1
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def stats_row(self, reset: bool = True) -> dict:
        """Windowed counters + live gauges for the fleet's
        ``serve_stats`` row (obs/schema.py OPTIONAL fields)."""
        with self._lock:
            hits, misses = self._hits, self._misses
            row = {
                "cache_hits": hits,
                "cache_misses": misses,
                "cache_hit_rate": round(
                    hits / (hits + misses), 6
                ) if (hits + misses) else 0.0,
                "cache_entries": len(self._d),
                "cache_bytes": self._bytes,
                "cache_evictions": self._evictions,
                "cache_invalidations": self._invalidations,
                "cache_inserts_dropped": self._inserts_dropped,
            }
            if reset:
                self._hits = 0
                self._misses = 0
                self._evictions = 0
                self._invalidations = 0
                self._inserts_dropped = 0
            return row
