"""Persistent binary serve transport — the XFB1 pipelined framing
over the ReplicaFleet (ISSUE 20; ROADMAP item 5).

The HTTP/1.1 tier (serve/server.py) pays per-request framing tax:
request line + headers in, status line + headers out, one
request/response in flight per connection.  The auction-scoring tiers
the reference is modeled on (PAPERS.md, arXiv:2501.10546) engineer
that away first — a persistent length-prefixed binary channel with
many requests in flight, matched by id.  This module is that channel:

**Frame layout** (little-endian throughout — analysis rule XF020):

* request  frame: ``b"XFB1"  u32 length  u64 request_id  u8 qos
  body``, where ``length`` counts everything after itself
  (``9 + len(body)``) and ``body`` is a complete XFS1/XFS2 packed
  scoring request (serve/server.py — the SAME body bytes that POST to
  ``/v1/score_packed``, so both transports share one fuzz-hardened
  row codec);
* response frame: ``b"XFB1"  u32 length  u64 request_id  u8 status
  body`` with status ``0`` ok (body = packed pctr response), ``1``
  shed (JSON — the typed-429 body of the HTTP tier), ``2`` timeout
  (JSON), ``3`` error (JSON).  Responses carry the request's id and
  may arrive in ANY order — the client matches, not the stream.

**QoS byte**: ``0`` bidding, ``1`` normal, ``2`` best_effort — the
admission class (serve/fleet.py QOS_CLASSES); anything else is a
typed decode refusal.  The HTTP twin is the ``X-XFlow-QoS`` header.

**Server** (:class:`BinaryTier`): one ``selectors``-based acceptor
thread owns every socket — accepts, reads, frame-parses, submits into
the fleet (admission control included), and writes responses.
Completion callbacks run on replica worker threads; they hand the
encoded response frame to the acceptor through a queue + socketpair
wake, so all socket I/O stays on one thread (no per-connection
threads, no handler-thread pool — the throughput multiplier is
exactly that the transport costs one thread).  Every wait is bounded
(XF017): the selector polls, sockets are non-blocking, and a deadline
sweep answers status-2 (timeout) for any request whose scoring future
outlives ``score_timeout_s`` — the 504 of this wire.  The loop beats
the flight recorder's ``http`` channel and survives the
``serve.binary_accept`` chaos failpoint exactly like the HTTP accept
loop (XF009/XF018).

``close()`` (XF006): stop flag + wake, bounded join of the acceptor,
then every socket closes.  The tier never closes the fleet — it may
share one with an HTTP ServeTier (the CLI runs both); whoever owns
the fleet drains it.

The client half (persistent per-stripe connections, pipelining depth
knob) is :class:`~xflow_tpu.serve.loadgen.BinaryTarget`.
"""

from __future__ import annotations

import json
import queue
import selectors
import socket
import struct
import threading
import time
from typing import Any, Callable

from xflow_tpu.chaos import ChaosError, failpoint
from xflow_tpu.serve.fleet import QOS_CLASSES, ShedError
from xflow_tpu.serve.server import (
    SCORE_TIMEOUT_S,
    SOCKET_TIMEOUT_S,
    decode_packed_request_traced,
    encode_packed_response,
)

FRAME_MAGIC = b"XFB1"
# frame length ceiling: a length-inflation frame must be refused
# before any allocation, not buffered toward OOM (the wirefuzz
# inflation mutator drives this)
MAX_FRAME_BYTES = 64 << 20
# u64 request_id + u8 qos/status
_HEAD = struct.Struct("<QB")
_LEN = struct.Struct("<I")

QOS_BYTE = {"bidding": 0, "normal": 1, "best_effort": 2}
QOS_NAME = {v: k for k, v in QOS_BYTE.items()}
assert set(QOS_BYTE) == set(QOS_CLASSES)

STATUS_OK = 0
STATUS_SHED = 1
STATUS_TIMEOUT = 2
STATUS_ERROR = 3


# -- frame codec --------------------------------------------------------------


def encode_frame(request_id: int, qos: str, body: bytes) -> bytes:
    """One request frame; ``body`` is a complete XFS1/XFS2 blob."""
    if qos not in QOS_BYTE:
        raise ValueError(
            f"unknown QoS class {qos!r} (want one of {QOS_CLASSES})"
        )
    if not 0 <= request_id < (1 << 64):
        raise ValueError(f"request_id {request_id} out of u64 range")
    return (
        FRAME_MAGIC
        + _LEN.pack(_HEAD.size + len(body))
        + _HEAD.pack(request_id, QOS_BYTE[qos])
        + body
    )


def encode_response_frame(
    request_id: int, status: int, body: bytes
) -> bytes:
    if status not in (
        STATUS_OK, STATUS_SHED, STATUS_TIMEOUT, STATUS_ERROR
    ):
        raise ValueError(f"bad response status {status}")
    return (
        FRAME_MAGIC
        + _LEN.pack(_HEAD.size + len(body))
        + _HEAD.pack(request_id, status)
        + body
    )


def _frame_at(buf: bytes, off: int) -> tuple[int, int, bytes, int] | None:
    """Parse one frame at ``off``: (request_id, tag_byte, body,
    next_off), or None when the buffer holds only an incomplete prefix
    of a frame (stream caller: wait for more bytes).  Malformed
    framing (bad magic, out-of-range length) is a typed refusal —
    a pipelined stream cannot resync past garbage."""
    avail = len(buf) - off
    if avail < 8:
        if avail and not FRAME_MAGIC.startswith(buf[off:off + 4]):
            raise ValueError(
                f"bad frame magic {bytes(buf[off:off + 4])!r} "
                f"(want {FRAME_MAGIC!r})"
            )
        return None
    if bytes(buf[off:off + 4]) != FRAME_MAGIC:
        raise ValueError(
            f"bad frame magic {bytes(buf[off:off + 4])!r} "
            f"(want {FRAME_MAGIC!r})"
        )
    (length,) = _LEN.unpack_from(buf, off + 4)
    if length < _HEAD.size or length > MAX_FRAME_BYTES:
        raise ValueError(
            f"frame length {length} outside "
            f"[{_HEAD.size}, {MAX_FRAME_BYTES}]"
        )
    if avail < 8 + length:
        return None
    rid, tag = _HEAD.unpack_from(buf, off + 8)
    body = bytes(buf[off + 8 + _HEAD.size:off + 8 + length])
    return rid, tag, body, off + 8 + length


def decode_frame(buf: bytes) -> tuple[int, str, bytes]:
    """Exactly ONE request frame: (request_id, qos class, body).
    Trailing bytes, truncation, or an unknown QoS byte are typed
    refusals."""
    got = _frame_at(buf, 0)
    if got is None:
        raise ValueError("truncated frame")
    rid, qos_b, body, end = got
    if end != len(buf):
        raise ValueError(f"{len(buf) - end} trailing byte(s) after frame")
    if qos_b not in QOS_NAME:
        raise ValueError(f"unknown QoS byte {qos_b}")
    return rid, QOS_NAME[qos_b], body

def decode_response_frame(buf: bytes) -> tuple[int, int, bytes]:
    """Exactly ONE response frame: (request_id, status, body)."""
    got = _frame_at(buf, 0)
    if got is None:
        raise ValueError("truncated frame")
    rid, status, body, end = got
    if end != len(buf):
        raise ValueError(f"{len(buf) - end} trailing byte(s) after frame")
    if status not in (
        STATUS_OK, STATUS_SHED, STATUS_TIMEOUT, STATUS_ERROR
    ):
        raise ValueError(f"unknown response status {status}")
    return rid, status, body


def decode_request_stream(buf: bytes) -> list[tuple]:
    """STRICT parse of a whole pipelined request stream: every frame
    complete and well-formed, every body a valid XFS1/XFS2 request.
    Returns ``[(request_id, qos, rows, trace), ...]``.  A truncated
    final frame is a refusal here (the fuzz contract); the live server
    uses the incremental ``_frame_at`` and waits instead."""
    out = []
    off = 0
    while off < len(buf):
        got = _frame_at(buf, off)
        if got is None:
            raise ValueError(
                f"truncated frame at offset {off} "
                f"({len(buf) - off} byte(s) left)"
            )
        rid, qos_b, body, off = got
        if qos_b not in QOS_NAME:
            raise ValueError(f"unknown QoS byte {qos_b}")
        rows, trace = decode_packed_request_traced(body)
        out.append((rid, QOS_NAME[qos_b], rows, trace))
    return out


def _json_body(doc: dict) -> bytes:
    return json.dumps(doc, sort_keys=True).encode()


# -- server -------------------------------------------------------------------


class _Request:
    """One in-flight frame's fan-in: N row futures resolve (on replica
    worker threads) into ONE response frame, exactly once — the
    deadline sweep and the last future race through ``finish``."""

    __slots__ = (
        "conn", "rid", "deadline", "results", "left", "lock", "done",
    )

    def __init__(self, conn: "_Conn", rid: int, nrows: int,
                 deadline: float):
        self.conn = conn
        self.rid = rid
        self.deadline = deadline
        self.results: list = [0.0] * nrows
        self.left = nrows
        self.lock = threading.Lock()
        self.done = False

    def finish(self, emit: Callable[["_Conn", bytes], None],
               status: int, body: bytes) -> bool:
        with self.lock:
            if self.done:
                return False
            self.done = True
        emit(self.conn, encode_response_frame(self.rid, status, body))
        return True


class _Conn:
    __slots__ = ("sock", "inbuf", "outbuf", "off", "pending", "last")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.off = 0  # parse offset into inbuf
        self.pending: dict[int, _Request] = {}
        self.last = time.perf_counter()


class BinaryTier:
    """The running binary front end: one selector thread over a
    listening socket + its persistent connections, feeding the fleet.
    """

    def __init__(
        self,
        fleet,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        flight=None,
        poll_s: float = 0.25,
        score_timeout_s: float = SCORE_TIMEOUT_S,
        socket_timeout_s: float = SOCKET_TIMEOUT_S,
        drain_timeout_s: float = 30.0,
    ):
        if score_timeout_s <= 0 or socket_timeout_s <= 0:
            raise ValueError(
                "score_timeout_s and socket_timeout_s must be > 0"
            )
        self.fleet = fleet
        self.flight = flight
        self.score_timeout_s = score_timeout_s
        # idle-connection reap bound — a client that stalls mid-frame
        # (half-open TCP) releases its buffers after this long instead
        # of holding them forever (the XF017 discipline of the HTTP
        # tier's per-socket timeout, selector-style)
        self.socket_timeout_s = socket_timeout_s
        self._poll_s = poll_s
        self._drain_timeout_s = drain_timeout_s
        self.accept_faults = 0  # survived serve.binary_accept fires
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(128)
        self._lsock.setblocking(False)
        # wake pipe: completion callbacks (replica worker threads) and
        # close() nudge the selector out of its poll
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._done_q: "queue.Queue[tuple[_Conn, bytes]]" = queue.Queue()
        self._sel = selectors.DefaultSelector()
        self._conns: set[_Conn] = set()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._closed = False

    @property
    def port(self) -> int:
        return self._lsock.getsockname()[1]

    @property
    def address(self) -> str:
        host, port = self._lsock.getsockname()[:2]
        return f"{host}:{port}"

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and not self._closed

    def start(self) -> "BinaryTier":
        with self._lock:
            if self._closed:
                raise RuntimeError("BinaryTier is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._serve,
                    name="xflow-serve-binary",
                    daemon=True,
                )
                self._thread.start()
        return self

    # -- selector loop ------------------------------------------------------

    def _serve(self) -> None:
        self._sel.register(self._lsock, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        # heartbeat IS pulsed each iteration (flight.note_http below);
        # the select() poll bounds every pass (xf: ignore[XF009])
        while not self._stop.is_set():
            try:
                # chaos site (XF018): a transient accept-loop fault —
                # the loop SURVIVES it, exactly like the HTTP tier's
                # serve.accept discipline
                failpoint("serve.binary_accept")
            except ChaosError:
                self.accept_faults += 1
            if self.flight is not None:
                self.flight.note_http("binary_accept")
            for key, _ in self._sel.select(timeout=self._poll_s):
                if key.data == "accept":
                    self._accept()
                elif key.data == "wake":
                    self._drain_wake()
                else:
                    self._service(key.data, key.events)
            self._drain_done()
            self._sweep()
        # shutdown: selector unregistered, sockets closed; pending
        # requests' futures keep resolving into _done_q and are dropped
        self._sel.close()

    def _accept(self) -> None:
        try:
            sock, _ = self._lsock.accept()
        except (BlockingIOError, OSError):
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock)
        with self._lock:
            self._conns.add(conn)
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _drain_wake(self) -> None:
        try:
            # bounded by the wake pipe's buffered bytes: the socket is
            # non-blocking, so an empty pipe exits via BlockingIOError
            # (xf: ignore[XF009])
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass  # closing; the loop is exiting anyway

    def _emit(self, conn: _Conn, frame: bytes) -> None:
        """Queue one response frame for ``conn`` — safe from ANY
        thread (replica workers, the sweep, the loop itself)."""
        self._done_q.put((conn, frame))
        self._wake()

    def _drain_done(self) -> None:
        # bounded by the queue's contents at entry: get_nowait exits
        # on Empty, never blocks (xf: ignore[XF009])
        while True:
            try:
                conn, frame = self._done_q.get_nowait()
            except queue.Empty:
                return
            with self._lock:
                live = conn in self._conns
            if not live:
                continue  # client went away; nothing to answer
            conn.outbuf += frame
            self._flush(conn)

    def _want_write(self, conn: _Conn, want: bool) -> None:
        events = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if want else 0
        )
        try:
            self._sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            pass  # already unregistered (connection died)

    def _service(self, conn: _Conn, events: int) -> None:
        if events & selectors.EVENT_WRITE:
            self._flush(conn)
        if events & selectors.EVENT_READ:
            self._read(conn)

    def _flush(self, conn: _Conn) -> None:
        conn.last = time.perf_counter()
        try:
            # bounded by the buffered bytes: the socket is non-blocking,
            # so a full kernel buffer exits via BlockingIOError
            # (xf: ignore[XF009])
            while conn.outbuf:
                n = conn.sock.send(conn.outbuf)
                if n <= 0:
                    break
                del conn.outbuf[:n]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._drop(conn)
            return
        self._want_write(conn, bool(conn.outbuf))

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not data:
            self._drop(conn)
            return
        conn.last = time.perf_counter()
        conn.inbuf += data
        try:
            # bounded by the bytes just buffered: _frame_at returns
            # None (break) once only an incomplete frame remains
            # (xf: ignore[XF009])
            while True:
                got = _frame_at(conn.inbuf, conn.off)
                if got is None:
                    break
                rid, qos_b, body, conn.off = got
                self._handle(conn, rid, qos_b, body)
        except (ValueError, struct.error):
            # unframeable garbage: a pipelined stream cannot resync
            # past it — drop the connection (the client's typed signal
            # is the reset; intra-frame garbage with GOOD framing gets
            # a STATUS_ERROR response instead, in _handle)
            self._drop(conn)
            return
        if conn.off:
            del conn.inbuf[:conn.off]
            conn.off = 0

    def _handle(self, conn: _Conn, rid: int, qos_b: int,
                body: bytes) -> None:
        if qos_b not in QOS_NAME:
            self._emit(conn, encode_response_frame(
                rid, STATUS_ERROR,
                _json_body({"error": f"unknown QoS byte {qos_b}"}),
            ))
            return
        qos = QOS_NAME[qos_b]
        try:
            rows, trace = decode_packed_request_traced(body)
        except (ValueError, KeyError, struct.error) as e:
            # the HTTP tier's 400 taxonomy, framed
            self._emit(conn, encode_response_frame(
                rid, STATUS_ERROR,
                _json_body({"error": f"{type(e).__name__}: {e}"}),
            ))
            return
        req = _Request(
            conn, rid, len(rows),
            time.perf_counter() + self.score_timeout_s,
        )
        conn.pending[rid] = req
        try:
            for i, row in enumerate(rows):
                fut = self.fleet.submit(*row, trace=trace, qos=qos)
                fut.add_done_callback(
                    lambda f, req=req, i=i: self._row_done(req, f, i)
                )
        except ShedError as e:
            conn.pending.pop(rid, None)
            retry_ms = max(
                1, int(self.fleet.policy.deadline_budget_s * 1000)
            )
            req.finish(self._emit, STATUS_SHED, _json_body({
                "error": "backpressure",
                "cause": e.cause,
                "qos": qos,
                "depth": e.depth,
                "queue_age_ms": round(e.queue_age_s * 1000.0, 3),
                "retry_after_ms": retry_ms,
            }))
        except Exception as e:
            conn.pending.pop(rid, None)
            req.finish(self._emit, STATUS_ERROR, _json_body({
                "error": f"{type(e).__name__}: {e}",
            }))

    def _row_done(self, req: _Request, fut, i: int) -> None:
        """One row future resolved (replica worker thread).  The LAST
        row emits the response frame; an error resolves the whole
        frame immediately (remaining rows still score and are ignored
        — the all-or-nothing contract of the HTTP tier)."""
        err = fut.exception()
        if err is not None:
            req.finish(self._emit, STATUS_ERROR, _json_body({
                "error": f"{type(err).__name__}: {err}",
            }))
            return
        with req.lock:
            if req.done:
                return
            # a done-callback's future is resolved by definition —
            # this .result() can never block
            req.results[i] = float(fut.result())  # xf: ignore[XF017]
            req.left -= 1
            last = req.left == 0
        if last:
            req.finish(
                self._emit, STATUS_OK,
                encode_packed_response(req.results),
            )

    def _sweep(self) -> None:
        """Bound every in-flight request (XF017): a scoring future
        that outlives ``score_timeout_s`` answers STATUS_TIMEOUT now —
        the wire's 504.  Also reaps idle connections past the socket
        timeout."""
        now = time.perf_counter()
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            for rid in [
                r for r, q in conn.pending.items() if q.deadline <= now
            ]:
                req = conn.pending.pop(rid)
                req.finish(self._emit, STATUS_TIMEOUT, _json_body({
                    "error": "scoring timed out",
                    "timeout_s": self.score_timeout_s,
                }))
            if (
                now - conn.last > self.socket_timeout_s
                and not conn.pending
                and not conn.outbuf
            ):
                self._drop(conn)

    def _drop(self, conn: _Conn) -> None:
        with self._lock:
            self._conns.discard(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        conn.pending.clear()

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Stop accepting, drain in-flight frames (bounded), join the
        acceptor (bounded — XF006), close every socket.  Never closes
        the fleet (it may be shared with an HTTP tier)."""
        with self._lock:
            first = not self._closed
            self._closed = True
            thread = self._thread
            self._thread = None
        if not first:
            return
        # drain window: frames already submitted resolve through the
        # loop before it stops (bounded)
        deadline = time.perf_counter() + self._drain_timeout_s
        while time.perf_counter() < deadline:
            with self._lock:
                busy = any(c.pending or c.outbuf for c in self._conns)
            if not busy:
                break
            time.sleep(0.01)
        self._stop.set()
        self._wake()
        if thread is not None:
            thread.join(timeout=10.0)
            if thread.is_alive():  # pragma: no cover - wedged socket
                import warnings

                warnings.warn(
                    "binary serve acceptor outlived its close() join",
                    RuntimeWarning,
                    stacklevel=2,
                )
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.sock.close()
            except OSError:
                pass
        for s in (self._lsock, self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def __enter__(self) -> "BinaryTier":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()
