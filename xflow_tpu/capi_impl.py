"""Python side of the C ABI (native/src/c_api.cc).

The reference shipped a (disabled) C API wrapping LRWorker —
``XFCreate(handle, train, test)`` / ``XFStartTrain(handle)``
(c_api.h:26-41, build commented out at CMakeLists.txt:28, includes
stale) — signalling an intended embed-as-a-library surface.  Here that
surface is real: ``libxflow_tpu.so`` embeds CPython and drives these
functions; C/C++ programs get create/train/evaluate/predict without a
Python process.

The predict path needs NO full Trainer: ``engine_create`` loads a
serving artifact (serve/artifact.py) into a PredictEngine — frozen
params + remap only, shape-bucketed compilation — so a C scoring
process never builds a loader, optimizer state, or training step.
``export_artifact`` is the training-side handoff.

Kept deliberately tiny: the C side only imports this module and calls
these functions, so the ABI never needs to know about Config, Trainer,
or engine internals.

Model families: every registry family (models/__init__.py — including
the cascade families ``two_tower``/``dcn``) trains and POINT-SCORES
through this surface; an unregistered name is refused at create time
with the registered-families list (the registry's actionable error).
Top-k retrieval is NOT part of the C ABI: a two_tower artifact scores
(user, item) rows like any family here, while candidate generation
lives behind the serving tier's /v1/topk / /v1/recommend endpoints
(serve/cascade.py) — an RPC surface, not an embed surface.
"""

from __future__ import annotations

import json

from xflow_tpu.api import XFlow


def create(train_path: str, test_path: str, config_json: str) -> XFlow:
    overrides = json.loads(config_json) if config_json else {}
    return XFlow(train_path, test_path, **overrides)


def train(xf: XFlow) -> int:
    xf.train()
    return 0


def evaluate(xf: XFlow) -> tuple[float, float]:
    res = xf.evaluate()
    return float(res["logloss"]), float(res["auc"])


def export_artifact(xf: XFlow, directory: str) -> str:
    return xf.export_artifact(directory)


def engine_create(artifact_dir: str, num_devices: int = 1):
    from xflow_tpu.serve.engine import PredictEngine

    return PredictEngine.load(artifact_dir, num_devices=num_devices)


def engine_score_line(engine, line: str) -> float:
    """pctr for one libffm-format line (label field ignored)."""
    return float(engine.score_text([line])[0])
