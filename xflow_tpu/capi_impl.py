"""Python side of the C ABI (native/src/c_api.cc).

The reference shipped a (disabled) C API wrapping LRWorker —
``XFCreate(handle, train, test)`` / ``XFStartTrain(handle)``
(c_api.h:26-41, build commented out at CMakeLists.txt:28, includes
stale) — signalling an intended embed-as-a-library surface.  Here that
surface is real: ``libxflow_tpu.so`` embeds CPython and drives these
functions; C/C++ programs get create/train/evaluate/predict without a
Python process.

Kept deliberately tiny: the C side only imports this module and calls
these three functions, so the ABI never needs to know about Config or
Trainer internals.
"""

from __future__ import annotations

import json

from xflow_tpu.api import XFlow


def create(train_path: str, test_path: str, config_json: str) -> XFlow:
    overrides = json.loads(config_json) if config_json else {}
    return XFlow(train_path, test_path, **overrides)


def train(xf: XFlow) -> int:
    xf.train()
    return 0


def evaluate(xf: XFlow) -> tuple[float, float]:
    res = xf.evaluate()
    return float(res["logloss"]), float(res["auc"])
