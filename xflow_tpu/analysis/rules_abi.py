"""XF005 — C-ABI parity across the three declaration surfaces.

The embed surface spans three files that nothing compiles together in
CI: ``native/include/xflow_tpu.h`` (what C callers see),
``native/src/c_api.cc`` (the embedding shims), and ``capi_impl.py``
(the Python functions the shims call via ``call_impl("name")``).  The
.so ships prebuilt, so a symbol added to one surface and forgotten in
another only explodes at customer link/run time.  This rule diffs all
three statically:

* every ``XF*`` function declared in the header is defined in c_api.cc
  and vice versa (no orphan definitions);
* every ``call_impl("name")`` target in c_api.cc exists as a function
  in capi_impl.py;
* every public function in capi_impl.py is reachable from c_api.cc
  (the module exists solely as the ABI's Python half).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from xflow_tpu.analysis.core import Finding, PackageIndex, Rule

_HEADER_REL = os.path.join("native", "include", "xflow_tpu.h")
_CC_REL = os.path.join("native", "src", "c_api.cc")

_XF_FN_RE = re.compile(r"\b(XF[A-Za-z0-9_]+)\s*\(")
_CALL_IMPL_RE = re.compile(r"call_impl\(\s*\"(\w+)\"")
_BLOCK_COMMENT_RE = re.compile(r"/\*.*?\*/", re.S)
_LINE_COMMENT_RE = re.compile(r"//[^\n]*")


def _strip_c_comments(text: str) -> str:
    """Blank out comments, preserving newlines so line numbers hold."""

    def blank(m: re.Match) -> str:
        return re.sub(r"[^\n]", " ", m.group(0))

    return _LINE_COMMENT_RE.sub(blank, _BLOCK_COMMENT_RE.sub(blank, text))


def _xf_symbols(text: str) -> dict[str, int]:
    """XF function name -> first line it appears at (comments stripped)."""
    stripped = _strip_c_comments(text)
    out: dict[str, int] = {}
    for m in _XF_FN_RE.finditer(stripped):
        name = m.group(1)
        if name not in out:
            out[name] = stripped.count("\n", 0, m.start()) + 1
    return out


class CAbiParity(Rule):
    id = "XF005"
    title = "C-ABI symbol parity (header / c_api.cc / capi_impl.py)"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        header_path = cc_path = None
        for root in index.roots:
            h = os.path.join(root, _HEADER_REL)
            c = os.path.join(root, _CC_REL)
            if header_path is None and os.path.exists(h):
                header_path = h
            if cc_path is None and os.path.exists(c):
                cc_path = c
        if header_path is None or cc_path is None:
            return  # no native surface in this scan
        with open(header_path, encoding="utf-8", errors="replace") as f:
            header_text = f.read()
        with open(cc_path, encoding="utf-8", errors="replace") as f:
            cc_text = f.read()
        declared = _xf_symbols(header_text)
        defined = _xf_symbols(cc_text)
        header_rel = _HEADER_REL.replace(os.sep, "/")
        cc_rel = _CC_REL.replace(os.sep, "/")
        for name, line in sorted(declared.items()):
            if name not in defined:
                yield Finding(
                    rule=self.id,
                    path=header_rel,
                    line=line,
                    message=(
                        f"{name} is declared in the header but has no "
                        "definition in c_api.cc — C callers link "
                        "against a symbol that does not exist"
                    ),
                )
        for name, line in sorted(defined.items()):
            if name not in declared:
                yield Finding(
                    rule=self.id,
                    path=cc_rel,
                    line=line,
                    message=(
                        f"{name} is defined in c_api.cc but not "
                        "declared in the header — unreachable ABI "
                        "surface; declare it or delete it"
                    ),
                )
        # -- python half ------------------------------------------------
        capi = index.by_rel("capi_impl.py")
        if capi is None or capi.tree is None:
            return
        impl_fns = {
            node.name: node.lineno
            for node in capi.tree.body
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        stripped_cc = _strip_c_comments(cc_text)
        called: dict[str, int] = {}
        for m in _CALL_IMPL_RE.finditer(stripped_cc):
            called.setdefault(
                m.group(1), stripped_cc.count("\n", 0, m.start()) + 1
            )
        for name, line in sorted(called.items()):
            if name not in impl_fns:
                yield Finding(
                    rule=self.id,
                    path=cc_rel,
                    line=line,
                    message=(
                        f"c_api.cc calls capi_impl.{name} which does "
                        "not exist — the ABI entry fails at runtime "
                        "with an AttributeError through XFLastError"
                    ),
                )
        for name, line in sorted(impl_fns.items()):
            if not name.startswith("_") and name not in called:
                yield Finding(
                    rule=self.id,
                    path=capi.rel,
                    line=line,
                    message=(
                        f"capi_impl.{name} is public but no c_api.cc "
                        "shim calls it — orphan ABI half; wire it into "
                        "c_api.cc + the header or prefix it with _"
                    ),
                )
