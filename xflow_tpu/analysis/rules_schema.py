"""XF004 — metrics JSONL schema drift.

obs/schema.py is the single source of truth for every ``kind`` the
framework emits (PR 1), and the runtime lints
(scripts/check_metrics_schema.py, check_serve_smoke.py) only validate
kinds the toy pipelines happen to emit.  This rule closes the gap
statically: every string-literal ``kind`` passed to a ``.log(...)``
call anywhere in the scanned tree must be declared in the SCHEMA dict,
and — on whole-package scans — every declared kind must be emitted
somewhere, so dead schema entries can't accumulate.
"""

from __future__ import annotations

import ast
from typing import Iterator

from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
)


def _schema_kinds(sf: SourceFile) -> dict[str, int] | None:
    """kind -> declaration line from a module-level ``SCHEMA = {...}``."""
    if sf.tree is None:
        return None
    for node in sf.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "SCHEMA"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        return {
            k.value: k.lineno
            for k in node.value.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
    return None


class SchemaDrift(Rule):
    id = "XF004"
    title = "emitted JSONL kind not declared in obs/schema.py (or vice versa)"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        schema_file = None
        kinds: dict[str, int] | None = None
        for sf in index.files:
            if sf.rel.endswith("schema.py"):
                kinds = _schema_kinds(sf)
                if kinds is not None:
                    schema_file = sf
                    break
        if schema_file is None or kinds is None:
            return  # nothing to check against (partial scan)
        emitted: dict[str, list[tuple[SourceFile, ast.AST]]] = {}
        for sf in index.files:
            if sf is schema_file or sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "log"
                    and len(node.args) >= 2
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    emitted.setdefault(node.args[0].value, []).append(
                        (sf, node)
                    )
        for kind, sites in sorted(emitted.items()):
            if kind not in kinds:
                for sf, node in sites:
                    yield self.finding(
                        sf,
                        node,
                        f"JSONL kind {kind!r} is emitted here but not "
                        f"declared in {schema_file.rel} SCHEMA — "
                        "consumers (obs validate/summarize, the CI "
                        "lints) will reject the file; declare the "
                        "kind's fields first",
                    )
        # The vice-versa direction only makes sense when the scan covers
        # the emitting side of the package, not just a subtree: use the
        # trainer (the primary emitter) as the whole-package sentinel.
        if index.by_rel("trainer.py") is None:
            return
        for kind, lineno in sorted(kinds.items()):
            if kind not in emitted:
                yield Finding(
                    rule=self.id,
                    path=schema_file.rel,
                    line=lineno,
                    message=(
                        f"SCHEMA declares kind {kind!r} but nothing in "
                        "the scanned tree emits it — dead schema "
                        "entries hide real drift; delete it or emit it"
                    ),
                )
