"""Seeded structure-aware decoder fuzzer — the runtime half of the
wire-protocol gate (ISSUE 18; static half: rules_protocol.py, both
gated by scripts/check_protocol.py).

Every hand-rolled binary format in the tree gets its decoder driven
through hundreds of deterministic mutations of a VALID blob:

* ``xfs1`` / ``xfs2``  — the packed HTTP scoring request
  (serve/server.py; XFS2 = traced variant), with a decode→re-encode
  roundtrip check: an accepted mutant must re-encode byte-exactly
  (the format is canonical), or the decoder silently rewrote the
  payload;
* ``packed_v2``        — the device-ready CompactBatch shard
  (io/packed.py, driven through the buffered BytesIO reader path);
* ``binary_csr``       — the XFBC0001 CSR block cache (io/binary.py);
* ``delta_manifest``   — the incremental-export manifest + its
  digest-chain refusal ladder (stream/delta.py).

Mutations are structure-aware: truncation, magic confusion (overlay
another format's magic), length/count inflation (overwrite an aligned
little-endian window with huge values), field transposition (swap two
windows), byte flips, zero-fill.  The contract under fuzz: a decoder
either ACCEPTS a structurally valid payload or raises a TYPED error
(ValueError — incl. JSONDecodeError/UnicodeDecodeError — KeyError, or
struct.error, the taxonomy serve/server.py maps to HTTP 400).  Any
other exception, a hang, or an accepted-but-rewritten payload is a
gate failure.

Determinism: all randomness comes from a splitmix64 stream seeded by
the caller (the chaos/registry.py mixer idiom) — same seed, same
mutations, same report digest.  tests/test_analysis.py pins this.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import struct
import tempfile
import time
from typing import Callable

import numpy as np

# deliberate refusals — the exception taxonomy the serve handler maps
# to HTTP 400 (serve/server.py _do_post) and the loaders treat as
# "corrupt shard".  Everything else escaping a decoder is a bug.
TYPED_ERRORS = (ValueError, KeyError, struct.error)

# one fuzz case may not take longer than this (a "fast refusal" that
# scans gigabytes first is a DoS on the serve path)
CASE_BUDGET_S = 5.0

DEFAULT_SEED = 0xC0FFEE
DEFAULT_ROUNDS = 200


class SplitMix64:
    """Deterministic 64-bit stream (same mixer as chaos/registry.py)."""

    _MASK = (1 << 64) - 1

    def __init__(self, seed: int):
        self._s = seed & self._MASK

    def next(self) -> int:
        self._s = (self._s + 0x9E3779B97F4A7C15) & self._MASK
        z = self._s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & self._MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & self._MASK
        return z ^ (z >> 31)

    def randrange(self, n: int) -> int:
        """Uniform-ish in [0, n) — modulo bias is irrelevant for
        mutation placement."""
        if n <= 0:
            return 0
        return self.next() % n

    def choice(self, seq):
        return seq[self.randrange(len(seq))]


# -- mutators ---------------------------------------------------------------


def _mut_truncate(rng: SplitMix64, blob: bytes, magics) -> bytes:
    return blob[: rng.randrange(len(blob))]


def _mut_flip(rng: SplitMix64, blob: bytes, magics) -> bytes:
    i = rng.randrange(len(blob))
    b = bytearray(blob)
    b[i] ^= 1 + rng.randrange(255)
    return bytes(b)


def _mut_magic(rng: SplitMix64, blob: bytes, magics) -> bytes:
    other = rng.choice(magics)
    return other + blob[len(other):]


def _mut_inflate(rng: SplitMix64, blob: bytes, magics) -> bytes:
    """Overwrite an aligned window with a huge little-endian value —
    the count/length-inflation attack (nrows, nnz, rec_bytes, hlen)."""
    width = rng.choice((2, 4, 8))
    if len(blob) <= width:
        return blob + b"\xff" * width
    off = rng.randrange(len(blob) - width)
    big = (1 << (8 * width)) - 1 - rng.randrange(1 << (4 * width))
    b = bytearray(blob)
    b[off : off + width] = big.to_bytes(width, "little")
    return bytes(b)


def _mut_transpose(rng: SplitMix64, blob: bytes, magics) -> bytes:
    """Swap two equal-size windows — field transposition."""
    if len(blob) < 8:
        return blob[::-1]
    w = 1 + rng.randrange(min(16, len(blob) // 2))
    i = rng.randrange(len(blob) - w)
    j = rng.randrange(len(blob) - w)
    if i > j:
        i, j = j, i
    if j < i + w:  # overlap: degrade to a reversal of one window
        b = bytearray(blob)
        b[i : i + w] = b[i : i + w][::-1]
        return bytes(b)
    b = bytearray(blob)
    b[i : i + w], b[j : j + w] = b[j : j + w], b[i : i + w]
    return bytes(b)


def _mut_zero(rng: SplitMix64, blob: bytes, magics) -> bytes:
    w = 1 + rng.randrange(min(32, len(blob)))
    off = rng.randrange(max(1, len(blob) - w))
    b = bytearray(blob)
    b[off : off + w] = b"\x00" * w
    return bytes(b)


_MUTATORS = (
    _mut_truncate,
    _mut_flip,
    _mut_magic,
    _mut_inflate,
    _mut_transpose,
    _mut_zero,
)


# -- targets ----------------------------------------------------------------


class FuzzTarget:
    def __init__(
        self,
        name: str,
        blob: bytes,
        decode: Callable[[bytes], object],
        reencode: Callable[[bytes], bytes] | None = None,
    ):
        self.name = name
        self.blob = blob
        self.decode = decode
        # reencode: blob -> canonical re-encoding of decode(blob); an
        # accepted mutant whose re-encoding differs was silently
        # rewritten by the decoder (the "silently-wrong rows" failure)
        self.reencode = reencode


def _xfs_rows() -> list:
    """A small deterministic request in the featurize_raw row
    protocol: a full (keys, slots, vals) row, a slots-only row, and a
    bare key array."""
    return [
        (
            (np.arange(5, dtype=np.int64) * 1000003 + 7),
            np.arange(5, dtype=np.int32),
            np.linspace(0.125, 1.0, 5).astype(np.float32),
        ),
        (np.asarray([3, 9], np.int64), np.asarray([0, 1], np.int32), None),
        np.asarray([42], np.int64),
    ]


def _make_xfs_targets() -> list[FuzzTarget]:
    from xflow_tpu.obs.reqtrace import TraceContext
    from xflow_tpu.serve.server import (
        decode_packed_request_traced,
        encode_packed_request,
    )

    def reencode(blob: bytes) -> bytes:
        rows, trace = decode_packed_request_traced(blob)
        return encode_packed_request(rows, trace)

    def decode(blob: bytes):
        return decode_packed_request_traced(blob)

    plain = encode_packed_request(_xfs_rows())
    traced = encode_packed_request(
        _xfs_rows(),
        trace=TraceContext(0x1234_5678_9ABC_DEF0, 17, True),
    )
    return [
        FuzzTarget("xfs1", plain, decode, reencode),
        FuzzTarget("xfs2", traced, decode, reencode),
    ]


def _make_xfb1_target() -> FuzzTarget:
    """Structure-aware target for the pipelined XFB1 binary frames
    (serve/binary.py): the seed blob is a THREE-frame stream (mixed
    QoS bytes, mixed XFS1/XFS2 bodies, a u64-max request id), so
    truncation, length inflation, magic confusion, and garbage hit
    mid-pipeline frame boundaries, not just the stream head — exactly
    the stream positions the live selector loop parses from."""
    from xflow_tpu.obs.reqtrace import TraceContext
    from xflow_tpu.serve.binary import (
        decode_request_stream,
        encode_frame,
    )
    from xflow_tpu.serve.server import encode_packed_request

    plain = encode_packed_request(_xfs_rows())
    traced = encode_packed_request(
        _xfs_rows(),
        trace=TraceContext(0x0F1E_2D3C_4B5A_6978, 3, True),
    )
    blob = (
        encode_frame(1, "bidding", plain)
        + encode_frame(2, "best_effort", traced)
        + encode_frame(0xFFFF_FFFF_FFFF_FFFF, "normal", plain)
    )

    def decode(mutant: bytes):
        return decode_request_stream(mutant)

    def reencode(mutant: bytes) -> bytes:
        out = b""
        for rid, qos, rows, trace in decode_request_stream(mutant):
            out += encode_frame(
                rid, qos, encode_packed_request(rows, trace)
            )
        return out

    return FuzzTarget("xfb1", blob, decode, reencode)


def _make_packed_v2_target(workdir: str) -> FuzzTarget:
    from xflow_tpu.io import packed
    from xflow_tpu.io.batch import make_batch

    b_sz, k, table = 8, 6, 1 << 14
    keys = (
        np.arange(b_sz * k, dtype=np.int64).reshape(b_sz, k) * 2654435761
    ) % table
    slots = np.tile(np.arange(k, dtype=np.int32), (b_sz, 1))
    vals = np.ones((b_sz, k), np.float32)
    mask = np.ones((b_sz, k), np.float32)
    mask[:, k - 1] = 0.0  # a padded tail entry per row
    labels = (np.arange(b_sz) % 2).astype(np.float32)
    weights = np.ones(b_sz, np.float32)
    batch = make_batch(keys, slots, vals, mask, labels, weights)
    meta = dict(
        batch_size=b_sz, cold_nnz=k, hot_nnz=0, hot_size=0,
        table_size=table, hash_mode=True, hash_seed=0,
        remap_sha256=None,
    )
    path = os.path.join(workdir, "fuzz-shard.pk2")
    packed.write_shard_v2(path, meta, iter([batch, batch]))
    with open(path, "rb") as f:
        blob = f.read()

    def decode(mutant: bytes):
        # BytesIO: no usable fileno, so the reader takes the buffered
        # fallback — same plane math as the mmap path (pinned byte-
        # equal by tests/test_compact.py)
        out = []
        for cb, _, _ in packed.iter_compact_batches(io.BytesIO(mutant)):
            out.append(cb)
        return out

    return FuzzTarget("packed_v2", blob, decode)


def _make_binary_csr_target() -> FuzzTarget:
    from xflow_tpu.io import binary, container
    from xflow_tpu.io.batch import ParsedBlock

    buf = io.BytesIO()
    meta = {"version": 1, "hash_mode": True, "hash_seed": 0}
    hdr_len = container.write_placeholder_header(
        buf, binary.MAGIC, meta, ("examples", "nnz", "blocks")
    )
    block = ParsedBlock(
        labels=np.asarray([1.0, 0.0], np.float32),
        row_ptr=np.asarray([0, 2, 3], np.int64),
        keys=np.asarray([11, -5, 1 << 40], np.int64),
        slots=np.asarray([0, 1, 0], np.int32),
        vals=np.asarray([1.0, 0.5, 2.0], np.float32),
    )
    binary._write_record(buf, block)
    meta.update(examples=2, nnz=3, blocks=1)
    container.rewrite_header(buf, binary.MAGIC, meta, hdr_len)

    def decode(mutant: bytes):
        out = []
        for blk, _, _ in binary.iter_blocks(io.BytesIO(mutant), 1 << 14):
            out.append(blk)
        return out

    return FuzzTarget("binary_csr", buf.getvalue(), decode)


def _make_delta_target(workdir: str) -> FuzzTarget:
    from xflow_tpu.config import Config
    from xflow_tpu.serve.artifact import servable_digest
    from xflow_tpu.stream.delta import (
        DELTA_FORMAT,
        DELTA_MANIFEST,
        load_delta_manifest,
    )

    cfg = Config()
    digest = cfg.digest()
    manifest = {
        "format": DELTA_FORMAT,
        "kind": "delta",
        "model": cfg.model,
        "config": cfg.to_json(),
        "config_digest": digest,
        "step": 100,
        "base_step": 50,
        "base_digest": servable_digest(digest, 50),
        "delta_digest": servable_digest(digest, 100),
        "rows": 0,
        "arrays": {},
        "dense": [],
        "content_sha256": "0" * 64,
        "created_unix": 0.0,
    }
    blob = json.dumps(manifest, indent=2).encode()
    ddir = os.path.join(workdir, "fuzz-delta")
    os.makedirs(ddir, exist_ok=True)

    def decode(mutant: bytes):
        with open(os.path.join(ddir, DELTA_MANIFEST), "wb") as f:
            f.write(mutant)
        return load_delta_manifest(ddir)

    return FuzzTarget("delta_manifest", blob, decode)


def build_targets(workdir: str) -> list[FuzzTarget]:
    """One FuzzTarget per wire decoder, each seeded with a valid blob."""
    return [
        *_make_xfs_targets(),
        _make_xfb1_target(),
        _make_packed_v2_target(workdir),
        _make_binary_csr_target(),
        _make_delta_target(workdir),
    ]


# -- driver -----------------------------------------------------------------


def fuzz_target(
    target: FuzzTarget,
    rng: SplitMix64,
    rounds: int,
    sha: "hashlib._Hash | None" = None,
) -> dict:
    """Drive one decoder through ``rounds`` mutations; returns the
    per-target report.  ``sha`` (when given) absorbs every mutant for
    the run-level determinism digest."""
    from xflow_tpu.io import binary, packed
    from xflow_tpu.serve.binary import FRAME_MAGIC as XFB1_MAGIC
    from xflow_tpu.serve.server import PACKED_MAGIC, PACKED_TRACE_MAGIC

    magics = [PACKED_MAGIC, PACKED_TRACE_MAGIC, XFB1_MAGIC, binary.MAGIC, packed.MAGIC]
    magics = [m for m in magics if not target.blob.startswith(m)]
    # the pristine blob must decode — a broken builder would make every
    # "typed error" below meaningless
    target.decode(target.blob)
    if target.reencode is not None and target.reencode(
        target.blob
    ) != target.blob:
        raise AssertionError(
            f"{target.name}: valid blob does not round-trip — builder "
            "or codec bug, fuzz results would be meaningless"
        )
    counts = {
        "typed": 0, "accepted": 0, "accepted_mismatch": 0,
        "untyped": 0, "slow": 0,
    }
    failures: list[dict] = []
    for case in range(rounds):
        mutator = _MUTATORS[rng.randrange(len(_MUTATORS))]
        mutant = mutator(rng, target.blob, magics)
        if sha is not None:
            sha.update(target.name.encode())
            sha.update(case.to_bytes(4, "little"))
            sha.update(mutant)
        t0 = time.perf_counter()
        outcome, detail = _drive(target, mutant)
        elapsed = time.perf_counter() - t0
        if elapsed > CASE_BUDGET_S:
            outcome, detail = "slow", f"case took {elapsed:.1f}s"
        counts[outcome] += 1
        if outcome in ("untyped", "accepted_mismatch", "slow") and len(
            failures
        ) < 8:
            failures.append({
                "case": case,
                "mutator": mutator.__name__,
                "outcome": outcome,
                "detail": detail,
            })
    return {
        "rounds": rounds,
        "counts": counts,
        "failures": failures,
        "ok": not (
            counts["untyped"] or counts["accepted_mismatch"]
            or counts["slow"]
        ),
    }


def _drive(target: FuzzTarget, mutant: bytes) -> tuple[str, str]:
    try:
        target.decode(mutant)
    except TYPED_ERRORS as e:
        return "typed", type(e).__name__
    except Exception as e:  # the gate failure we exist to catch
        return "untyped", f"{type(e).__name__}: {e}"
    if mutant == target.blob:
        return "accepted", "mutation was identity"
    if target.reencode is not None:
        try:
            if target.reencode(mutant) != mutant:
                return (
                    "accepted_mismatch",
                    "decoder accepted a mutant that does not re-encode "
                    "byte-exactly — silently rewritten payload",
                )
        except TYPED_ERRORS:
            return (
                "accepted_mismatch",
                "mutant decoded but its decoded form refuses to "
                "re-encode — decoder accepted out-of-domain values",
            )
    return "accepted", "structurally valid mutation"


def run_wirefuzz(
    seed: int = DEFAULT_SEED,
    rounds: int = DEFAULT_ROUNDS,
    workdir: str | None = None,
) -> dict:
    """Fuzz every wire decoder; returns the run report.

    ``mutation_digest`` is a sha256 over (target, case, mutant bytes)
    for the whole run — byte-identical across runs with the same seed
    and rounds (the determinism contract tests/test_analysis.py pins).
    """
    own_dir = workdir is None
    if own_dir:
        workdir = tempfile.mkdtemp(prefix="xf-wirefuzz-")
    try:
        sha = hashlib.sha256()
        targets = build_targets(workdir)
        report: dict = {
            "seed": seed,
            "rounds": rounds,
            "targets": {},
        }
        for i, target in enumerate(targets):
            # per-target stream: target order can change without
            # re-rolling every other target's mutations
            rng = SplitMix64((seed ^ (0xA5A5_0000 + i)) * 0x9E3779B9)
            report["targets"][target.name] = fuzz_target(
                target, rng, rounds, sha
            )
        report["mutation_digest"] = sha.hexdigest()
        report["ok"] = all(
            t["ok"] for t in report["targets"].values()
        )
        return report
    finally:
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)


def render_report(report: dict) -> str:
    lines = [
        f"wirefuzz: seed={report['seed']:#x} rounds={report['rounds']} "
        f"digest={report['mutation_digest'][:16]}",
    ]
    for name, t in report["targets"].items():
        c = t["counts"]
        lines.append(
            f"  {name:<16} typed={c['typed']:<4} "
            f"accepted={c['accepted']:<4} "
            f"untyped={c['untyped']} mismatch={c['accepted_mismatch']} "
            f"slow={c['slow']}  -> {'OK' if t['ok'] else 'FAIL'}"
        )
        for f in t["failures"]:
            lines.append(
                f"    case {f['case']} [{f['mutator']}] "
                f"{f['outcome']}: {f['detail']}"
            )
    return "\n".join(lines)
