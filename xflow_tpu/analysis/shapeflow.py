"""Symbolic shape/dtype dataflow — the abstract interpreter under the
XF010–XF014 memory rules (rules_memory.py).

The concurrency rules (PR 6) mechanized "who runs on which thread";
this module mechanizes "how big is that array".  It walks jitted
functions — discovered package-wide the way XF002 finds traced code —
and propagates SYMBOLIC shapes through assignments, ``jnp``/``np``
calls, dict/tuple plumbing, ``lax.scan`` bodies, and resolvable
in-package call edges (riding PR 6's ``ConcurrencyContext`` for call
resolution).  Dims are expressions over named symbols seeded from
``Config`` caps:

    T  table rows (cfg.table_size)      H  hot head rows (cfg.hot_size)
    B  batch_size                       K  max_nnz       Kh hot_nnz
    S  microbatch                       D  table row width (flagship)

so ``zeros_like(state["tables"][n]["param"])`` is known to allocate
``[T, D]`` and ``t["param"][batch["keys"]]`` to gather ``[B, K, D]`` —
the facts XF010 (full-table transients), XF012 (sharding coverage) and
XF014 (the transient-HBM budget, evaluated at the north-star geometry
T=2^28) gate on.

Design constraints, inherited from core.py: pure stdlib ``ast`` — the
interpreter never imports or executes the code under analysis.  It is
deliberately CONSERVATIVE: anything it cannot model becomes UNKNOWN and
simply contributes nothing (rules only ever fire on facts it could
prove), branches are both taken (flow-insensitive: an allocation behind
``if`` counts), loop bodies run once, and recursion/depth are bounded.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Any, Callable, Iterable

from xflow_tpu.analysis.core import PackageIndex, SourceFile, dotted_name
from xflow_tpu.analysis.rules_concurrency import (
    ConcurrencyContext,
    _Fn,
    get_context,
)
from xflow_tpu.analysis.rules_jax import _is_partial_of_jit

# -- symbolic dims ---------------------------------------------------------
#
# A dim is a nested tuple expression:  ('c', 7) const, ('s', 'T') symbol,
# ('+'|'*'|'//'|'-'|'%', a, b) arithmetic.  Tuples give structural
# equality and hashability for free.

Dim = tuple


def dconst(n: int) -> Dim:
    return ("c", int(n))


def dsym(name: str) -> Dim:
    return ("s", name)


def dbin(op: str, a: Dim, b: Dim) -> Dim:
    if a[0] == "c" and b[0] == "c":
        x, y = a[1], b[1]
        try:
            v = {
                "+": x + y,
                "-": x - y,
                "*": x * y,
                "//": x // y if y else 0,
                "%": x % y if y else 0,
            }[op]
        except KeyError:
            return (op, a, b)
        return dconst(v)
    # cheap identities keep rendered dims readable
    if op == "*" and a == dconst(1):
        return b
    if op == "*" and b == dconst(1):
        return a
    if op == "//" and b == dconst(1):
        return a
    if op in ("+", "-") and b == dconst(0):
        return a
    if op == "+" and a == dconst(0):
        return b
    return (op, a, b)


def dprod(dims: Iterable[Dim]) -> Dim:
    out = dconst(1)
    for d in dims:
        out = dbin("*", out, d)
    return out


def deval(d: Dim, env: dict[str, int]) -> int | None:
    """Evaluate at a concrete geometry; None when a symbol is unbound."""
    kind = d[0]
    if kind == "c":
        return d[1]
    if kind == "s":
        return env.get(d[1])
    a = deval(d[1], env)
    b = deval(d[2], env)
    if a is None or b is None:
        return None
    if kind == "+":
        return a + b
    if kind == "-":
        return a - b
    if kind == "*":
        return a * b
    if kind == "//":
        return a // b if b else None
    if kind == "%":
        return a % b if b else None
    return None


def dstr(d: Dim) -> str:
    kind = d[0]
    if kind == "c":
        return str(d[1])
    if kind == "s":
        return d[1]
    return f"({dstr(d[1])}{kind}{dstr(d[2])})"


def shape_str(shape: tuple[Dim, ...]) -> str:
    return "[" + ", ".join(dstr(d) for d in shape) + "]"


# -- abstract values -------------------------------------------------------


class _Unknown:
    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "UNK"


UNK = _Unknown()


@dataclass(frozen=True)
class DimV:
    """A Python int abstracted as a symbolic dim (e.g. cfg.table_size)."""

    d: Dim


@dataclass(frozen=True)
class StrV:
    s: str


@dataclass(frozen=True)
class ShapeV:
    """Result of ``x.shape`` — a tuple of dims that indexes/slices."""

    dims: tuple[Dim, ...]


@dataclass(frozen=True)
class ArrV:
    """An array of known symbolic shape.  dtype is a best-effort string
    ('float32', 'int32', 'uint8', 'bfloat16', ...; None = unknown,
    sized as 4 bytes)."""

    shape: tuple[Dim, ...]
    dtype: str | None = None


@dataclass
class MapV:
    """A dict whose values the flow tracks per known string key, with a
    ``default`` for unknown keys (e.g. ``tables``: every value is a
    table dict).  default may be a zero-arg callable for lazy cycles."""

    known: dict[str, Any]
    default: Any = None

    def lookup(self, key: str | None) -> Any:
        if key is not None and key in self.known:
            return self.known[key]
        d = self.default
        if callable(d):
            d = d()
        return d if d is not None else UNK


@dataclass(frozen=True)
class TupV:
    items: tuple[Any, ...]


@dataclass(frozen=True)
class ItemsV:
    """``m.items()`` — carried to the for/comprehension that unpacks it."""

    m: MapV


class ConfigV:
    """The Config object: attribute reads become dims via CONFIG_SYMS."""


@dataclass(frozen=True)
class FnRefV:
    fn: _Fn


@dataclass(frozen=True)
class AtV:
    arr: ArrV


@dataclass(frozen=True)
class AtIdxV:
    arr: ArrV
    idx: Any


# Config attribute -> symbol.  table_size/hot_size/hot_capacity are
# the @property spellings of the *_log2 knobs (config.py).  Hc is the
# tiered store's hot-tier row count (store/hot.py) — the dim the
# store's transients are PROVEN to scale with instead of T (XF014).
CONFIG_SYMS = {
    "table_size": "T",
    "hot_size": "H",
    "hot_capacity": "Hc",
    "max_nnz": "K",
    "hot_nnz": "Kh",
    "microbatch": "S",
    "batch_size": "B",
}

_ALLOC_LEAVES = {"zeros", "ones", "full", "empty"}
_ALLOC_LIKE_LEAVES = {"zeros_like", "ones_like", "full_like", "empty_like"}
_ELEMWISE_LEAVES = {
    "where", "maximum", "minimum", "clip", "add", "multiply", "subtract",
    "exp", "log", "abs", "negative", "sign", "tanh", "logaddexp",
}
_SAMESHAPE_METHODS = {"cumsum", "sort", "argsort", "copy"}
_REDUCE_LEAVES = {"sum", "max", "min", "mean", "prod", "all", "any"}

_DTYPE_LEAVES = {
    "float32": "float32", "float64": "float64", "bfloat16": "bfloat16",
    "int32": "int32", "int64": "int64", "uint8": "uint8",
    "uint16": "uint16", "uint32": "uint32", "bool_": "bool",
    "bool": "bool",
}

DTYPE_BYTES = {
    "float32": 4, "float64": 8, "bfloat16": 2, "float16": 2,
    "int32": 4, "int64": 8, "uint8": 1, "uint16": 2, "uint32": 4,
    "int8": 1, "bool": 1,
}


def dtype_bytes(dtype: str | None) -> int:
    return DTYPE_BYTES.get(dtype or "", 4)


@dataclass
class Transient:
    """One array materialization the flow could size: an explicit
    allocation, a one-hot expansion, or a gather."""

    sf: SourceFile
    node: ast.AST
    shape: tuple[Dim, ...]
    dtype: str | None
    kind: str  # 'alloc' | 'one_hot' | 'gather'

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)

    def nbytes(self, env: dict[str, int]) -> int | None:
        n = deval(dprod(self.shape), env)
        return None if n is None else n * dtype_bytes(self.dtype)


@dataclass
class JitBinding:
    """One discovered jit entry point: ``self.attr = jax.jit(self._f,
    donate_argnums=...)``, ``g = jax.jit(f)`` or ``@jax.jit``."""

    sf: SourceFile
    node: ast.AST  # the binding site (Assign / FunctionDef)
    bind_cls: str | None  # class owning the bound attr (self.attr = ...)
    bind_name: str  # 'train' / 'step'
    impl: _Fn | None
    donate: tuple[int, ...]

    @property
    def key(self) -> str:
        """Stable budget key: '<rel>::<Class.method>'."""
        if self.impl is not None:
            return f"{self.impl.sf.rel}::{self.impl.qualname}"
        return f"{self.sf.rel}::{self.bind_name}"


def _donate_spec(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, int
                    ):
                        out.append(e.value)
                return tuple(out)
    return ()


def _is_jit_name(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] in ("jit", "pjit")


def discover_jit_bindings(
    index: PackageIndex, ctx: ConcurrencyContext
) -> list[JitBinding]:
    """Every jit entry the package binds: decorated defs, module-level
    ``g = jax.jit(f)``, and the TrainStep idiom ``self.train =
    jax.jit(self._impl, ...)``.  ``jax.jit(f).lower().compile()`` AOT
    sites and ``partial``-wrapped inits are not ENTRIES here (their
    impl isn't a plain def reference)."""
    out: list[JitBinding] = []
    seen: set[int] = set()

    def add(b: JitBinding) -> None:
        if b.impl is not None:
            if id(b.impl) in seen:
                return
            seen.add(id(b.impl))
        out.append(b)

    for fn in ctx.fns:
        for dec in fn.node.decorator_list:
            if _is_jit_name(dec) or (
                isinstance(dec, ast.Call)
                and (_is_jit_name(dec.func) or _is_partial_of_jit(dec))
            ):
                donate = (
                    _donate_spec(dec) if isinstance(dec, ast.Call) else ()
                )
                add(JitBinding(fn.sf, fn.node, fn.cls, fn.name, fn, donate))
    for sf in index.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_jit_name(node.value.func)
                and node.value.args
            ):
                continue
            ref = node.value.args[0]
            impl: _Fn | None = None
            cls: str | None = None
            if isinstance(ref, ast.Name):
                impl = ctx.module_fns.get((sf.rel, ref.id))
            elif (
                isinstance(ref, ast.Attribute)
                and isinstance(ref.value, ast.Name)
                and ref.value.id == "self"
            ):
                # find the enclosing class by locating the method that
                # contains this assignment
                for fn in ctx.fns:
                    if fn.sf is sf and fn.cls is not None and any(
                        n is node for n in ast.walk(fn.node)
                    ):
                        cls = fn.cls
                        impl = ctx.methods.get((sf.rel, cls, ref.attr))
                        break
            if impl is None:
                continue
            bind_name = ""
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    bind_name = tgt.id
                elif (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    bind_name = tgt.attr
            add(
                JitBinding(
                    sf, node, cls, bind_name, impl,
                    _donate_spec(node.value),
                )
            )
    return out


def traced_closure(ctx: ConcurrencyContext,
                   entries: Iterable[JitBinding]) -> set[int]:
    """id(_Fn) of every function reachable from a jit entry through
    resolvable calls (cross-module — a superset of XF002's intra-module
    closure), plus nested defs of traced functions (scan bodies)."""
    traced: set[int] = set()
    stack = [b.impl for b in entries if b.impl is not None]
    while stack:
        fn = stack.pop()
        if id(fn) in traced:
            continue
        traced.add(id(fn))
        stack.extend(fn.calls)
        stack.extend(fn.children.values())
    # children of traced fns added above only one level deep; close it
    changed = True
    while changed:
        changed = False
        for fn in ctx.fns:
            if fn.parent is not None and id(fn.parent) in traced and (
                id(fn) not in traced
            ):
                traced.add(id(fn))
                stack = [fn]
                while stack:
                    f = stack.pop()
                    for c in list(f.calls) + list(f.children.values()):
                        if id(c) not in traced:
                            traced.add(id(c))
                            stack.append(c)
                changed = True
    return traced


# -- the interpreter -------------------------------------------------------

_MAX_DEPTH = 14


class Interpreter:
    """Abstract interpretation of one jit entry (and its resolvable
    callees).  ``seed_param`` maps a parameter NAME to an abstract
    value at the entry function only; callee parameters are bound from
    the actual inferred call arguments."""

    def __init__(
        self,
        ctx: ConcurrencyContext,
        seed_param: Callable[[str], Any],
        self_attr: Callable[[str], Any],
    ):
        self.ctx = ctx
        self.seed_param = seed_param
        self.self_attr = self_attr
        self.transients: list[Transient] = []
        self._stack: list[int] = []

    # -- public ------------------------------------------------------------

    def run(self, entry: _Fn) -> Any:
        env: dict[str, Any] = {}
        args = entry.node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.arg == "self":
                env["self"] = "SELF"
            else:
                env[a.arg] = self.seed_param(a.arg)
        try:
            return self._exec_fn(entry, env)
        except RecursionError:  # pragma: no cover - defensive
            return UNK

    # -- statements --------------------------------------------------------

    def _exec_fn(self, fn: _Fn, env: dict[str, Any]) -> Any:
        if len(self._stack) >= _MAX_DEPTH or id(fn) in self._stack:
            return UNK
        self._stack.append(id(fn))
        try:
            rets: list[Any] = []
            self._exec_block(fn, fn.node.body, env, rets)
            out = UNK
            for r in rets:
                out = join(out, r)
            return out
        finally:
            self._stack.pop()

    def _exec_block(self, fn: _Fn, stmts, env, rets) -> None:
        for stmt in stmts:
            self._exec_stmt(fn, stmt, env, rets)

    def _exec_stmt(self, fn: _Fn, stmt: ast.AST, env, rets) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            child = fn.children.get(stmt.name)
            if child is not None:
                env[stmt.name] = FnRefV(child)
        elif isinstance(stmt, ast.Return):
            rets.append(
                self.infer(fn, stmt.value, env)
                if stmt.value is not None
                else UNK
            )
        elif isinstance(stmt, ast.Assign):
            v = self.infer(fn, stmt.value, env)
            for tgt in stmt.targets:
                self._bind(fn, tgt, v, env)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(fn, stmt.target, self.infer(fn, stmt.value, env), env)
        elif isinstance(stmt, ast.AugAssign):
            v = self._binop(
                type(stmt.op),
                self.infer(fn, stmt.target, env),
                self.infer(fn, stmt.value, env),
            )
            self._bind(fn, stmt.target, v, env)
        elif isinstance(stmt, ast.If):
            self.infer(fn, stmt.test, env)
            self._exec_block(fn, stmt.body, env, rets)
            self._exec_block(fn, stmt.orelse, env, rets)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.infer(fn, stmt.iter, env)
            self._bind_loop_target(fn, stmt.target, it, env)
            self._exec_block(fn, stmt.body, env, rets)
            self._exec_block(fn, stmt.orelse, env, rets)
        elif isinstance(stmt, ast.While):
            self.infer(fn, stmt.test, env)
            self._exec_block(fn, stmt.body, env, rets)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.infer(fn, item.context_expr, env)
            self._exec_block(fn, stmt.body, env, rets)
        elif isinstance(stmt, ast.Try):
            self._exec_block(fn, stmt.body, env, rets)
            for h in stmt.handlers:
                self._exec_block(fn, h.body, env, rets)
            self._exec_block(fn, stmt.orelse, env, rets)
            self._exec_block(fn, stmt.finalbody, env, rets)
        elif isinstance(stmt, ast.Expr):
            self.infer(fn, stmt.value, env)
        # Import / Raise / Pass / Assert / Delete / Global: no flow

    def _bind(self, fn: _Fn, tgt: ast.AST, v: Any, env) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = v
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            items = None
            if isinstance(v, TupV):
                items = v.items
            elif isinstance(v, ShapeV):
                items = tuple(DimV(d) for d in v.dims)
            for i, sub in enumerate(tgt.elts):
                if isinstance(sub, ast.Starred):
                    self._bind(fn, sub.value, UNK, env)
                    continue
                sv = (
                    items[i]
                    if items is not None and i < len(items)
                    else UNK
                )
                self._bind(fn, sub, sv, env)
        elif isinstance(tgt, ast.Subscript):
            base = tgt.value
            if isinstance(base, ast.Name):
                m = env.get(base.id)
                if isinstance(m, MapV):
                    key = self._const_key(fn, tgt.slice, env)
                    if key is not None:
                        m.known[key] = v
                    else:
                        m.default = join(
                            m.default if not callable(m.default) else UNK, v
                        )
        # self.attr = ... : not tracked (entry seeds cover self state)

    def _bind_loop_target(self, fn: _Fn, tgt: ast.AST, it: Any, env) -> None:
        if isinstance(it, ItemsV):
            elem = TupV((UNK, self._map_join_values(it.m)))
        elif isinstance(it, MapV):
            elem = UNK  # iterating a dict yields keys
        elif isinstance(it, TupV):
            e = UNK
            for x in it.items:
                e = join(e, x)
            elem = e
        elif isinstance(it, ArrV) and it.shape:
            elem = ArrV(it.shape[1:], it.dtype)
        else:
            elem = UNK
        self._bind(fn, tgt, elem, env)

    @staticmethod
    def _map_join_values(m: MapV) -> Any:
        out = m.default() if callable(m.default) else (m.default or UNK)
        for v in m.known.values():
            out = join(out, v)
        return out

    def _const_key(self, fn: _Fn, expr: ast.AST, env) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        v = self.infer(fn, expr, env)
        if isinstance(v, StrV):
            return v.s
        return None

    # -- expressions --------------------------------------------------------

    def infer(self, fn: _Fn, expr: ast.AST | None, env) -> Any:
        if expr is None:
            return UNK
        try:
            return self._infer(fn, expr, env)
        except RecursionError:  # pragma: no cover - defensive
            raise
        except Exception:  # noqa: BLE001 - arbitrary scanned code
            return UNK

    def _infer(self, fn: _Fn, expr: ast.AST, env) -> Any:
        if isinstance(expr, ast.Name):
            return env.get(expr.id, UNK)
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, bool):
                return ArrV((), "bool")
            if isinstance(expr.value, int):
                return DimV(dconst(expr.value))
            if isinstance(expr.value, str):
                return StrV(expr.value)
            if isinstance(expr.value, float):
                return ArrV((), None)  # scalar: broadcasts shape-free
            return UNK
        if isinstance(expr, ast.Attribute):
            return self._attr(fn, expr, env)
        if isinstance(expr, ast.Subscript):
            return self._subscript(fn, expr, env)
        if isinstance(expr, ast.Call):
            return self._call(fn, expr, env)
        if isinstance(expr, ast.BinOp):
            return self._binop(
                type(expr.op),
                self._infer(fn, expr.left, env),
                self._infer(fn, expr.right, env),
            )
        if isinstance(expr, ast.UnaryOp):
            v = self._infer(fn, expr.operand, env)
            if isinstance(expr.op, ast.USub) and isinstance(v, DimV):
                return DimV(dbin("-", dconst(0), v.d))
            return v if isinstance(v, ArrV) else UNK
        if isinstance(expr, ast.Compare):
            left = self._infer(fn, expr.left, env)
            rights = [self._infer(fn, c, env) for c in expr.comparators]
            ops = [left] + rights
            if any(v is UNK for v in ops):
                return UNK  # an unknown operand means an unknown shape
            arrs = [v for v in ops if isinstance(v, ArrV)]
            if arrs:
                return ArrV(_broadcast([a.shape for a in arrs]), "bool")
            return UNK
        if isinstance(expr, (ast.Tuple, ast.List)):
            return TupV(
                tuple(self._infer(fn, e, env) for e in expr.elts)
            )
        if isinstance(expr, ast.Dict):
            known: dict[str, Any] = {}
            default: Any = None
            for k, v in zip(expr.keys, expr.values):
                val = self._infer(fn, v, env)
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    known[k.value] = val
                elif k is None:  # **spread
                    sv = val
                    if isinstance(sv, MapV):
                        known.update(sv.known)
                        default = sv.default
                else:
                    key = self._const_key(fn, k, env)
                    if key is not None:
                        known[key] = val
                    else:
                        default = join(default, val)
            return MapV(known, default)
        if isinstance(expr, ast.DictComp):
            return self._dictcomp(fn, expr, env)
        if isinstance(expr, ast.IfExp):
            return join(
                self._infer(fn, expr.body, env),
                self._infer(fn, expr.orelse, env),
            )
        if isinstance(expr, ast.Lambda):
            return UNK
        if isinstance(expr, ast.Starred):
            return self._infer(fn, expr.value, env)
        return UNK

    def _dictcomp(self, fn: _Fn, expr: ast.DictComp, env) -> Any:
        if len(expr.generators) != 1:
            return UNK
        gen = expr.generators[0]
        it = self.infer(fn, gen.iter, env)
        local = dict(env)

        def eval_one(key_name: str | None, val: Any) -> tuple[str | None, Any]:
            self._bind(fn, gen.target, _items_elem(key_name, val), local)
            k = self._const_key(fn, expr.key, local)
            return k, self.infer(fn, expr.value, local)

        if isinstance(it, ItemsV):
            m = it.m
            known: dict[str, Any] = {}
            for k, v in m.known.items():
                kk, vv = eval_one(k, v)
                known[kk if kk is not None else k] = vv
            default = None
            d = m.default() if callable(m.default) else m.default
            if d is not None:
                _, default = eval_one(None, d)
            return MapV(known, default)
        if isinstance(it, TupV):
            known = {}
            default = None
            for item in it.items:
                self._bind(fn, gen.target, item, local)
                k = self._const_key(fn, expr.key, local)
                v = self.infer(fn, expr.value, local)
                if k is not None:
                    known[k] = v
                else:
                    default = join(default, v)
            return MapV(known, default)
        # unknown iterable: evaluate once with UNK bindings
        self._bind(fn, gen.target, UNK, local)
        return MapV({}, self.infer(fn, expr.value, local))

    def _attr(self, fn: _Fn, expr: ast.Attribute, env) -> Any:
        base = self._infer(fn, expr.value, env)
        if base == "SELF":
            if expr.attr == "cfg":
                return self.self_attr("cfg")
            return self.self_attr(expr.attr)
        if isinstance(base, ConfigV):
            sym = CONFIG_SYMS.get(expr.attr)
            return DimV(dsym(sym)) if sym else UNK
        if isinstance(base, ArrV):
            if expr.attr == "shape":
                return ShapeV(base.shape)
            if expr.attr == "at":
                return AtV(base)
            if expr.attr == "T" and len(base.shape) == 2:
                return ArrV((base.shape[1], base.shape[0]), base.dtype)
            return UNK
        return UNK

    def _subscript(self, fn: _Fn, expr: ast.Subscript, env) -> Any:
        base = self._infer(fn, expr.value, env)
        if isinstance(base, AtV):
            return AtIdxV(base.arr, self._infer(fn, expr.slice, env))
        if isinstance(base, MapV):
            return base.lookup(self._const_key(fn, expr.slice, env))
        if isinstance(base, (TupV, ShapeV)):
            items = (
                base.items
                if isinstance(base, TupV)
                else tuple(DimV(d) for d in base.dims)
            )
            idx = expr.slice
            if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
                i = idx.value
                if -len(items) <= i < len(items):
                    return items[i]
                return UNK
            if isinstance(idx, ast.Slice):
                lo = idx.lower.value if isinstance(
                    idx.lower, ast.Constant
                ) else None
                hi = idx.upper.value if isinstance(
                    idx.upper, ast.Constant
                ) else None
                sub = items[slice(lo, hi)]
                if isinstance(base, ShapeV):
                    return ShapeV(tuple(d.d for d in sub))
                return TupV(sub)
            return UNK
        if isinstance(base, ArrV):
            return self._index_arr(fn, base, expr.slice, env)
        return UNK

    def _index_arr(self, fn: _Fn, base: ArrV, idx: ast.AST, env) -> Any:
        shape = base.shape
        if isinstance(idx, ast.Tuple):
            dims: list[Dim] = []
            pos = 0
            for el in idx.elts:
                if isinstance(el, ast.Constant) and el.value is None:
                    dims.append(dconst(1))
                    continue
                if pos >= len(shape):
                    return UNK
                if isinstance(el, ast.Slice):
                    d = self._slice_dim(fn, shape[pos], el, env)
                    if d is None:
                        return UNK
                    dims.append(d)
                    pos += 1
                    continue
                v = self._infer(fn, el, env)
                if isinstance(v, (DimV,)):
                    pos += 1  # integer index drops the dim
                    continue
                return UNK  # advanced indexing inside a tuple: punt
            dims.extend(shape[pos:])
            return ArrV(tuple(dims), base.dtype)
        if isinstance(idx, ast.Slice):
            d = self._slice_dim(fn, shape[0] if shape else None, idx, env)
            if d is None or not shape:
                return UNK
            return ArrV((d,) + shape[1:], base.dtype)
        v = self._infer(fn, idx, env)
        if isinstance(v, DimV):
            return ArrV(shape[1:], base.dtype) if shape else UNK
        if isinstance(v, ArrV):
            # gather: idx.shape + base.shape[1:]
            out = ArrV(v.shape + shape[1:], base.dtype)
            self._record(fn, idx, out, "gather")
            return out
        return UNK

    def _slice_dim(
        self, fn: _Fn, dim0: Dim | None, sl: ast.Slice, env
    ) -> Dim | None:
        if sl.step is not None:
            return None
        lo: Dim = dconst(0)
        if sl.lower is not None:
            v = self._infer(fn, sl.lower, env)
            if not isinstance(v, DimV):
                return None
            lo = v.d
        if sl.upper is None:
            if dim0 is None:
                return None
            return dbin("-", dim0, lo)
        v = self._infer(fn, sl.upper, env)
        if not isinstance(v, DimV):
            return None
        return dbin("-", v.d, lo)

    def _binop(self, op: type, left: Any, right: Any) -> Any:
        ops = {
            ast.Add: "+", ast.Sub: "-", ast.Mult: "*",
            ast.FloorDiv: "//", ast.Mod: "%",
        }
        if isinstance(left, DimV) and isinstance(right, DimV):
            sym = ops.get(op)
            if sym is not None:
                return DimV(dbin(sym, left.d, right.d))
            return UNK
        # tuple concat: (a, b) + shape[1:]
        if op is ast.Add and isinstance(left, (TupV, ShapeV)) and isinstance(
            right, (TupV, ShapeV)
        ):
            def as_items(v):
                return (
                    v.items
                    if isinstance(v, TupV)
                    else tuple(DimV(d) for d in v.dims)
                )

            return TupV(as_items(left) + as_items(right))
        if left is UNK or right is UNK:
            return UNK  # an unknown operand means an unknown shape
        arrs = [v for v in (left, right) if isinstance(v, ArrV)]
        if arrs:
            dtype = arrs[0].dtype
            return ArrV(_broadcast([a.shape for a in arrs]), dtype)
        return UNK

    # -- calls --------------------------------------------------------------

    def _call(self, fn: _Fn, call: ast.Call, env) -> Any:
        func = call.func
        # in-package resolution first: a local FnRef (scan body), then
        # the ConcurrencyContext resolver (self.m / module f / mod.f)
        callee: _Fn | None = None
        if isinstance(func, ast.Name) and isinstance(
            env.get(func.id), FnRefV
        ):
            callee = env[func.id].fn
        if callee is None:
            callee = self.ctx._resolve_call(fn, fn.sf, call)
        if callee is not None and callee.name != "__init__":
            return self._interproc(fn, callee, call, env)

        name = dotted_name(func)
        leaf = (
            name.rsplit(".", 1)[-1]
            if name
            else (func.attr if isinstance(func, ast.Attribute) else None)
        )
        if leaf is None:
            return UNK
        if leaf == "scan":
            return self._scan(fn, call, env)
        if leaf in _ALLOC_LEAVES:
            return self._alloc(fn, call, env)
        if leaf in _ALLOC_LIKE_LEAVES:
            src = self.infer(fn, call.args[0], env) if call.args else UNK
            if isinstance(src, ArrV):
                out = ArrV(src.shape, src.dtype)
                self._record(fn, call, out, "alloc")
                return out
            return UNK
        if leaf == "one_hot":
            x = self.infer(fn, call.args[0], env) if call.args else UNK
            n = (
                self.infer(fn, call.args[1], env)
                if len(call.args) > 1
                else UNK
            )
            if isinstance(x, ArrV) and isinstance(n, DimV):
                out = ArrV(x.shape + (n.d,), self._dtype_kw(fn, call, env))
                self._record(fn, call, out, "one_hot")
                return out
            return UNK
        if leaf in _DTYPE_LEAVES and call.args:
            # jnp.int32(x) scalar casts: shape-free, broadcast-neutral
            v = self.infer(fn, call.args[0], env)
            if isinstance(v, ArrV):
                return ArrV(v.shape, _DTYPE_LEAVES[leaf])
            return ArrV((), _DTYPE_LEAVES[leaf])
        if leaf == "arange":
            n = self.infer(fn, call.args[0], env) if call.args else UNK
            if isinstance(n, DimV):
                return ArrV((n.d,), "int32")
            return UNK
        if leaf in ("concatenate", "stack"):
            return self._concat(fn, call, env, stacked=leaf == "stack")
        if leaf == "reshape" and name is not None:
            # jnp.reshape(x, shape)
            if len(call.args) >= 2:
                x = self.infer(fn, call.args[0], env)
                return self._reshape(fn, x, [call.args[1]], env)
            return UNK
        if leaf == "segment_sum":
            data = self.infer(fn, call.args[0], env) if call.args else UNK
            nseg = None
            for kw in call.keywords:
                if kw.arg == "num_segments":
                    nseg = self.infer(fn, kw.value, env)
            if len(call.args) > 2 and nseg is None:
                nseg = self.infer(fn, call.args[2], env)
            if isinstance(data, ArrV) and isinstance(nseg, DimV):
                out = ArrV((nseg.d,) + data.shape[1:], data.dtype)
                self._record(fn, call, out, "alloc")
                return out
            return UNK
        if leaf in _ELEMWISE_LEAVES:
            vals = [self.infer(fn, a, env) for a in call.args]
            if any(v is UNK for v in vals):
                return UNK  # an unknown operand means an unknown shape
            arrs = [v for v in vals if isinstance(v, ArrV)]
            if arrs:
                return ArrV(_broadcast([a.shape for a in arrs]), arrs[0].dtype)
            return UNK
        if leaf in _REDUCE_LEAVES and name is not None and name.split(
            ".", 1
        )[0] in ("jnp", "np", "numpy", "jax"):
            x = self.infer(fn, call.args[0], env) if call.args else UNK
            if isinstance(x, ArrV):
                for kw in call.keywords:
                    if kw.arg == "axis":
                        ax = kw.value
                        if isinstance(ax, ast.Constant) and isinstance(
                            ax.value, int
                        ) and x.shape:
                            s = list(x.shape)
                            if -len(s) <= ax.value < len(s):
                                s.pop(ax.value)
                                return ArrV(tuple(s), x.dtype)
                        return UNK
                return ArrV((), x.dtype)
            return UNK
        if leaf in ("cumsum", "take", "asarray", "argsort"):
            x = self.infer(fn, call.args[0], env) if call.args else UNK
            if leaf == "take" and isinstance(x, ArrV) and len(call.args) > 1:
                idx = self.infer(fn, call.args[1], env)
                if isinstance(idx, ArrV):
                    axis0 = not any(
                        kw.arg == "axis" and not (
                            isinstance(kw.value, ast.Constant)
                            and kw.value.value == 0
                        )
                        for kw in call.keywords
                    )
                    if axis0:
                        out = ArrV(idx.shape + x.shape[1:], x.dtype)
                        self._record(fn, call, out, "gather")
                        return out
                return UNK
            return x if isinstance(x, ArrV) else UNK
        # method calls x.m(...)
        if isinstance(func, ast.Attribute):
            return self._method(fn, func, call, env)
        return UNK

    def _method(
        self, fn: _Fn, func: ast.Attribute, call: ast.Call, env
    ) -> Any:
        recv = self._infer(fn, func.value, env)
        m = func.attr
        if isinstance(recv, MapV):
            if m == "items":
                return ItemsV(recv)
            if m in ("pop", "get") and call.args:
                return recv.lookup(self._const_key(fn, call.args[0], env))
            if m in ("keys", "values"):
                return UNK
            return UNK
        if isinstance(recv, AtIdxV):
            if m in ("add", "set", "mul", "min", "max", "apply"):
                return recv.arr
            if m == "get":
                if isinstance(recv.idx, ArrV):
                    out = ArrV(
                        recv.idx.shape + recv.arr.shape[1:], recv.arr.dtype
                    )
                    self._record(fn, call, out, "gather")
                    return out
                return UNK
            return UNK
        if isinstance(recv, ArrV):
            if m == "reshape":
                return self._reshape(fn, recv, call.args, env)
            if m == "astype":
                dt = self._dtype_of(fn, call.args[0], env) if call.args else None
                return ArrV(recv.shape, dt or recv.dtype)
            if m == "swapaxes" and len(call.args) == 2:
                a, b = (
                    self.infer(fn, call.args[0], env),
                    self.infer(fn, call.args[1], env),
                )
                if (
                    isinstance(a, DimV) and a.d[0] == "c"
                    and isinstance(b, DimV) and b.d[0] == "c"
                ):
                    i, j = a.d[1], b.d[1]
                    s = list(recv.shape)
                    if 0 <= i < len(s) and 0 <= j < len(s):
                        s[i], s[j] = s[j], s[i]
                        return ArrV(tuple(s), recv.dtype)
                return UNK
            if m == "transpose":
                return UNK
            if m in _SAMESHAPE_METHODS:
                return recv
            if m in _REDUCE_LEAVES:
                return ArrV((), recv.dtype)
            return UNK
        return UNK

    def _reshape(self, fn: _Fn, x: Any, args: list, env) -> Any:
        if not isinstance(x, ArrV):
            return UNK
        dim_exprs: list[Any]
        if len(args) == 1:
            v = self.infer(fn, args[0], env)
            if isinstance(v, (TupV, ShapeV)):
                dim_exprs = list(
                    v.items
                    if isinstance(v, TupV)
                    else tuple(DimV(d) for d in v.dims)
                )
            elif isinstance(v, DimV):
                dim_exprs = [v]
            else:
                return UNK
        else:
            dim_exprs = [self.infer(fn, a, env) for a in args]
        dims: list[Dim | None] = []
        minus_one_at = None
        for i, v in enumerate(dim_exprs):
            if isinstance(v, DimV):
                if v.d == dconst(-1):
                    minus_one_at = i
                    dims.append(None)
                else:
                    dims.append(v.d)
            else:
                return UNK
        total = dprod(x.shape)
        if minus_one_at is not None:
            known = dprod(d for d in dims if d is not None)
            dims[minus_one_at] = dbin("//", total, known)
        return ArrV(tuple(d for d in dims if d is not None), x.dtype)

    def _concat(self, fn: _Fn, call: ast.Call, env, stacked: bool) -> Any:
        if not call.args:
            return UNK
        seq = self.infer(fn, call.args[0], env)
        if not isinstance(seq, TupV):
            return UNK
        arrs = [v for v in seq.items if isinstance(v, ArrV)]
        if len(arrs) != len(seq.items) or not arrs:
            return UNK
        axis = 0
        for kw in call.keywords:
            if kw.arg == "axis" and isinstance(
                kw.value, ast.Constant
            ) and isinstance(kw.value.value, int):
                axis = kw.value.value
        if len(call.args) > 1 and isinstance(
            call.args[1], ast.Constant
        ) and isinstance(call.args[1].value, int):
            axis = call.args[1].value
        base = arrs[0].shape
        if stacked:
            out_shape = (
                base[:axis] + (dconst(len(arrs)),) + base[axis:]
            )
            return ArrV(out_shape, arrs[0].dtype)
        if not all(len(a.shape) == len(base) for a in arrs):
            return UNK
        if axis < 0:
            axis += len(base)
        if not 0 <= axis < len(base):
            return UNK
        cat = arrs[0].shape[axis]
        for a in arrs[1:]:
            cat = dbin("+", cat, a.shape[axis])
        return ArrV(
            base[:axis] + (cat,) + base[axis + 1:], arrs[0].dtype
        )

    def _scan(self, fn: _Fn, call: ast.Call, env) -> Any:
        """jax.lax.scan(body, init, xs): analyze the body with
        carry=init and x = xs stripped of its leading (slice) axis."""
        if len(call.args) < 2:
            return UNK
        body_v = self.infer(fn, call.args[0], env)
        init = self.infer(fn, call.args[1], env)
        xs = self.infer(fn, call.args[2], env) if len(call.args) > 2 else UNK
        if not isinstance(body_v, FnRefV):
            return UNK
        body = body_v.fn
        benv = dict(env)
        args = body.node.args
        names = [a.arg for a in args.posonlyargs + args.args]
        if len(names) >= 1:
            benv[names[0]] = init
        if len(names) >= 2:
            benv[names[1]] = strip_leading(xs)
        ret = self._exec_fn(body, benv)
        if isinstance(ret, TupV) and len(ret.items) == 2:
            return TupV((ret.items[0], UNK))
        return UNK

    def _interproc(self, fn: _Fn, callee: _Fn, call: ast.Call, env) -> Any:
        cenv: dict[str, Any] = {}
        args = callee.node.args
        params = [a.arg for a in args.posonlyargs + args.args]
        if params and params[0] == "self":
            cenv["self"] = "SELF"
            params = params[1:]
        vals = [self.infer(fn, a, env) for a in call.args]
        for p, v in zip(params, vals):
            cenv[p] = v
        for kw in call.keywords:
            if kw.arg is not None and (
                kw.arg in params
                or kw.arg in [a.arg for a in args.kwonlyargs]
            ):
                cenv[kw.arg] = self.infer(fn, kw.value, env)
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            cenv.setdefault(a.arg, UNK)
        return self._exec_fn(callee, cenv)

    # -- misc ---------------------------------------------------------------

    def _dtype_kw(self, fn: _Fn, call: ast.Call, env) -> str | None:
        for kw in call.keywords:
            if kw.arg == "dtype":
                return self._dtype_of(fn, kw.value, env)
        return None

    def _dtype_of(self, fn: _Fn, expr: ast.AST, env) -> str | None:
        name = dotted_name(expr)
        if name is not None:
            leaf = name.rsplit(".", 1)[-1]
            return _DTYPE_LEAVES.get(leaf)
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        return None

    def _alloc(self, fn: _Fn, call: ast.Call, env) -> Any:
        if not call.args:
            return UNK
        shape_v = self.infer(fn, call.args[0], env)
        dims: tuple[Dim, ...] | None = None
        if isinstance(shape_v, DimV):
            dims = (shape_v.d,)
        elif isinstance(shape_v, (TupV, ShapeV)):
            items = (
                shape_v.items
                if isinstance(shape_v, TupV)
                else tuple(DimV(d) for d in shape_v.dims)
            )
            if all(isinstance(i, DimV) for i in items):
                dims = tuple(i.d for i in items)
        if dims is None:
            return UNK
        dt = self._dtype_kw(fn, call, env)
        if dt is None and len(call.args) > 1:
            dt = self._dtype_of(fn, call.args[1], env)
        if dt is None and len(call.args) > 2:  # full(shape, fill, dtype)
            dt = self._dtype_of(fn, call.args[2], env)
        out = ArrV(dims, dt)
        self._record(fn, call, out, "alloc")
        return out

    def _record(self, fn: _Fn, node: ast.AST, arr: ArrV, kind: str) -> None:
        self.transients.append(
            Transient(fn.sf, node, arr.shape, arr.dtype, kind)
        )


def _items_elem(key: str | None, val: Any) -> TupV:
    return TupV((StrV(key) if key is not None else UNK, val))


def strip_leading(v: Any) -> Any:
    """The per-iteration element of a scanned/stacked value: every array
    loses its leading axis."""
    if isinstance(v, ArrV) and v.shape:
        return ArrV(v.shape[1:], v.dtype)
    if isinstance(v, MapV):
        return MapV(
            {k: strip_leading(x) for k, x in v.known.items()},
            strip_leading(v.default() if callable(v.default) else v.default)
            if v.default is not None
            else None,
        )
    if isinstance(v, TupV):
        return TupV(tuple(strip_leading(x) for x in v.items))
    return UNK


def join(a: Any, b: Any) -> Any:
    """Best-effort join: prefer the known side; per-key for maps (an
    ``_expand_wire`` that returns the input batch on one path and a
    rebuilt dict on another keeps the seeded plane shapes)."""
    if a is UNK or a is None:
        return b
    if b is UNK or b is None:
        return a
    if isinstance(a, MapV) and isinstance(b, MapV):
        known = dict(a.known)
        for k, v in b.known.items():
            known[k] = join(known.get(k, UNK), v)
        ad = a.default() if callable(a.default) else a.default
        bd = b.default() if callable(b.default) else b.default
        return MapV(known, join(ad, bd) if (ad or bd) else None)
    if isinstance(a, TupV) and isinstance(b, TupV) and len(a.items) == len(
        b.items
    ):
        return TupV(
            tuple(join(x, y) for x, y in zip(a.items, b.items))
        )
    return a


def _broadcast(shapes: list[tuple[Dim, ...]]) -> tuple[Dim, ...]:
    """Right-aligned broadcast; on symbolic disagreement the first
    non-1 dim wins (heuristic — sizes, not correctness, are at stake)."""
    rank = max(len(s) for s in shapes)
    out: list[Dim] = []
    for i in range(rank):
        dim = dconst(1)
        for s in shapes:
            j = i - (rank - len(s))
            if j < 0:
                continue
            d = s[j]
            if d == dconst(1):
                continue
            if dim == dconst(1):
                dim = d
        out.append(dim)
    return tuple(out)


# -- memory context (cached per index, like ConcurrencyContext) ------------


class MemoryContext:
    """Jit entries + per-entry transient flows, computed once and shared
    by XF010/XF011/XF013/XF014."""

    def __init__(
        self,
        index: PackageIndex,
        seed_param: Callable[[str], Any],
        self_attr: Callable[[str], Any],
    ):
        self.index = index
        self.ctx = get_context(index)
        self.bindings = discover_jit_bindings(index, self.ctx)
        self.traced = traced_closure(self.ctx, self.bindings)
        self.flows: dict[str, list[Transient]] = {}
        for b in self.bindings:
            if b.impl is None:
                continue
            interp = Interpreter(self.ctx, seed_param, self_attr)
            try:
                interp.run(b.impl)
            except Exception:  # noqa: BLE001 - never crash the pass
                continue
            # dedupe by site within one entry (loops/branches revisit
            # the same node); col_offset keeps two same-shape
            # allocations on ONE source line distinct — dropping one
            # would under-count the XF014 upper bound
            seen: set[tuple[str, int, int, str]] = set()
            uniq: list[Transient] = []
            for t in interp.transients:
                key = (
                    t.sf.rel,
                    t.line,
                    getattr(t.node, "col_offset", 0),
                    shape_str(t.shape),
                )
                if key not in seen:
                    seen.add(key)
                    uniq.append(t)
            self.flows[b.key] = uniq


def get_memory_context(
    index: PackageIndex,
    seed_param: Callable[[str], Any],
    self_attr: Callable[[str], Any],
) -> MemoryContext:
    # keyed by the seed functions: a caller with DIFFERENT seeds must
    # not silently receive flows computed under someone else's
    cache: dict = getattr(index, "_memory_ctx", None)
    if cache is None:
        cache = {}
        index._memory_ctx = cache
    key = (id(seed_param), id(self_attr))
    ctx = cache.get(key)
    if ctx is None:
        ctx = MemoryContext(index, seed_param, self_attr)
        cache[key] = ctx
    return ctx
