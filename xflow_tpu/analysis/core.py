"""Rule engine: file walking, AST parsing, pragma suppression, and the
rule registry the five XF rules plug into.

Design constraints:

* pure stdlib ``ast`` — the pass never imports or executes the code
  under analysis, so it works on files whose imports this environment
  lacks and needs no functional accelerator backend;
* cross-file rules — XF004 (schema drift) and XF005 (ABI parity) need
  the whole scanned tree at once, so rules receive a ``PackageIndex``
  rather than one file at a time;
* suppression is data, not control flow — pragmas and the baseline are
  applied to the collected findings AFTER every rule ran, so reporters
  can show what was suppressed and a stale pragma/baseline entry is
  visible instead of silently eating future findings.

Pragma syntax (matched ONLY inside real ``#`` comments, via tokenize —
prose in docstrings like this one never registers): ``xf: ignore[XF001]``
suppresses that rule on the comment's line (a comment-ONLY pragma line
also covers the next line); ``xf: ignore-file[XF001,XF003]`` suppresses
for the whole file; bare ``xf: ignore`` suppresses every rule on the
line.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Iterable, Iterator

_PRAGMA_RE = re.compile(
    r"\bxf:\s*ignore(?P<scope>-file)?(?:\[(?P<rules>[A-Z0-9,\s]+)\])?"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one site.  ``key()`` (rule, path, message)
    deliberately excludes the line number so baseline entries survive
    unrelated edits that shift lines."""

    rule: str
    path: str  # scan-relative, posix separators
    line: int
    message: str

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


class SourceFile:
    """One parsed python file plus its suppression pragmas."""

    def __init__(self, abspath: str, rel: str, source: str):
        self.abspath = abspath
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        try:
            self.tree: ast.Module | None = ast.parse(source)
        except SyntaxError:
            self.tree = None
        self.file_ignores: set[str] = set()
        self.line_ignores: dict[int, set[str]] = {}
        # pragmas live in COMMENT tokens only: docstrings or string
        # literals that merely DESCRIBE the syntax never register
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(source).readline)
            )
        except (tokenize.TokenError, SyntaxError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if m is None:
                continue
            rules = m.group("rules")
            ids = (
                {r.strip() for r in rules.split(",") if r.strip()}
                if rules
                else {"*"}
            )
            lineno = tok.start[0]
            if m.group("scope"):
                self.file_ignores |= ids
            else:
                self.line_ignores.setdefault(lineno, set()).update(ids)
                if tok.line[: tok.start[1]].strip() == "":
                    # standalone pragma comment: also covers the
                    # statement starting on the next line
                    self.line_ignores.setdefault(
                        lineno + 1, set()
                    ).update(ids)

    def suppressed(self, finding: Finding) -> bool:
        if {"*", finding.rule} & self.file_ignores:
            return True
        at_line = self.line_ignores.get(finding.line, set())
        return bool({"*", finding.rule} & at_line)


class PackageIndex:
    """Every python file under the scanned paths, parsed once, plus the
    scan roots (XF005 probes them for the non-python ABI files)."""

    def __init__(self, paths: Iterable[str]):
        self.roots: list[str] = []
        self.files: list[SourceFile] = []
        seen: set[str] = set()
        for path in paths:
            path = os.path.abspath(path)
            if os.path.isdir(path):
                self.roots.append(path)
                for f in sorted(_walk_py(path)):
                    self._add(f, os.path.relpath(f, path), seen)
            elif path.endswith(".py"):
                self.roots.append(os.path.dirname(path))
                self._add(path, os.path.basename(path), seen)
            else:
                raise FileNotFoundError(
                    f"not a directory or .py file: {path}"
                )

    def _add(self, abspath: str, rel: str, seen: set[str]) -> None:
        if abspath in seen:
            return
        seen.add(abspath)
        with open(abspath, encoding="utf-8", errors="replace") as f:
            source = f.read()
        self.files.append(
            SourceFile(abspath, rel.replace(os.sep, "/"), source)
        )

    def by_rel(self, suffix: str) -> SourceFile | None:
        """The file whose scan-relative path ends with ``suffix``."""
        for f in self.files:
            if f.rel == suffix or f.rel.endswith("/" + suffix):
                return f
        return None


def _walk_py(root: str) -> Iterator[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d
            for d in dirnames
            if not d.startswith(".") and d != "__pycache__"
        ]
        for name in filenames:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


class Rule:
    """Base class: subclasses set ``id``/``title`` and implement
    ``run(index)``.  Instantiating registers nothing — the registry is
    the explicit ``all_rules()`` list so test fixtures can run subsets."""

    id: str = "XF000"
    title: str = ""

    def run(self, index: PackageIndex) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, sf: SourceFile, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=sf.rel,
            line=getattr(node, "lineno", 0),
            message=message,
        )


def all_rules() -> list[Rule]:
    from xflow_tpu.analysis.rules_abi import CAbiParity
    from xflow_tpu.analysis.rules_concurrency import (
        HeartbeatCoverage,
        LockOrder,
        SharedStateDiscipline,
        ThreadLifecycle,
    )
    from xflow_tpu.analysis.rules_jax import HiddenHostSyncs, RecompileHazards
    from xflow_tpu.analysis.rules_memory import (
        DonationSafety,
        DtypeDiscipline,
        FullTableTransient,
        ShardingCoverage,
        TransientBudget,
    )
    from xflow_tpu.analysis.rules_protocol import (
        BlockingIoTimeout,
        CodecParity,
        DeterminismTaint,
        ExplicitEndian,
        FailpointCoverage,
    )
    from xflow_tpu.analysis.rules_robustness import SwallowedWorkerException
    from xflow_tpu.analysis.rules_schema import SchemaDrift
    from xflow_tpu.analysis.rules_threads import LockDiscipline

    return [
        RecompileHazards(),
        HiddenHostSyncs(),
        LockDiscipline(),
        SchemaDrift(),
        CAbiParity(),
        ThreadLifecycle(),
        LockOrder(),
        SharedStateDiscipline(),
        HeartbeatCoverage(),
        FullTableTransient(),
        DtypeDiscipline(),
        ShardingCoverage(),
        DonationSafety(),
        TransientBudget(),
        SwallowedWorkerException(),
        CodecParity(),
        BlockingIoTimeout(),
        FailpointCoverage(),
        DeterminismTaint(),
        ExplicitEndian(),
    ]


def run_analysis(
    paths: Iterable[str],
    rules: Iterable[Rule] | None = None,
    select: Iterable[str] | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """Run the rule set over ``paths``.

    Returns ``(findings, pragma_suppressed)`` — baseline filtering is a
    separate step (baseline.split_baselined) so callers can report the
    grandfathered set.  ``paths`` may be a ready-built ``PackageIndex``
    so callers that also need the index (scripts/check_memory.py's
    estimate report) parse and interpret the tree once, not twice.
    """
    index = paths if isinstance(paths, PackageIndex) else PackageIndex(paths)
    rule_list = list(rules) if rules is not None else all_rules()
    if select is not None:
        wanted = set(select)
        unknown = wanted - {r.id for r in rule_list}
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
        rule_list = [r for r in rule_list if r.id in wanted]
    by_rel = {f.rel: f for f in index.files}
    active: list[Finding] = []
    suppressed: list[Finding] = []
    seen: set[tuple] = set()
    for rule in rule_list:
        for finding in rule.run(index):
            # dedupe: e.g. a jit inside nested loops matches the
            # loop-body scan once per enclosing loop
            dupkey = (finding.rule, finding.path, finding.line,
                      finding.message)
            if dupkey in seen:
                continue
            seen.add(dupkey)
            sf = by_rel.get(finding.path)
            if sf is not None and sf.suppressed(finding):
                suppressed.append(finding)
            else:
                active.append(finding)
    order = {r.id: i for i, r in enumerate(rule_list)}
    active.sort(key=lambda f: (f.path, f.line, order.get(f.rule, 99)))
    suppressed.sort(key=lambda f: (f.path, f.line))
    return active, suppressed


# -- shared AST helpers (used by several rules) ---------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); None when the
    expression isn't a plain dotted path."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_callable(node: ast.AST) -> bool:
    """Does this expression name jax's jit entry point?  Accepts
    ``jax.jit``, ``jit``, ``pjit``, ``jax.experimental.pjit.pjit`` —
    anything whose dotted path ends in jit/pjit."""
    name = dotted_name(node)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    return leaf in ("jit", "pjit")


def jit_call(node: ast.AST) -> ast.Call | None:
    """The ``jax.jit(...)`` Call when ``node`` is one, else None."""
    if isinstance(node, ast.Call) and is_jit_callable(node.func):
        return node
    return None


def walk_scoped(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does NOT descend into nested function/class
    definitions — the body of a nested def is its own scope."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(
            child,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
        ):
            stack.extend(ast.iter_child_nodes(child))
