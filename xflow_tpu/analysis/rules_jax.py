"""XF001 (recompile hazards) and XF002 (hidden host syncs).

These guard the two PR-2 serving/trainer invariants that die silently:
the no-recompile guarantee (PredictEngine buckets + one jit per
TrainStep — docs/SERVING.md) and the phase-accounting contract (every
host sync is booked under an obs phase so exclusive phases cover >= 90%
of wall-clock — docs/OBSERVABILITY.md, scripts/check_metrics_schema.py).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    dotted_name,
    jit_call,
    walk_scoped,
)

_STATIC_KWARGS = ("static_argnums", "static_argnames")


def _jit_has_static(call: ast.Call) -> bool:
    return any(kw.arg in _STATIC_KWARGS for kw in call.keywords)


def _contains_shape(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "shape":
            return True
    return False


def _is_partial_of_jit(call: ast.Call) -> bool:
    """functools.partial(jax.jit, ...) — the decorator-with-options
    idiom; partial's keywords are jit's keywords."""
    name = dotted_name(call.func)
    if name is None or name.rsplit(".", 1)[-1] != "partial" or not call.args:
        return False
    first = dotted_name(call.args[0])
    return first is not None and first.rsplit(".", 1)[-1] in ("jit", "pjit")


class RecompileHazards(Rule):
    id = "XF001"
    title = "jax.jit recompile hazards"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        for sf in index.files:
            if sf.tree is None:
                continue
            yield from self._check_file(sf)

    def _check_file(self, sf: SourceFile) -> Iterator[Finding]:
        tree = sf.tree
        assert tree is not None
        # (a) jit created inside a loop body: rebuilt — and retraced —
        # every iteration (the jit cache is keyed by function object).
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While)):
                for sub in walk_scoped(node):
                    call = jit_call(sub)
                    if call is not None:
                        yield self.finding(
                            sf,
                            call,
                            "jax.jit created inside a loop — the "
                            "compilation cache is keyed by function "
                            "object, so every iteration rebuilds and "
                            "retraces it; hoist the jit out of the "
                            "loop or cache the compiled executable",
                        )
        # (b) jax.jit(f)(args): a fresh traced callable per call —
        # nothing is ever cached.  (jax.jit(f).lower().compile() is the
        # sanctioned AOT idiom, serve/engine.py, and does not match.)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and jit_call(node.func) is not None:
                yield self.finding(
                    sf,
                    node,
                    "jax.jit(...) invoked immediately — a fresh jitted "
                    "callable per call defeats the compilation cache; "
                    "bind the jitted function once (TrainStep.__init__ "
                    "idiom) or AOT-compile via .lower(...).compile()",
                )
        yield from self._check_call_sites(sf, tree)

    def _check_call_sites(
        self, sf: SourceFile, tree: ast.Module
    ) -> Iterator[Finding]:
        # (c) names bound to jitted callables, then call sites feeding
        # them Python scalar literals or .shape-derived expressions.
        jitted_names: dict[str, bool] = {}  # name -> has static args
        jitted_attrs: dict[str, bool] = {}  # self.<attr> -> has static
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                call = jit_call(node.value)
                if call is None:
                    continue
                static = _jit_has_static(call)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        jitted_names[tgt.id] = static
                    elif (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        jitted_attrs[tgt.attr] = static
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if jit_call(dec) is not None:
                        jitted_names[node.name] = _jit_has_static(dec)
                    elif dotted_name(dec) is not None and dotted_name(
                        dec
                    ).rsplit(".", 1)[-1] in ("jit", "pjit"):
                        jitted_names[node.name] = False
                    elif isinstance(dec, ast.Call) and _is_partial_of_jit(
                        dec
                    ):
                        jitted_names[node.name] = _jit_has_static(dec)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in jitted_names:
                name, static = func.id, jitted_names[func.id]
            elif (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in jitted_attrs
            ):
                name, static = func.attr, jitted_attrs[func.attr]
            else:
                continue
            if static:
                # static_argnums/argnames declared: scalar args are the
                # INTENDED compile-time keys, not an accident
                continue
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, (int, float)
                ):
                    yield self.finding(
                        sf,
                        arg,
                        f"Python scalar literal in traced position {i} "
                        f"of jitted {name!r} — weak-typed scalars split "
                        "the jit cache and silently promote dtypes; "
                        "pass a jnp array or declare the arg in "
                        "static_argnums",
                    )
                elif _contains_shape(arg):
                    yield self.finding(
                        sf,
                        arg,
                        f".shape-derived value in traced position {i} "
                        f"of jitted {name!r} — every distinct shape "
                        "retraces; route it through static_argnums or "
                        "snap to fixed buckets (serve/engine.py idiom)",
                    )


# -- XF002 ----------------------------------------------------------------

_HOST_CONVERSIONS = ("float", "int", "bool")
_NP_SYNC_LEAVES = ("asarray", "array")
_SYNC_METHOD_ATTRS = ("item", "tolist")
# modules where an unbooked sync breaks the phase-accounting invariant
_HOT_PATH_PREFIXES = ("parallel/", "serve/", "io/", "ops/")
_HOT_PATH_FILES = ("trainer.py",)


def _is_hot_path(rel: str) -> bool:
    if rel in _HOT_PATH_FILES or any(
        rel.endswith("/" + f) for f in _HOT_PATH_FILES
    ):
        return True
    return any(
        rel.startswith(p) or ("/" + p) in rel for p in _HOT_PATH_PREFIXES
    )


class _FnInfo:
    __slots__ = ("node", "cls", "parent")

    def __init__(self, node, cls, parent):
        self.node = node  # FunctionDef
        self.cls = cls  # enclosing class name or None
        self.parent = parent  # enclosing _FnInfo or None


def _collect_functions(tree: ast.Module) -> list[_FnInfo]:
    out: list[_FnInfo] = []

    def visit(node: ast.AST, cls: str | None, parent: _FnInfo | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FnInfo(child, cls, parent)
                out.append(info)
                visit(child, cls, info)
            elif isinstance(child, ast.ClassDef):
                visit(child, child.name, None)
            else:
                visit(child, cls, parent)

    visit(tree, None, None)
    return out


class HiddenHostSyncs(Rule):
    id = "XF002"
    title = "hidden host syncs"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        for sf in index.files:
            if sf.tree is None:
                continue
            yield from self._check_traced(sf)
            if _is_hot_path(sf.rel):
                yield from self._check_spans(sf)

    # -- traced-function scope (ConcretizationError / silent sync) -----

    def _traced_functions(self, sf: SourceFile) -> list[_FnInfo]:
        tree = sf.tree
        assert tree is not None
        fns = _collect_functions(tree)
        traced: set[int] = set()

        def seed(info: _FnInfo) -> bool:
            for dec in info.node.decorator_list:
                name = dotted_name(dec)
                if name is not None and name.rsplit(".", 1)[-1] in (
                    "jit",
                    "pjit",
                ):
                    return True
                if isinstance(dec, ast.Call):
                    if jit_call(dec) is not None or _is_partial_of_jit(dec):
                        return True
            return False

        # seeds: @jit decorations plus any function passed to jax.jit
        # by name (f, self.f) anywhere in the module
        jit_targets_names: set[str] = set()
        jit_targets_methods: set[str] = set()
        for node in ast.walk(tree):
            call = jit_call(node)
            if call is None or not call.args:
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Name):
                jit_targets_names.add(arg.id)
            elif (
                isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"
            ):
                jit_targets_methods.add(arg.attr)
        for info in fns:
            if seed(info):
                traced.add(id(info))
            elif info.cls is None and info.node.name in jit_targets_names:
                traced.add(id(info))
            elif info.cls is not None and (
                info.node.name in jit_targets_methods
            ):
                traced.add(id(info))
        # closure: callees of traced functions (same module) are traced,
        # and so is any function DEFINED inside a traced one (lax.scan
        # bodies are called by reference, not by name)
        by_name_module = {
            info.node.name: info for info in fns if info.cls is None
        }
        by_method: dict[tuple[str, str], _FnInfo] = {
            (info.cls, info.node.name): info
            for info in fns
            if info.cls is not None
        }
        changed = True
        while changed:
            changed = False
            for info in fns:
                if id(info) in traced:
                    continue
                if info.parent is not None and id(info.parent) in traced:
                    traced.add(id(info))
                    changed = True
            for info in fns:
                if id(info) not in traced:
                    continue
                for node in walk_scoped(info.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee: _FnInfo | None = None
                    if isinstance(node.func, ast.Name):
                        callee = by_name_module.get(node.func.id)
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and info.cls is not None
                    ):
                        callee = by_method.get((info.cls, node.func.attr))
                    if callee is not None and id(callee) not in traced:
                        traced.add(id(callee))
                        changed = True
        return [info for info in fns if id(info) in traced]

    def _check_traced(self, sf: SourceFile) -> Iterator[Finding]:
        for info in self._traced_functions(sf):
            fname = info.node.name
            for node in walk_scoped(info.node):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if (
                    isinstance(func, ast.Name)
                    and func.id in _HOST_CONVERSIONS
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)
                ):
                    yield self.finding(
                        sf,
                        node,
                        f"{func.id}() inside traced function "
                        f"{fname!r} — host conversion of a traced "
                        "value is a device sync (or a Concretization"
                        "Error); keep reductions in jnp and convert "
                        "after device_get",
                    )
                    continue
                name = dotted_name(func)
                leaf = name.rsplit(".", 1)[-1] if name else (
                    func.attr if isinstance(func, ast.Attribute) else None
                )
                if leaf is None:
                    continue
                if name is not None and name.split(".", 1)[0] in (
                    "np",
                    "numpy",
                ) and leaf in _NP_SYNC_LEAVES:
                    yield self.finding(
                        sf,
                        node,
                        f"numpy {leaf}() inside traced function "
                        f"{fname!r} — materializes the traced value on "
                        "host every call; use jnp or move it outside "
                        "the jitted step",
                    )
                elif leaf == "device_get":
                    yield self.finding(
                        sf,
                        node,
                        f"jax.device_get inside traced function "
                        f"{fname!r} — a host round-trip inside the "
                        "compiled step; fetch results after dispatch",
                    )
                elif leaf == "block_until_ready":
                    yield self.finding(
                        sf,
                        node,
                        f"block_until_ready inside traced function "
                        f"{fname!r} — blocking has no meaning under "
                        "tracing and signals host/device confusion",
                    )
                elif (
                    isinstance(func, ast.Attribute)
                    and func.attr in _SYNC_METHOD_ATTRS
                    and not node.args
                ):
                    yield self.finding(
                        sf,
                        node,
                        f".{func.attr}() inside traced function "
                        f"{fname!r} — host conversion of a traced "
                        "value; return the array and convert outside",
                    )

    # -- hot-path span accounting (blocking outside obs phases) ---------

    def _check_spans(self, sf: SourceFile) -> Iterator[Finding]:
        tree = sf.tree
        assert tree is not None
        findings: list[Finding] = []

        def span_item(item: ast.withitem) -> bool:
            call = item.context_expr
            return (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in ("phase", "span")
            )

        def visit(node: ast.AST, in_span: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_span = in_span
                if isinstance(child, ast.With):
                    child_span = in_span or any(
                        span_item(i) for i in child.items
                    )
                if isinstance(child, ast.Call) and not in_span:
                    name = dotted_name(child.func)
                    leaf = (
                        name.rsplit(".", 1)[-1]
                        if name
                        else (
                            child.func.attr
                            if isinstance(child.func, ast.Attribute)
                            else None
                        )
                    )
                    if leaf in ("block_until_ready", "device_get"):
                        findings.append(
                            self.finding(
                                sf,
                                child,
                                f"{leaf} outside an obs phase/span "
                                "context in a hot-path module — the "
                                "blocked seconds vanish from phase "
                                "accounting (the >=90% wall-clock "
                                "coverage invariant, scripts/"
                                "check_metrics_schema.py); wrap it in "
                                "`with obs.phase(...)`",
                            )
                        )
                visit(child, child_span)

        visit(tree, False)
        yield from findings
