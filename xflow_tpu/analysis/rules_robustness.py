"""XF015 — robustness discipline for worker-context exception
handling (docs/ROBUSTNESS.md, docs/ANALYSIS.md).

The self-healing fabric's contract is **recovery is never silent**: a
retried read, a quarantined record, a restarted worker, an evicted
replica each leave a ``health``/``chaos`` row.  The way that contract
rots is one ``try/except Exception: pass`` deep inside a worker thread
— the thread survives, the fault vanishes, and six months later the
"self-healing" system is silently eating real corruption.  Worker
context is the dangerous place: an exception swallowed on the main
thread at least perturbs control flow somewhere visible, while a
worker's swallow is invisible by construction (nothing joins on it,
nothing reads its return value).

XF015 therefore demands that every BROAD exception handler (bare
``except:``, ``except Exception``, ``except BaseException`` — narrow
idioms like ``except queue.Empty: continue`` are expected control
flow, not swallows) inside a worker-context function (PR 6's
ConcurrencyContext classification) does at least one of:

* **re-raise** — any ``raise`` in the handler body;
* **propagate the exception object** — a call that receives the bound
  exception name (``fut.set_exception(e)``, ``self._put_or_abort(e)``,
  a message built from ``e``): the fault travels to someone who will
  act on it;
* **report loudly** — a call whose leaf name is a known reporting
  surface (``health_row``/``emit_health``/``log``/``counter``/
  ``warn``/``note_shed``/``note_error``/``flight_dump``/...).

Anything else is a silent worker swallow — fix it or pragma it with a
justification (``xf: ignore[XF015]``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    dotted_name,
    walk_scoped,
)
from xflow_tpu.analysis.rules_concurrency import get_context

_BROAD = {"Exception", "BaseException"}

# leaf names that count as loud reporting even without the exception
# object in hand (counters and health rows carry their own context)
_REPORT_LEAVES = {
    "health_row",
    "emit_health",
    "log",
    "counter",
    "counter_add",
    "warn",
    "warning",
    "error",
    "exception",
    "note_error",
    "note_shed",
    "set_exception",
    "flight_dump",
    "put_nowait",
}


def _leaf_of(node: ast.AST) -> str | None:
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else None


class SwallowedWorkerException(Rule):
    id = "XF015"
    title = "worker-context handler swallows exceptions silently"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        ctx = get_context(index)
        for fn in ctx.fns:
            if not fn.is_worker:
                continue
            for node in walk_scoped(fn.node):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not self._is_broad(handler):
                        continue
                    if self._handles_loudly(handler):
                        continue
                    yield Finding(
                        rule=self.id,
                        path=fn.sf.rel,
                        line=handler.lineno,
                        message=(
                            f"broad except in worker-context "
                            f"{fn.qualname}() swallows the exception "
                            "silently — a worker's swallow is "
                            "invisible by construction (nothing joins "
                            "it, nothing reads its return); re-raise, "
                            "propagate the exception object, or emit "
                            "a health/chaos row "
                            "(docs/ROBUSTNESS.md), or pragma with a "
                            "justification"
                        ),
                    )

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True  # bare except:
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(_leaf_of(e) in _BROAD for e in elts)

    @staticmethod
    def _handles_loudly(handler: ast.ExceptHandler) -> bool:
        """Raise / exception-object propagation / reporting call in the
        handler body (pruned walk: a nested def the handler merely
        DEFINES doesn't handle anything)."""
        bound = handler.name
        stack = list(handler.body)
        while stack:
            node = stack.pop()
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef,
                 ast.Lambda, ast.ClassDef),
            ):
                continue
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                leaf = _leaf_of(node.func)
                if leaf in _REPORT_LEAVES:
                    return True
                if bound is not None and any(
                    isinstance(sub, ast.Name) and sub.id == bound
                    for sub in ast.walk(node)
                ):
                    return True
            stack.extend(ast.iter_child_nodes(node))
        return False
