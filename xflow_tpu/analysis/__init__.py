"""Static-analysis pass enforcing the framework's performance and
thread-safety invariants (ISSUE 3; docs/ANALYSIS.md).

PRs 1–2 made the invariants that keep this trainer/server fast explicit
— no recompiles under any traffic mix, phase seconds account for
wall-clock, loader/batcher worker threads never touch shared state
unlocked — but runtime tests only catch a regression when the exact
scenario executes.  This package checks the *code* instead: an AST rule
engine with five JAX-aware rules, runnable as

    python -m xflow_tpu.analysis xflow_tpu/

Rules (each documented in docs/ANALYSIS.md with its rationale and the
PR-1/PR-2 invariant it guards):

* XF001 recompile hazards — ``jax.jit`` re-created per loop iteration /
  per call, Python scalars or ``.shape``-derived values flowing into
  traced positions of a jitted callable;
* XF002 hidden host syncs — ``float()``/``int()``/``bool()``/
  ``np.asarray``/``device_get``/``.item()`` inside traced functions,
  and ``block_until_ready``/``device_get`` in hot-path modules outside
  an ``obs.phase(...)``/``span(...)`` accounting context;
* XF003 lock discipline — attributes of lock-owning classes written
  both inside and outside ``with self._lock``;
* XF004 schema drift — every JSONL ``kind`` emitted anywhere must be
  declared in ``obs/schema.py`` and vice versa;
* XF005 C-ABI parity — ``XF*`` symbols in ``native/include/xflow_tpu.h``
  vs ``native/src/c_api.cc`` vs ``capi_impl.py``, no orphans.

Suppression: ``# xf: ignore[XF001]`` on the finding line, or
``# xf: ignore-file[XF001]`` anywhere in the file; a committed baseline
file (``analysis-baseline.json``) grandfathers legacy findings without
silencing new ones.
"""

from __future__ import annotations

from xflow_tpu.analysis.baseline import (
    load_baseline,
    split_baselined,
    write_baseline,
)
from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    all_rules,
    run_analysis,
)
from xflow_tpu.analysis.report import render_json, render_text

__all__ = [
    "Finding",
    "PackageIndex",
    "Rule",
    "SourceFile",
    "all_rules",
    "run_analysis",
    "load_baseline",
    "write_baseline",
    "split_baselined",
    "render_text",
    "render_json",
]
