"""Static-analysis pass enforcing the framework's performance and
thread-safety invariants (ISSUE 3; docs/ANALYSIS.md).

PRs 1–2 made the invariants that keep this trainer/server fast explicit
— no recompiles under any traffic mix, phase seconds account for
wall-clock, loader/batcher worker threads never touch shared state
unlocked — but runtime tests only catch a regression when the exact
scenario executes.  This package checks the *code* instead: an AST rule
engine with five JAX-aware rules, runnable as

    python -m xflow_tpu.analysis xflow_tpu/

Rules (each documented in docs/ANALYSIS.md with its rationale and the
PR-1/PR-2 invariant it guards):

* XF001 recompile hazards — ``jax.jit`` re-created per loop iteration /
  per call, Python scalars or ``.shape``-derived values flowing into
  traced positions of a jitted callable;
* XF002 hidden host syncs — ``float()``/``int()``/``bool()``/
  ``np.asarray``/``device_get``/``.item()`` inside traced functions,
  and ``block_until_ready``/``device_get`` in hot-path modules outside
  an ``obs.phase(...)``/``span(...)`` accounting context;
* XF003 lock discipline — attributes of lock-owning classes written
  both inside and outside ``with self._lock``;
* XF004 schema drift — every JSONL ``kind`` emitted anywhere must be
  declared in ``obs/schema.py`` and vice versa;
* XF005 C-ABI parity — ``XF*`` symbols in ``native/include/xflow_tpu.h``
  vs ``native/src/c_api.cc`` vs ``capi_impl.py``, no orphans.

Concurrency rules (ISSUE 6; rules_concurrency.py) ride a package-wide
call graph that classifies every function main-context / worker-context
(reachable from ``Thread(target=...)``/executor ``submit``/``map``) /
both:

* XF006 thread lifecycle — started threads/executors need a bounded
  (timeout) ``join``/``shutdown`` reachable from a close()/__exit__
  path;
* XF007 lock order — the package lock-acquisition graph must be
  acyclic, and no untimed blocking call may run while holding a lock;
  the runtime companion (analysis/sanitizer.py) cross-checks observed
  acquisition orders against this graph;
* XF008 shared-state discipline — state written outside ``__init__``
  and touched from both thread contexts must be guarded at every
  access;
* XF009 heartbeat coverage — unbounded worker loops in hot-path
  modules must pulse the flight-recorder heartbeat.

Memory rules (ISSUE 7; rules_memory.py) ride a symbolic shape/dtype
dataflow (shapeflow.py) that propagates dims seeded from ``Config``
caps (T/B/K/Kh/H/S/D) through jitted traces:

* XF010 full-table transients — ``zeros_like(table)`` /
  ``one_hot(keys, T)`` materializations inside jit (multi-GB at the
  north-star T=2^28);
* XF011 dtype discipline — ad-hoc uint64->int32 key narrowing outside
  ``io/batch.py::narrow_keys_i32``, explicit float64 in traced code;
* XF012 sharding coverage — unsharded ``device_put`` in hot paths,
  shardings constructed outside parallel/mesh.py, unknown collective
  axis names;
* XF013 donation safety — ``donate_argnums`` buffers read after the
  donating call;
* XF014 transient-HBM budget — per-jit transient estimates at the
  north-star geometry gated against the committed
  ``memory-budget.json`` (scripts/check_memory.py).

Wire-protocol & failure-domain rules (ISSUE 18; rules_protocol.py)
pre-gate the pod-scale store and binary serve transport — the formats
that will cross real sockets and failure domains:

* XF016 codec parity — every struct format packed somewhere must be
  unpacked somewhere (and vice versa), and each wire module's
  fingerprint (magics, format-version constants, struct formats) must
  match the committed ``protocol-registry.json``;
* XF017 blocking-I/O timeout discipline — ``.result()``/``.wait()``/
  bare ``.get()`` and HTTP/socket constructors in serve/stream/store
  must carry a timeout (Config ``serve_*_timeout_s`` knobs);
* XF018 failpoint coverage — file-I/O boundaries in the chaos-covered
  modules must be reachable from a ``failpoint(...)`` site;
* XF019 determinism taint — wall-clock/random values must not flow
  into digest computations;
* XF020 explicit endianness — struct format literals must pin byte
  order (``<``/``>``/``!``).

Runtime companion: analysis/wirefuzz.py, a seeded structure-aware
decoder fuzzer over every wire format (XFS1/XFS2, packed-v2, binary
CSR, delta manifests) asserting typed refusals only; both halves gate
in scripts/check_protocol.py.

Suppression: ``# xf: ignore[XF001]`` on the finding line, or
``# xf: ignore-file[XF001]`` anywhere in the file; a committed baseline
file (``analysis-baseline.json``) grandfathers legacy findings without
silencing new ones.
"""

from __future__ import annotations

from xflow_tpu.analysis.baseline import (
    load_baseline,
    split_baselined,
    write_baseline,
)
from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    all_rules,
    run_analysis,
)
from xflow_tpu.analysis.report import render_json, render_text
from xflow_tpu.analysis.rules_concurrency import static_lock_order
from xflow_tpu.analysis.rules_memory import (
    estimate_transients,
    find_budget,
    load_budget,
)
from xflow_tpu.analysis.rules_protocol import (
    PROTOCOL_RULES,
    build_registry,
    find_registry,
    load_registry,
    wire_fingerprint,
)
from xflow_tpu.analysis.sanitizer import LockOrderSanitizer
from xflow_tpu.analysis.wirefuzz import render_report, run_wirefuzz

__all__ = [
    "PROTOCOL_RULES",
    "build_registry",
    "find_registry",
    "load_registry",
    "wire_fingerprint",
    "run_wirefuzz",
    "render_report",
    "estimate_transients",
    "find_budget",
    "load_budget",
    "Finding",
    "PackageIndex",
    "Rule",
    "SourceFile",
    "all_rules",
    "run_analysis",
    "load_baseline",
    "write_baseline",
    "split_baselined",
    "render_text",
    "render_json",
    "static_lock_order",
    "LockOrderSanitizer",
]
