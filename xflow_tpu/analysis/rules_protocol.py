"""Wire-protocol & failure-domain rules (ISSUE 18; docs/ANALYSIS.md).

ROADMAP items 2 (pod-scale parameter store) and 5 (persistent binary
serve transport) put the hand-rolled binary formats — XFS1/XFS2,
packed-v2, delta chains, checkpoint manifests — onto real sockets
across failure domains.  Every downstream guarantee (bitwise fan-out
parity, delta digest chains, rollout atomicity) assumes codecs,
timeouts, and failpoint coverage don't silently decay; these five
rules gate that fabric statically, before bytes leave the host:

* XF016 codec parity — every ``struct`` format string packed anywhere
  in the tree must be unpacked somewhere (and vice versa), and every
  wire module's format fingerprint (magic constants, format-version
  constants, struct format strings) must match the committed
  ``protocol-registry.json``: changing a wire format without a
  registered version/magic bump is a finding, not a silent drift.
* XF017 blocking-I/O timeout discipline — ``.result()``/``.wait()``/
  bare ``.get()`` and HTTP/socket constructors in the serve/stream/
  store domain must carry a timeout; failures route through
  ``retry_call``/``emit_health`` (chaos/heal.py) or a typed error.
  The I/O-domain extension of XF007's no-untimed-blocking-under-lock.
* XF018 failpoint coverage — file-I/O boundaries in the chaos-covered
  modules (io/, serve/, stream/, store/, utils/checkpoint.py) must be
  reachable from a registered ``failpoint(...)`` site, so the fault
  fabric (PR 11) can't rot as code lands.
* XF019 determinism taint — wall-clock/random values must not flow
  into digest computations (hashlib constructors/updates, ``*digest*``
  helpers): the invariant every bitwise gate stands on.
* XF020 explicit-endian/width discipline — every ``struct`` format
  literal must begin with an explicit byte-order prefix (``<``, ``>``
  or ``!``); native order/size (``@`` or none, and ``=``) describes
  the host, not the wire.

Runtime companion: analysis/wirefuzz.py (seeded structure-aware
decoder fuzzer); both halves gate in scripts/check_protocol.py.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Iterator

from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    dotted_name,
    walk_scoped,
)
from xflow_tpu.analysis.rules_concurrency import get_context

DEFAULT_REGISTRY = "protocol-registry.json"

PROTOCOL_RULES = ["XF016", "XF017", "XF018", "XF019", "XF020"]

# serve/stream/store: the processes-talking-to-processes domain where
# an unbounded block turns one slow peer into a wedged tier (XF017)
_IO_DOMAIN_PREFIXES = ("serve/", "stream/", "store/")

# modules the chaos fabric (chaos/registry.py) must keep covered: the
# storage/wire boundaries whose faults PR 11's gate injects (XF018)
_CHAOS_PREFIXES = ("io/", "serve/", "stream/", "store/")
_CHAOS_FILES = ("utils/checkpoint.py",)

_PACK_LEAVES = ("pack", "pack_into")
_UNPACK_LEAVES = ("unpack", "unpack_from", "iter_unpack")
_STRUCT_FMT_LEAVES = _PACK_LEAVES + _UNPACK_LEAVES + ("Struct", "calcsize")


def _in_domain(rel: str, prefixes, files=()) -> bool:
    if rel in files or any(rel.endswith("/" + f) for f in files):
        return True
    return any(
        rel.startswith(p) or ("/" + p) in rel for p in prefixes
    )


def _leaf(node: ast.AST) -> str | None:
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else None


def _fmt_literal(call: ast.Call) -> str | None:
    """The struct format string when the call's first argument is a
    plain literal (the only statically checkable case)."""
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def _timeout_arg(call: ast.Call) -> bool:
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


# -- per-file wire inventory (XF016 + XF020 share it) ----------------------


class _WireUse:
    """struct format usage + magic/version constants of one file."""

    def __init__(self) -> None:
        self.packed: dict[str, ast.AST] = {}  # fmt -> first site
        self.unpacked: dict[str, ast.AST] = {}
        self.formats: dict[str, ast.AST] = {}  # any struct fmt literal
        self.magics: dict[str, str] = {}  # NAME -> bytes hex
        self.versions: dict[str, int] = {}
        self.const_nodes: dict[str, ast.AST] = {}


def _collect_wire(sf: SourceFile) -> _WireUse:
    use = _WireUse()
    struct_names: dict[str, str] = {}  # local Struct-object name -> fmt
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            val = node.value
            if (
                isinstance(val, ast.Call)
                and dotted_name(val.func) in ("struct.Struct", "Struct")
                and _fmt_literal(val) is not None
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        struct_names[tgt.id] = _fmt_literal(val)
            if isinstance(val, ast.Constant):
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    name = tgt.id
                    if isinstance(val.value, bytes) and "MAGIC" in name:
                        use.magics[name] = val.value.hex()
                        use.const_nodes[name] = node
                    elif isinstance(val.value, int) and not isinstance(
                        val.value, bool
                    ) and (
                        name.endswith("_FORMAT") or name.endswith("_VERSION")
                    ):
                        use.versions[name] = val.value
                        use.const_nodes[name] = node
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        head, _, leaf = name.rpartition(".")
        if head == "struct" and leaf in _STRUCT_FMT_LEAVES:
            fmt = _fmt_literal(node)
            if fmt is not None:
                use.formats.setdefault(fmt, node)
                if leaf in _PACK_LEAVES:
                    use.packed.setdefault(fmt, node)
                elif leaf in _UNPACK_LEAVES:
                    use.unpacked.setdefault(fmt, node)
        elif head in struct_names and leaf in _PACK_LEAVES + _UNPACK_LEAVES:
            fmt = struct_names[head]
            use.formats.setdefault(fmt, node)
            if leaf in _PACK_LEAVES:
                use.packed.setdefault(fmt, node)
            else:
                use.unpacked.setdefault(fmt, node)
    return use


def wire_fingerprint(sf: SourceFile) -> dict | None:
    """The registry entry for one file: magic constants, format-version
    constants, struct format strings.  None when the file touches no
    wire surface (nothing to register)."""
    if sf.tree is None:
        return None
    use = _collect_wire(sf)
    if not (use.magics or use.versions or use.formats):
        return None
    return {
        "magics": dict(sorted(use.magics.items())),
        "versions": dict(sorted(use.versions.items())),
        "formats": sorted(use.formats),
    }


def find_registry(index: PackageIndex) -> str | None:
    """protocol-registry.json next to (or one level above) a scan root
    — repo layout: roots=[REPO/xflow_tpu], registry at REPO/ (the
    find_budget idiom, rules_memory.py)."""
    for root in index.roots:
        for base in (root, os.path.dirname(root)):
            cand = os.path.join(base, DEFAULT_REGISTRY)
            if os.path.exists(cand):
                return cand
    return None


def load_registry(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return doc.get("modules", {})


def build_registry(index: PackageIndex) -> dict:
    """Current-tree fingerprints, ready to commit (check_protocol.py
    --write-registry)."""
    modules = {}
    for sf in index.files:
        fp = wire_fingerprint(sf)
        if fp is not None:
            modules[sf.rel] = fp
    return modules


class CodecParity(Rule):
    id = "XF016"
    title = "encoder without decoder / unregistered wire-format change"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        uses: dict[SourceFile, _WireUse] = {}
        for sf in index.files:
            if sf.tree is None:
                continue
            use = _collect_wire(sf)
            if use.packed or use.unpacked or use.magics or use.versions:
                uses[sf] = use
        all_packed = {f for u in uses.values() for f in u.packed}
        all_unpacked = {f for u in uses.values() for f in u.unpacked}
        for sf, use in uses.items():
            for fmt, node in sorted(use.packed.items()):
                if fmt not in all_unpacked:
                    yield self.finding(
                        sf, node,
                        f"struct format {fmt!r} is packed here but "
                        "never unpacked anywhere in the scanned tree — "
                        "a write-only wire format has no decoder to "
                        "cross-check (codec parity)",
                    )
            for fmt, node in sorted(use.unpacked.items()):
                if fmt not in all_packed:
                    yield self.finding(
                        sf, node,
                        f"struct format {fmt!r} is unpacked here but "
                        "never packed anywhere in the scanned tree — "
                        "a read-only wire format has no encoder to "
                        "cross-check (codec parity)",
                    )
        # registry half: wire fingerprints vs the committed registry.
        # No registry next to the scan roots (unit-test trees) = the
        # check is not armed, same contract as XF014's memory budget.
        path = find_registry(index)
        if path is None:
            return
        try:
            registry = load_registry(path)
        except (OSError, ValueError) as e:
            yield Finding(
                rule=self.id, path=DEFAULT_REGISTRY, line=0,
                message=f"unreadable protocol registry: {e}",
            )
            return
        current = {sf.rel: wire_fingerprint(sf) for sf in index.files}
        current = {k: v for k, v in current.items() if v is not None}
        for rel, fp in sorted(current.items()):
            want = registry.get(rel)
            sf = index.by_rel(rel)
            node = ast.Module(body=[], type_ignores=[])
            if want is None:
                yield self.finding(
                    sf, node,
                    "wire module is not registered in "
                    f"{DEFAULT_REGISTRY} — register its magic/version/"
                    "format fingerprint (python scripts/"
                    "check_protocol.py --write-registry)",
                )
            elif want != fp:
                drift = [
                    k for k in ("magics", "versions", "formats")
                    if want.get(k) != fp.get(k)
                ]
                yield self.finding(
                    sf, node,
                    f"wire fingerprint drifted from {DEFAULT_REGISTRY} "
                    f"({', '.join(drift)} changed) — a format change "
                    "requires a version/magic bump registered via "
                    "python scripts/check_protocol.py --write-registry",
                )
        for rel in sorted(set(registry) - set(current)):
            yield Finding(
                rule=self.id, path=rel, line=0,
                message=f"stale {DEFAULT_REGISTRY} entry: module no "
                "longer defines a wire surface — prune it "
                "(python scripts/check_protocol.py --write-registry)",
            )


class BlockingIoTimeout(Rule):
    id = "XF017"
    title = "blocking I/O without timeout in the serve/stream/store domain"

    _HTTP_CTORS = (
        "HTTPConnection", "HTTPSConnection", "create_connection",
        "urlopen",
    )

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        for sf in index.files:
            if sf.tree is None or not _in_domain(
                sf.rel, _IO_DOMAIN_PREFIXES
            ):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _leaf(node.func)
                if leaf in ("result", "wait"):
                    if not _timeout_arg(node):
                        yield self.finding(
                            sf, node,
                            f".{leaf}() without a timeout blocks this "
                            "domain's thread on a peer that may never "
                            "answer — pass a timeout and route the "
                            "failure through retry_call/emit_health "
                            "(chaos/heal.py) or a typed error",
                        )
                elif leaf == "get":
                    # bare .get(): the blocking-queue idiom (dict.get
                    # always carries a key argument)
                    if not node.args and not node.keywords:
                        yield self.finding(
                            sf, node,
                            ".get() with no timeout blocks forever on "
                            "an empty queue — pass timeout= (or a "
                            "sentinel-drain justified pragma) and "
                            "route the failure through retry_call/"
                            "emit_health or a typed error",
                        )
                elif leaf in self._HTTP_CTORS:
                    if not any(
                        kw.arg == "timeout" for kw in node.keywords
                    ):
                        yield self.finding(
                            sf, node,
                            f"{leaf}(...) without timeout= gives the "
                            "socket no deadline — a wedged peer holds "
                            "this thread indefinitely; pass an "
                            "explicit timeout (Config serve_*_timeout_s"
                            " knobs)",
                        )


class FailpointCoverage(Rule):
    id = "XF018"
    title = "I/O boundary unreachable from any registered chaos site"

    _IO_LEAVES = {"open", "replace", "load", "save", "fsync"}
    _IO_NAMES = {
        "open", "os.replace", "np.load", "np.save", "numpy.load",
        "numpy.save", "os.fsync",
    }

    def _does_io(self, fn) -> ast.AST | None:
        for node in walk_scoped(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in self._IO_NAMES:
                return node
        return None

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        ctx = get_context(index)
        # seeds: functions that call failpoint(...) directly
        seeds = set()
        for fn in ctx.fns:
            for node in walk_scoped(fn.node):
                if (
                    isinstance(node, ast.Call)
                    and _leaf(node.func) == "failpoint"
                ):
                    seeds.add(id(fn))
                    break
        # reverse call graph: covered = self or any transitive caller
        # calls failpoint (the site fires whenever the boundary is on
        # an injected path)
        callers: dict[int, list] = {}
        for fn in ctx.fns:
            for callee in fn.calls:
                callers.setdefault(id(callee), []).append(fn)
        covered: dict[int, bool] = {}

        def is_covered(fn) -> bool:
            stack, visiting = [fn], set()
            # iterative DFS up the caller chain with memoization
            while stack:
                cur = stack[-1]
                if id(cur) in covered:
                    stack.pop()
                    continue
                if id(cur) in seeds:
                    covered[id(cur)] = True
                    stack.pop()
                    continue
                if id(cur) in visiting:
                    ups = callers.get(id(cur), [])
                    covered[id(cur)] = any(
                        covered.get(id(u), False) for u in ups
                    )
                    stack.pop()
                    continue
                visiting.add(id(cur))
                for up in callers.get(id(cur), []):
                    if id(up) not in covered and id(up) not in visiting:
                        stack.append(up)
            return covered[id(fn)]

        for fn in ctx.fns:
            rel = fn.sf.rel
            if not _in_domain(rel, _CHAOS_PREFIXES, _CHAOS_FILES):
                continue
            if rel.endswith("__main__.py") or fn.name == "main":
                continue  # CLI one-shots are not the fault fabric
            site = self._does_io(fn)
            if site is None:
                continue
            if not is_covered(fn):
                yield self.finding(
                    fn.sf, site,
                    f"{fn.qualname} performs file I/O but is not "
                    "reachable from any failpoint(...) chaos site — "
                    "the fault-injection gate (scripts/check_chaos.py) "
                    "cannot exercise this boundary; add a failpoint on "
                    "the path or justify with a pragma "
                    "(docs/ROBUSTNESS.md)",
                )


class DeterminismTaint(Rule):
    id = "XF019"
    title = "wall-clock/random value flowing into a digest"

    _TAINT_NAMES = {
        "time.time", "time.time_ns", "time.perf_counter",
        "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
        "os.urandom", "uuid.uuid4", "uuid.uuid1", "random.random",
        "random.randint", "random.randrange", "random.getrandbits",
    }

    def _tainted_call(self, node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and dotted_name(node.func) in self._TAINT_NAMES
        )

    def _expr_tainted(self, node: ast.AST, tainted: set[str]) -> bool:
        for sub in ast.walk(node):
            if self._tainted_call(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        ctx = get_context(index)
        for fn in ctx.fns:
            tainted: set[str] = set()
            hashes: set[str] = set()
            # pass 1 to fixed point (walk_scoped has no source-order
            # guarantee): names assigned from taint sources or hashlib
            # constructors
            assigns = [
                n for n in walk_scoped(fn.node)
                if isinstance(n, ast.Assign)
            ]
            changed = True
            while changed:
                changed = False
                for node in assigns:
                    names = [
                        t.id for t in node.targets
                        if isinstance(t, ast.Name)
                    ]
                    if not names:
                        continue
                    if self._expr_tainted(node.value, tainted) and not (
                        set(names) <= tainted
                    ):
                        tainted.update(names)
                        changed = True
                    if isinstance(node.value, ast.Call):
                        ctor = dotted_name(node.value.func) or ""
                        if ctor.startswith("hashlib.") and not (
                            set(names) <= hashes
                        ):
                            hashes.update(names)
                            changed = True
            if not tainted and not any(
                self._tainted_call(n) for n in walk_scoped(fn.node)
            ):
                continue
            # pass 2: taint reaching a digest sink
            for node in walk_scoped(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                leaf = name.rsplit(".", 1)[-1]
                is_sink = (
                    name.startswith("hashlib.")
                    or "digest" in leaf
                    or (
                        leaf == "update"
                        and name.rsplit(".", 1)[0] in hashes
                    )
                )
                if not is_sink:
                    continue
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if self._expr_tainted(arg, tainted):
                        yield self.finding(
                            fn.sf, node,
                            f"{fn.qualname} feeds a wall-clock/random "
                            f"value into {name or leaf}(...) — digests "
                            "must be deterministic functions of config "
                            "+ data or every bitwise gate (fan-out "
                            "parity, delta chains, rollout identity) "
                            "silently breaks",
                        )
                        break


class ExplicitEndian(Rule):
    id = "XF020"
    title = "native-order struct format on a cross-process surface"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        for sf in index.files:
            if sf.tree is None:
                continue
            use = _collect_wire(sf)
            for fmt, node in sorted(use.formats.items()):
                if not fmt or fmt[0] not in "<>!":
                    how = (
                        "'=' (native byte order)" if fmt[:1] == "="
                        else "native order AND native sizes"
                    )
                    yield self.finding(
                        sf, node,
                        f"struct format {fmt!r} uses {how} — bytes "
                        "that cross a process/host boundary must pin "
                        "byte order and width explicitly ('<', '>' or "
                        "'!'), or a mixed-arch pod reads garbage",
                    )
