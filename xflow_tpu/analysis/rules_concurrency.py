"""XF006–XF009 — package-wide concurrency rules over a thread-context
call graph.

ROADMAP item 1 fans the input pipeline out to N shard-reader streams
with per-stream compaction workers: more threads, more locks, more
queues.  Every concurrency bug this repo has shipped so far (torn
``MetricsLogger`` lines, the ``MicroBatcher`` close race, leaked
``_PrefetchIter`` producer threads) was invisible to single-threaded
tests and obvious in hindsight from the *code*.  These rules mechanize
that hindsight before the fan-out multiplies the surface.

The shared engine (``ConcurrencyContext``) extends XF002's intra-module
call-graph closure into a package-wide one with thread-entrypoint
tracking: every function is classified

* **worker-context** — reachable (through resolvable calls) from a
  ``threading.Thread(target=...)`` target or a
  ``ThreadPoolExecutor.submit``/``.map`` submission;
* **main-context** — reachable from a call-graph root (a function with
  no resolvable in-package caller that is not itself a thread target);
* or **both** (e.g. ``TrainStep.put_batch``: called inline on the
  multi-host voting thread AND submitted to the transfer-ahead ring).

Resolution is deliberately conservative: ``self.m()``, same-module
``f()``, imported-module ``mod.f()``, and class instantiation resolve;
arbitrary ``obj.m()`` calls do not (an unresolved callee simply stays a
root, i.e. main-context).  Thread/submit *targets* are rare and
explicit, so those additionally resolve ``self.x.m`` by unique method
name across the package.

Rules on top of the context graph:

* **XF006 thread lifecycle** — every started thread / constructed
  executor must have a reachable ``join``/``shutdown`` on a
  ``close()``/``__exit__``/``stop()`` path, with a timeout (the
  ``_PrefetchIter`` leak class, generalized);
* **XF007 lock order** — the package-wide lock-acquisition graph
  (nested ``with self._lock`` blocks, closed over calls) must be
  acyclic, and no blocking call (``queue.get()``/``join()``/
  ``.result()``/``.wait()`` without a timeout) may run while a lock is
  held.  ``static_lock_order()`` exports this graph; the runtime
  sanitizer (analysis/sanitizer.py) cross-checks observed acquisition
  orders against it;
* **XF008 shared-state discipline** — an attribute written outside
  ``__init__`` and touched from both thread contexts must be guarded
  at EVERY access (XF003 extended beyond lock-owning-class writes:
  reads count, and the contexts come from the graph, not the class);
* **XF009 heartbeat coverage** — unbounded loops in worker-context
  functions inside hot-path modules must pulse the flight-recorder
  heartbeat (``note_loader``/``note_serve``/``_pulse``…) so new
  threads can never silently evade ``obs doctor``/the watchdog.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    dotted_name,
    walk_scoped,
)
from xflow_tpu.analysis.rules_threads import _lock_ctor, _self_attr

_CONSTRUCTOR_METHODS = ("__init__", "__new__")

# method names that form a shutdown path: a join/shutdown reachable
# from one of these (via same-class self-calls) satisfies XF006
_CLOSER_METHODS = {
    "close", "stop", "shutdown", "join", "terminate", "__exit__", "__del__",
}

# the flight-recorder/watchdog heartbeat surface (obs/flight.py,
# trainer._pulse): a worker loop pulsing any of these is observable
_HEARTBEAT_CALLS = {
    "note_loader", "note_serve", "note_phase", "note_batch", "_pulse",
}

# attribute types that ARE the hand-off discipline: mutating through
# them is thread-safe by construction, so XF008 exempts the attribute
_THREADSAFE_CTORS = {
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    "Event", "Lock", "RLock", "Condition",
    "Semaphore", "BoundedSemaphore", "Barrier", "deque",
}

# modules whose worker silence the watchdog must be able to classify
_HOT_PATH_PREFIXES = ("io/", "serve/", "obs/", "parallel/")
_HOT_PATH_FILES = ("trainer.py",)


def _is_hot_path(rel: str) -> bool:
    if rel in _HOT_PATH_FILES or any(
        rel.endswith("/" + f) for f in _HOT_PATH_FILES
    ):
        return True
    return any(
        rel.startswith(p) or ("/" + p) in rel for p in _HOT_PATH_PREFIXES
    )


def _leaf(name: str | None) -> str | None:
    return None if name is None else name.rsplit(".", 1)[-1]


def _call_leaf(node: ast.Call) -> str | None:
    """Trailing attribute/name of the called expression ('submit' for
    ``ex.submit(...)`` even when ``ex`` isn't a plain dotted path)."""
    name = dotted_name(node.func)
    if name is not None:
        return _leaf(name)
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _timeout_arg(call: ast.Call) -> bool:
    return bool(call.args) or any(
        kw.arg == "timeout" for kw in call.keywords
    )


def _thread_join_call(node: ast.AST) -> ast.Call | None:
    """``node`` when it is plausibly a THREAD's join: ``x.join(...)``
    where the receiver is a name or attribute chain.  ``', '.join(
    parts)`` (a string-literal receiver) must not satisfy the XF006
    shutdown-join requirement — the classic false pass."""
    if not (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "join"
    ):
        return None
    recv = node.func.value
    if isinstance(recv, (ast.Name, ast.Attribute)):
        return node
    return None


@dataclass
class _Fn:
    """One function/method in the package-wide graph."""

    sf: SourceFile
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: str | None
    parent: "_Fn | None"
    children: dict[str, "_Fn"] = field(default_factory=dict)
    calls: list["_Fn"] = field(default_factory=list)
    called: bool = False  # has a resolved in-package plain caller
    is_worker: bool = False
    is_main: bool = False
    worker_seed_site: str = ""  # how it became a thread entrypoint

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class ConcurrencyContext:
    """Package-wide call graph + thread-context classification, built
    once per ``PackageIndex`` and shared by XF006–XF009 (cached on the
    index so the four rules don't re-derive it)."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self.fns: list[_Fn] = []
        self.module_fns: dict[tuple[str, str], _Fn] = {}
        self.methods: dict[tuple[str, str, str], _Fn] = {}
        self.methods_by_name: dict[str, list[_Fn]] = {}
        self.classes: dict[tuple[str, str], ast.ClassDef] = {}
        self.class_methods: dict[tuple[str, str], list[_Fn]] = {}
        self.class_locks: dict[tuple[str, str], dict[str, str]] = {}
        self.module_locks: dict[tuple[str, str], str] = {}
        self.imports: dict[str, dict[str, str]] = {}  # rel -> alias -> module
        for sf in index.files:
            if sf.tree is not None:
                self._collect_file(sf)
        self._resolve_calls()
        self._classify()

    # -- collection --------------------------------------------------------

    def _collect_file(self, sf: SourceFile) -> None:
        imports: dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        imports[alias.asname] = alias.name
                    elif "." not in alias.name:
                        imports[alias.name] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )
        self.imports[sf.rel] = imports

        def visit(node: ast.AST, cls: str | None, parent: _Fn | None):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    fn = _Fn(sf, child, cls, parent)
                    self.fns.append(fn)
                    if parent is not None:
                        parent.children[child.name] = fn
                    elif cls is not None:
                        self.methods[(sf.rel, cls, child.name)] = fn
                        self.class_methods.setdefault(
                            (sf.rel, cls), []
                        ).append(fn)
                        self.methods_by_name.setdefault(
                            child.name, []
                        ).append(fn)
                    else:
                        self.module_fns[(sf.rel, child.name)] = fn
                    visit(child, cls, fn)
                elif isinstance(child, ast.ClassDef):
                    self.classes[(sf.rel, child.name)] = child
                    self.class_methods.setdefault((sf.rel, child.name), [])
                    self._collect_class_locks(sf, child)
                    visit(child, child.name, None)
                else:
                    if cls is None and parent is None:
                        self._collect_module_lock(sf, child)
                    visit(child, cls, parent)

        visit(sf.tree, None, None)

    def _collect_class_locks(self, sf: SourceFile, cls: ast.ClassDef) -> None:
        locks: dict[str, str] = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _lock_ctor(node.value):
                kind = _leaf(dotted_name(node.value.func)) or "Lock"
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        locks[attr] = kind
        if locks:
            self.class_locks[(sf.rel, cls.name)] = locks

    def _collect_module_lock(self, sf: SourceFile, node: ast.AST) -> None:
        if isinstance(node, ast.Assign) and _lock_ctor(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.module_locks[(sf.rel, tgt.id)] = (
                        _leaf(dotted_name(node.value.func)) or "Lock"
                    )

    # -- resolution --------------------------------------------------------

    def _module_file(self, rel: str, modpath: str) -> str | None:
        """Scan-relative file for a dotted module path, by suffix."""
        parts = modpath.split(".")
        for i in range(len(parts)):
            cand = "/".join(parts[i:]) + ".py"
            sf = self.index.by_rel(cand)
            if sf is not None:
                return sf.rel
        return None

    def _resolve_name(self, fn: _Fn | None, rel: str, name: str) -> _Fn | None:
        """A bare-name callee: nested defs up the enclosing chain, then
        module functions, then imported symbols, then classes (their
        ``__init__``)."""
        scope = fn
        while scope is not None:
            if name in scope.children:
                return scope.children[name]
            scope = scope.parent
        if (rel, name) in self.module_fns:
            return self.module_fns[(rel, name)]
        if (rel, name) in self.classes:
            return self.methods.get((rel, name, "__init__"))
        target = self.imports.get(rel, {}).get(name)
        if target is not None:
            mod, _, leafname = target.rpartition(".")
            mrel = self._module_file(rel, mod) if mod else None
            if mrel is not None:
                if (mrel, leafname) in self.module_fns:
                    return self.module_fns[(mrel, leafname)]
                if (mrel, leafname) in self.classes:
                    return self.methods.get((mrel, leafname, "__init__"))
        return None

    def _resolve_call(self, fn: _Fn | None, sf: SourceFile,
                      call: ast.Call) -> _Fn | None:
        func = call.func
        if isinstance(func, ast.Name):
            return self._resolve_name(fn, sf.rel, func.id)
        if isinstance(func, ast.Attribute):
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and fn is not None
                and fn.cls is not None
            ):
                return self.methods.get((sf.rel, fn.cls, func.attr))
            name = dotted_name(func)
            if name is not None and "." in name:
                head, _, leafname = name.rpartition(".")
                modpath = self.imports.get(sf.rel, {}).get(
                    head.split(".", 1)[0]
                )
                if modpath is not None:
                    full = head.replace(head.split(".", 1)[0], modpath, 1)
                    mrel = self._module_file(sf.rel, full)
                    if mrel is not None:
                        return self.module_fns.get((mrel, leafname))
        return None

    def _resolve_target_ref(self, fn: _Fn | None, sf: SourceFile,
                            ref: ast.AST) -> list[_Fn]:
        """A function REFERENCE (thread target / submit arg).  Unlike
        plain calls, ``self.x.m`` resolves fuzzily by method name — the
        submission site is explicit and rare, so over-approximating
        worker context there is the safe direction."""
        if isinstance(ref, ast.Name):
            got = self._resolve_name(fn, sf.rel, ref.id)
            return [got] if got is not None else []
        if isinstance(ref, ast.Attribute):
            if (
                isinstance(ref.value, ast.Name)
                and ref.value.id == "self"
                and fn is not None
                and fn.cls is not None
            ):
                got = self.methods.get((sf.rel, fn.cls, ref.attr))
                if got is not None:
                    return [got]
            return list(self.methods_by_name.get(ref.attr, []))
        return []

    def _resolve_calls(self) -> None:
        worker_seeds: list[tuple[_Fn, str]] = []

        attr_called: set[str] = set()

        def scan_calls(owner: _Fn | None, sf: SourceFile, root: ast.AST):
            for node in walk_scoped(root):
                if not isinstance(node, ast.Call):
                    continue
                callee = self._resolve_call(owner, sf, node)
                if callee is not None and owner is not None:
                    owner.calls.append(callee)
                    callee.called = True
                elif callee is not None:
                    callee.called = True  # module-level call
                elif isinstance(node.func, ast.Attribute):
                    # unresolved obj.m(...) — evidence that a method
                    # named m has a plain (main-context) caller even
                    # when the receiver can't be typed statically
                    attr_called.add(node.func.attr)
                leaf = _call_leaf(node)
                targets: list[ast.AST] = []
                if leaf == "Thread":
                    targets = [
                        kw.value for kw in node.keywords
                        if kw.arg == "target"
                    ]
                elif leaf == "submit" and isinstance(
                    node.func, ast.Attribute
                ) and node.args:
                    targets = [node.args[0]]
                elif leaf == "map" and isinstance(
                    node.func, ast.Attribute
                ) and node.args:
                    targets = [node.args[0]]
                for ref in targets:
                    for t in self._resolve_target_ref(owner, sf, ref):
                        site = f"{sf.rel}:{node.lineno}"
                        worker_seeds.append((t, site))

        for fn in self.fns:
            scan_calls(fn, fn.sf, fn.node)
        for sf in self.index.files:
            if sf.tree is None:
                continue
            # module-level statements (outside any def)
            scan_calls(None, sf, sf.tree)
        self._worker_seeds = worker_seeds
        self._attr_called = attr_called

    # -- classification ----------------------------------------------------

    def _classify(self) -> None:
        seeded: set[int] = set()
        stack: list[_Fn] = []
        for fn, site in self._worker_seeds:
            if id(fn) not in seeded:
                seeded.add(id(fn))
                fn.worker_seed_site = site
                stack.append(fn)
        worker: set[int] = set(seeded)
        while stack:
            fn = stack.pop()
            fn.is_worker = True
            for callee in fn.calls:
                if id(callee) not in worker:
                    worker.add(id(callee))
                    stack.append(callee)
        # main roots: no resolved in-package caller and not exclusively
        # a thread entrypoint (an unresolved call site keeps its callee
        # a root — conservative toward main).  A seeded entrypoint that
        # ALSO has an unresolved obj.m() caller by its name (TrainStep.
        # put_batch: submitted to the ring AND called inline) is both.
        stack = [
            fn for fn in self.fns
            if not fn.called
            and (id(fn) not in seeded or fn.name in self._attr_called)
        ]
        main: set[int] = {id(fn) for fn in stack}
        while stack:
            fn = stack.pop()
            fn.is_main = True
            for callee in fn.calls:
                if id(callee) not in main:
                    main.add(id(callee))
                    stack.append(callee)

    # -- shared lock machinery (XF007 + sanitizer export) ------------------

    def lock_node(self, fn: _Fn | None, sf: SourceFile,
                  expr: ast.AST) -> str | None:
        """The lock-graph node acquired by a ``with <expr>`` item, or
        None when the expression isn't a known lock."""
        attr = _self_attr(expr)
        if attr is not None and fn is not None and fn.cls is not None:
            if attr in self.class_locks.get((sf.rel, fn.cls), {}):
                return f"{fn.cls}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if (sf.rel, expr.id) in self.module_locks:
                return f"{sf.rel}:{expr.id}"
        return None

    def lock_kind(self, node: str) -> str:
        if ":" in node:
            rel, name = node.split(":", 1)
            return self.module_locks.get((rel, name), "Lock")
        cls, _, attr = node.rpartition(".")
        for (rel, c), locks in self.class_locks.items():
            if c == cls and attr in locks:
                return locks[attr]
        return "Lock"


def get_context(index: PackageIndex) -> ConcurrencyContext:
    ctx = getattr(index, "_concurrency_ctx", None)
    if ctx is None:
        ctx = ConcurrencyContext(index)
        index._concurrency_ctx = ctx
    return ctx


# -- XF006 ----------------------------------------------------------------


class ThreadLifecycle(Rule):
    id = "XF006"
    title = "thread/executor without a bounded shutdown path"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        ctx = get_context(index)
        for (rel, cls), cls_node in ctx.classes.items():
            sf = index.by_rel(rel)
            if sf is not None:
                yield from self._check_class(ctx, sf, rel, cls, cls_node)
        for fn in ctx.fns:
            yield from self._check_locals(fn)

    # -- class-owned threads/executors ------------------------------------

    def _closer_reachable(self, ctx: ConcurrencyContext, rel: str,
                          cls: str) -> list[_Fn]:
        """Methods reachable (same-class self-calls) from a shutdown-
        path method — where the join/shutdown must live."""
        methods = ctx.class_methods.get((rel, cls), [])
        reach = [m for m in methods if m.name in _CLOSER_METHODS]
        seen = {id(m) for m in reach}
        stack = list(reach)
        while stack:
            m = stack.pop()
            for callee in m.calls:
                if callee.cls == cls and id(callee) not in seen:
                    seen.add(id(callee))
                    reach.append(callee)
                    stack.append(callee)
        return reach

    def _check_class(self, ctx: ConcurrencyContext, sf: SourceFile,
                     rel: str, cls: str,
                     cls_node: ast.ClassDef) -> Iterator[Finding]:
        thread_attrs: dict[str, ast.Call] = {}
        exec_attrs: dict[str, ast.Call] = {}
        started: set[str] = set()
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                leaf = _call_leaf(node.value)
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if leaf == "Thread":
                        thread_attrs[attr] = node.value
                    elif leaf is not None and leaf.endswith("PoolExecutor"):
                        exec_attrs[attr] = node.value
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr == "start":
                attr = _self_attr(node.func.value)
                if attr is not None:
                    started.add(attr)
        if not thread_attrs and not exec_attrs:
            return
        closers = self._closer_reachable(ctx, rel, cls)
        joins: list[ast.Call] = []
        shutdowns: list[ast.Call] = []
        for m in closers:
            for node in walk_scoped(m.node):
                join = _thread_join_call(node)
                if join is not None:
                    joins.append(join)
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ) and node.func.attr == "shutdown":
                    shutdowns.append(node)
        for attr, ctor in thread_attrs.items():
            if attr not in started:
                continue
            if not joins:
                yield self.finding(
                    sf, ctor,
                    f"thread self.{attr} of {cls} is started but no "
                    "join() is reachable from a close()/__exit__/stop() "
                    "method — an abandoned consumer leaks the thread "
                    "(the _PrefetchIter leak class); join it with a "
                    "timeout on the shutdown path",
                )
            elif not any(_timeout_arg(j) for j in joins):
                yield self.finding(
                    sf, ctor,
                    f"thread self.{attr} of {cls} is joined without a "
                    "timeout on its shutdown path — a wedged worker "
                    "blocks close() forever; use join(timeout=...) and "
                    "surface is_alive() leaks",
                )
        for attr, ctor in exec_attrs.items():
            if not shutdowns:
                yield self.finding(
                    sf, ctor,
                    f"executor self.{attr} of {cls} has no shutdown() "
                    "reachable from a close()/__exit__/stop() method — "
                    "its worker threads outlive the owner; call "
                    "shutdown() on the shutdown path (or use `with`)",
                )

    # -- function-local threads/executors ----------------------------------

    def _check_locals(self, fn: _Fn) -> Iterator[Finding]:
        with_items: set[int] = set()
        self_assigned: set[int] = set()
        local_threads: list[ast.Call] = []
        local_execs: list[ast.Call] = []
        for node in walk_scoped(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        with_items.add(id(sub))
            if isinstance(node, ast.Assign):
                to_self = any(
                    _self_attr(t) is not None for t in node.targets
                )
                if to_self:
                    for sub in ast.walk(node.value):
                        self_assigned.add(id(sub))
        for node in walk_scoped(fn.node):
            if not isinstance(node, ast.Call):
                continue
            leaf = _call_leaf(node)
            if id(node) in self_assigned or id(node) in with_items:
                continue
            if leaf == "Thread":
                local_threads.append(node)
            elif leaf is not None and leaf.endswith("PoolExecutor"):
                local_execs.append(node)
        if not local_threads and not local_execs:
            return
        joins = [
            join for node in walk_scoped(fn.node)
            if (join := _thread_join_call(node)) is not None
        ]
        shutdowns = [
            node for node in walk_scoped(fn.node)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "shutdown"
        ]
        for ctor in local_threads:
            if not joins:
                yield self.finding(
                    fn.sf, ctor,
                    f"thread created in {fn.qualname}() is never "
                    "joined in the function — fire-and-forget threads "
                    "outlive their work and evade shutdown; join with "
                    "a timeout (or own it on self with a close() path)",
                )
            elif not any(_timeout_arg(j) for j in joins):
                yield self.finding(
                    fn.sf, ctor,
                    f"thread created in {fn.qualname}() is joined "
                    "without a timeout — a wedged worker hangs the "
                    "caller forever; use join(timeout=...)",
                )
        for ctor in local_execs:
            if not shutdowns:
                yield self.finding(
                    fn.sf, ctor,
                    f"executor created in {fn.qualname}() without "
                    "`with` or a shutdown() call — worker threads "
                    "leak past the function; use a `with` block",
                )


# -- XF007 ----------------------------------------------------------------

_BLOCKING_ATTRS = ("join", "result", "wait", "get")


class LockOrder(Rule):
    id = "XF007"
    title = "lock-order cycle / blocking call under a lock"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        ctx = get_context(index)
        edges, sites, blocking = _lock_analysis(ctx)
        yield from (
            self.finding(sf, node, msg) for sf, node, msg in blocking
        )
        for cycle in _find_cycles(edges):
            a = cycle[0]
            nxt = cycle[1] if len(cycle) > 1 else a
            sf, node = sites[(a, nxt)]
            path = " -> ".join(cycle + (a,))
            if len(cycle) == 1:
                kind = ctx.lock_kind(a)
                if kind == "RLock":
                    continue  # reentrant: self-nesting is legal
                yield self.finding(
                    sf, node,
                    f"lock {a} is re-acquired while already held "
                    "(non-reentrant Lock) — self-deadlock; use RLock "
                    "or restructure",
                )
            else:
                yield self.finding(
                    sf, node,
                    f"lock-order cycle {path} — two threads taking "
                    "these locks in opposite orders deadlock; impose "
                    "one global order (docs/ANALYSIS.md XF007)",
                )


def _lock_analysis(ctx: ConcurrencyContext):
    """(edges, edge_sites, blocking_findings) over the whole package.

    Edges are lexical nestings of known-lock ``with`` blocks plus, for
    calls made while holding a lock, every lock the callee's transitive
    closure acquires.
    """
    direct: dict[int, set[str]] = {}
    calls_held: list[tuple[str, _Fn, SourceFile, ast.AST]] = []
    edges: dict[str, set[str]] = {}
    sites: dict[tuple[str, str], tuple[SourceFile, ast.AST]] = {}
    blocking: list[tuple[SourceFile, ast.AST, str]] = []

    def add_edge(a: str, b: str, sf: SourceFile, node: ast.AST) -> None:
        edges.setdefault(a, set()).add(b)
        sites.setdefault((a, b), (sf, node))

    def scan(fn: _Fn) -> None:
        acquired: set[str] = set()

        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef,
                     ast.ClassDef, ast.Lambda),
                ):
                    continue
                child_held = held
                if isinstance(child, ast.With):
                    # items acquire LEFT TO RIGHT: in `with a, b:` the
                    # edge a->b comes from the accumulating held set,
                    # not the outer one
                    for item in child.items:
                        lock = ctx.lock_node(fn, fn.sf, item.context_expr)
                        if lock is None:
                            continue
                        acquired.add(lock)
                        for h in child_held:
                            add_edge(h, lock, fn.sf, child)
                        child_held = child_held + (lock,)
                if isinstance(child, ast.Call) and held:
                    callee = ctx._resolve_call(fn, fn.sf, child)
                    if callee is not None:
                        calls_held.append(
                            (held[-1], callee, fn.sf, child)
                        )
                    leaf = (
                        child.func.attr
                        if isinstance(child.func, ast.Attribute)
                        else None
                    )
                    if leaf in _BLOCKING_ATTRS:
                        is_blocking = (
                            leaf != "get"
                            and not _timeout_arg(child)
                        ) or (
                            leaf == "get"
                            and not child.args
                            and not any(
                                kw.arg == "timeout"
                                for kw in child.keywords
                            )
                        )
                        # dict.get(k)/deque ops pass args; a bare
                        # .get() is the blocking queue idiom
                        if is_blocking:
                            blocking.append((
                                fn.sf, child,
                                f".{leaf}() without a timeout while "
                                f"holding {held[-1]} — a blocked "
                                "holder stalls every other thread at "
                                "the lock; add a timeout or move the "
                                "wait outside the critical section",
                            ))
                visit(child, child_held)

        visit(fn.node, ())
        direct[id(fn)] = acquired

    for fn in ctx.fns:
        scan(fn)

    # transitive acquisition closure per function
    closure: dict[int, set[str]] = {
        id(fn): set(direct.get(id(fn), ())) for fn in ctx.fns
    }
    changed = True
    while changed:
        changed = False
        for fn in ctx.fns:
            mine = closure[id(fn)]
            before = len(mine)
            for callee in fn.calls:
                mine |= closure.get(id(callee), set())
            if len(mine) != before:
                changed = True
    for held, callee, sf, node in calls_held:
        for lock in closure.get(id(callee), ()):  # interprocedural edge
            add_edge(held, lock, sf, node)
    return edges, sites, blocking


def _find_cycles(edges: dict[str, set[str]]) -> list[tuple[str, ...]]:
    """Elementary cycles.  Every cycle is discovered from its smallest
    node only (the ``nxt > start`` prune), so each PATH is already the
    cycle's canonical rotation — deduping by path keeps two
    opposite-direction cycles over the same node set distinct (A->B->C
    and A->C->B are different deadlocks)."""
    cycles: list[tuple[str, ...]] = []
    seen: set[tuple[str, ...]] = set()
    for start in sorted(edges):
        stack: list[tuple[str, tuple[str, ...]]] = [(start, (start,))]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, ())):
                if nxt == start:
                    if path not in seen:
                        seen.add(path)
                        cycles.append(path)
                elif nxt not in path and nxt > start:
                    # only explore nodes > start: each cycle is found
                    # from its smallest node exactly once
                    stack.append((nxt, path + (nxt,)))
    return cycles


def static_lock_order(
    paths: list[str] | PackageIndex,
) -> dict[str, list[str]]:
    """The static XF007 lock-acquisition graph as plain JSON-able data
    — the contract the runtime sanitizer (analysis/sanitizer.py)
    cross-checks observed acquisition orders against."""
    index = (
        paths if isinstance(paths, PackageIndex) else PackageIndex(paths)
    )
    edges, _, _ = _lock_analysis(get_context(index))
    return {a: sorted(bs) for a, bs in sorted(edges.items())}


# -- XF008 ----------------------------------------------------------------


@dataclass
class _Access:
    attr: str
    fn: _Fn
    guarded: bool
    is_write: bool
    node: ast.AST


class SharedStateDiscipline(Rule):
    id = "XF008"
    title = "cross-thread-context state without a guard"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        ctx = get_context(index)
        for (rel, cls) in ctx.classes:
            sf = index.by_rel(rel)
            if sf is not None:
                yield from self._check_class(ctx, sf, rel, cls)

    def _check_class(self, ctx: ConcurrencyContext, sf: SourceFile,
                     rel: str, cls: str) -> Iterator[Finding]:
        methods = ctx.class_methods.get((rel, cls), [])
        if not methods:
            return
        locks = set(ctx.class_locks.get((rel, cls), ()))
        method_names = {m.name for m in methods}
        primitives: set[str] = set()
        cls_node = ctx.classes[(rel, cls)]
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                leaf = _call_leaf(node.value)
                if leaf in _THREADSAFE_CTORS:
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            primitives.add(attr)
        accesses: list[_Access] = []
        for m in methods:
            self._collect(ctx, m, locks, accesses)
            for nested in self._nested(m):
                self._collect(ctx, nested, locks, accesses)
        by_attr: dict[str, list[_Access]] = {}
        for a in accesses:
            if a.attr in locks or a.attr in primitives:
                continue
            if a.attr in method_names:
                continue  # bound-method references, not state
            by_attr.setdefault(a.attr, []).append(a)
        for attr, sites in sorted(by_attr.items()):
            outside = [
                s for s in sites
                if s.fn.name not in _CONSTRUCTOR_METHODS
            ]
            if not any(s.is_write for s in outside):
                continue  # init-then-read-only: publication, not a race
            worker = [s for s in outside if s.fn.is_worker]
            main = [
                s for s in outside
                if s.fn.is_main or not s.fn.is_worker
            ]
            if not worker or not main:
                continue  # single-context state
            for s in outside:
                if s.guarded:
                    continue
                kind = "written" if s.is_write else "read"
                wm = sorted({x.fn.name for x in worker})[0]
                mm = sorted({x.fn.name for x in main})[0]
                yield self.finding(
                    sf, s.node,
                    f"self.{attr} of {cls} crosses thread contexts "
                    f"(worker-context {wm}(), main-context {mm}()) but "
                    f"is {kind} in {s.fn.name}() without a lock — "
                    "guard every access or hand off via a "
                    "queue/Event (XF008, docs/ANALYSIS.md)",
                )

    @staticmethod
    def _nested(fn: _Fn) -> list[_Fn]:
        out: list[_Fn] = []
        stack = list(fn.children.values())
        while stack:
            f = stack.pop()
            out.append(f)
            stack.extend(f.children.values())
        return out

    def _collect(self, ctx: ConcurrencyContext, fn: _Fn,
                 locks: set[str], out: list[_Access]) -> None:
        def lock_item(item: ast.withitem) -> bool:
            return _self_attr(item.context_expr) in locks

        def visit(node: ast.AST, guarded: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef,
                     ast.ClassDef, ast.Lambda),
                ):
                    continue
                child_guarded = guarded
                if isinstance(child, ast.With):
                    child_guarded = guarded or any(
                        lock_item(i) for i in child.items
                    )
                if isinstance(child, ast.Subscript) and isinstance(
                    child.ctx, ast.Store
                ):
                    attr = _self_attr(child)
                    if attr is not None:
                        # self.x[k] = v: ONE write to x (the inner
                        # self.x Load must not double as a read site)
                        out.append(_Access(
                            attr, fn, child_guarded, True, child
                        ))
                        visit(child.slice, child_guarded)
                        continue
                if isinstance(child, ast.Attribute) and isinstance(
                    child.value, ast.Name
                ) and child.value.id == "self":
                    if isinstance(child.ctx, ast.Store):
                        out.append(_Access(
                            child.attr, fn, child_guarded, True, child
                        ))
                    elif isinstance(child.ctx, ast.Load):
                        out.append(_Access(
                            child.attr, fn, child_guarded, False, child
                        ))
                visit(child, child_guarded)

        visit(fn.node, False)


# -- XF009 ----------------------------------------------------------------


class HeartbeatCoverage(Rule):
    id = "XF009"
    title = "worker loop without a watchdog heartbeat"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        ctx = get_context(index)
        has_beat = self._heartbeat_closure(ctx)
        for fn in ctx.fns:
            if not fn.is_worker or not _is_hot_path(fn.sf.rel):
                continue
            for node in walk_scoped(fn.node):
                if isinstance(node, ast.While) and _unbounded(node.test):
                    if not self._loop_beats(ctx, fn, node, has_beat):
                        yield self.finding(
                            fn.sf, node,
                            f"unbounded loop in worker-context "
                            f"{fn.qualname}() (hot-path module) never "
                            "pulses the flight-recorder heartbeat — "
                            "its silence is invisible to the watchdog "
                            "and `obs doctor`; call note_loader/"
                            "note_serve/_pulse each iteration "
                            "(docs/OBSERVABILITY.md) or pragma with "
                            "a justification",
                        )

    @staticmethod
    def _heartbeat_closure(ctx: ConcurrencyContext) -> set[int]:
        direct: set[int] = set()
        for fn in ctx.fns:
            for node in walk_scoped(fn.node):
                if isinstance(node, ast.Call) and _call_leaf(
                    node
                ) in _HEARTBEAT_CALLS:
                    direct.add(id(fn))
                    break
        changed = True
        while changed:
            changed = False
            for fn in ctx.fns:
                if id(fn) in direct:
                    continue
                if any(id(c) in direct for c in fn.calls):
                    direct.add(id(fn))
                    changed = True
        return direct

    def _loop_beats(self, ctx: ConcurrencyContext, fn: _Fn,
                    loop: ast.While, has_beat: set[int]) -> bool:
        # pruned walk (walk_scoped semantics): a heartbeat inside a
        # nested def/lambda the loop merely DEFINES is not a beat —
        # only calls the loop body actually executes count
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            node = stack.pop()
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef,
                 ast.Lambda, ast.ClassDef),
            ):
                continue
            if isinstance(node, ast.Call):
                if _call_leaf(node) in _HEARTBEAT_CALLS:
                    return True
                callee = ctx._resolve_call(fn, fn.sf, node)
                if callee is not None and id(callee) in has_beat:
                    return True
            stack.extend(ast.iter_child_nodes(node))
        return False


def _unbounded(test: ast.AST) -> bool:
    """A loop condition with no comparison is treated as unbounded:
    ``while True``, ``while not stopping``, ``while not
    stop.is_set()``.  Counting loops (``while n < limit``) compare."""
    if isinstance(test, ast.Constant):
        return bool(test.value)
    return not any(isinstance(n, ast.Compare) for n in ast.walk(test))
