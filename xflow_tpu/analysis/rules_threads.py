"""XF003 — lock discipline for classes that own a threading.Lock.

The overlap machinery (io/loader.py prefetch + parse pool,
serve/batcher.py worker thread, obs/registry.py metric mutations from
every thread) only stays correct because shared attributes are mutated
under the owning object's lock.  A mutation added outside ``with
self._lock`` compiles, passes single-threaded tests, and then tears
state under real concurrency — exactly the class of bug a runtime test
suite is worst at catching.

The rule: for every class that assigns a ``threading.Lock``/``RLock``
to a ``self.*`` attribute, any OTHER ``self.*`` attribute that is
written under a lock somewhere must be written under a lock everywhere
(``__init__`` is exempt — the object is not yet shared during
construction).  Subscript stores (``self._counters[k] = v``) count as
writes to the attribute.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator

from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    dotted_name,
)

_CONSTRUCTOR_METHODS = ("__init__", "__new__")


def _lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return name is not None and name.rsplit(".", 1)[-1] in ("Lock", "RLock")


def _self_attr(node: ast.AST) -> str | None:
    """'x' for ``self.x``; also resolves ``self.x[k]`` to 'x'."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class _Write:
    attr: str
    method: str
    guarded: bool
    node: ast.AST


class LockDiscipline(Rule):
    id = "XF003"
    title = "unlocked mutation of lock-guarded state"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        for sf in index.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(sf, node)

    def _check_class(
        self, sf: SourceFile, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        locks = {
            attr
            for node in ast.walk(cls)
            if isinstance(node, ast.Assign) and _lock_ctor(node.value)
            for tgt in node.targets
            if (attr := _self_attr(tgt)) is not None
        }
        if not locks:
            return
        writes: list[_Write] = []
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_writes(item, locks, writes)
        guarded_attrs = {w.attr for w in writes if w.guarded}
        guard_example = {
            w.attr: w for w in reversed(writes) if w.guarded
        }
        lock_name = sorted(locks)[0]
        for w in writes:
            if (
                not w.guarded
                and w.attr in guarded_attrs
                and w.method not in _CONSTRUCTOR_METHODS
            ):
                g = guard_example[w.attr]
                # no line numbers in the message: baseline matching is
                # (rule, path, message) and must survive line drift
                yield self.finding(
                    sf,
                    w.node,
                    f"self.{w.attr} of {cls.name} is written in "
                    f"{w.method}() without the lock but under `with "
                    f"self.{lock_name}` in {g.method}() — an unlocked "
                    "mutation of shared state races with worker "
                    "threads",
                )

    def _collect_writes(
        self,
        method: ast.FunctionDef | ast.AsyncFunctionDef,
        locks: set[str],
        out: list[_Write],
    ) -> None:
        def lock_item(item: ast.withitem) -> bool:
            return _self_attr(item.context_expr) in locks

        def visit(node: ast.AST, guarded: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_guarded = guarded
                if isinstance(child, ast.With):
                    child_guarded = guarded or any(
                        lock_item(i) for i in child.items
                    )
                if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        child.targets
                        if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    for tgt in targets:
                        for leaf in self._flatten(tgt):
                            attr = _self_attr(leaf)
                            if attr is not None and attr not in locks:
                                out.append(
                                    _Write(
                                        attr,
                                        method.name,
                                        child_guarded,
                                        leaf,
                                    )
                                )
                visit(child, child_guarded)

        visit(method, False)

    @staticmethod
    def _flatten(target: ast.AST) -> Iterator[ast.AST]:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                yield from LockDiscipline._flatten(elt)
        else:
            yield target
