"""Runtime lock-order sanitizer — XF007's runtime companion.

The static rule (rules_concurrency.LockOrder) proves the lock graph
acyclic for the acquisition orders it can SEE: lexical ``with`` nesting
plus resolvable calls.  Acquisitions it cannot see — callbacks, locks
reached through untyped references, ``acquire()`` calls — only show up
at runtime.  This module closes that gap: an instrumented lock wrapper
records every *actual* nested acquisition during the tier-1 lock-stress
tests, and the observed edges are cross-checked against the static
XF007 graph (``rules_concurrency.static_lock_order``).  A cycle in the
combined graph that the static pass alone doesn't have is a
**contradiction**: real executions take those locks in an order the
static model says (or would say, once both orders ship) can deadlock.

Opt-in and zero-overhead when off:

* ``maybe_instrument(obj, attr)`` is a no-op returning ``None`` unless
  armed — the object keeps its plain ``threading.Lock``, no wrapper is
  even allocated;
* armed via the ``XFLOW_LOCK_SANITIZER`` env var, ``Config.
  obs_lock_sanitizer`` (the Trainer instruments its obs-stack locks —
  MetricsLogger/FlightRecorder/Watchdog/MetricsRegistry), or
  explicitly by constructing a ``LockOrderSanitizer`` and calling
  ``instrument`` (what the lock-stress tests do);
* when armed, the cost per acquisition is one thread-local list
  append plus — only while another lock is already held — a dict
  insert under the sanitizer's own (internal, never-nested) lock.

Naming: instrumented locks default to ``ClassName.attr``, matching the
static graph's node names, so observed and static edges join without a
translation table.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Iterable, Mapping

from xflow_tpu.analysis.rules_concurrency import _find_cycles

ENV_FLAG = "XFLOW_LOCK_SANITIZER"


def armed(environ: Mapping[str, str] = os.environ) -> bool:
    """Is the sanitizer requested by the environment?"""
    return environ.get(ENV_FLAG, "") not in ("", "0", "false", "off")


class _InstrumentedLock:
    """A ``threading.Lock``/``RLock`` proxy that reports acquisition
    order to its sanitizer.  Context-manager and acquire/release
    compatible; the wrapped lock does the real blocking."""

    __slots__ = ("_lock", "name", "_san")

    def __init__(self, lock: Any, name: str, san: "LockOrderSanitizer"):
        self._lock = lock
        self.name = name
        self._san = san

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            # record AFTER acquiring: the edge is the order that
            # actually happened, not the order that was attempted
            self._san._acquired(self.name)
        return got

    def release(self) -> None:
        self._san._released(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()


class LockOrderSanitizer:
    """Records (held -> acquired) edges across every instrumented lock
    and cross-checks them against the static XF007 graph."""

    def __init__(self) -> None:
        self._meta = threading.Lock()  # guards _edges; never nested
        self._tls = threading.local()
        self._edges: dict[str, set[str]] = {}

    # -- instrumentation ----------------------------------------------------

    def wrap(self, lock: Any, name: str) -> _InstrumentedLock:
        return _InstrumentedLock(lock, name, self)

    def instrument(
        self, obj: Any, attr: str, name: str | None = None
    ) -> _InstrumentedLock:
        """Swap ``obj.<attr>`` for an instrumented wrapper (idempotent).
        The default name ``ClassName.attr`` matches the static graph's
        node naming."""
        current = getattr(obj, attr)
        if isinstance(current, _InstrumentedLock):
            return current
        wrapper = self.wrap(
            current, name or f"{type(obj).__name__}.{attr}"
        )
        setattr(obj, attr, wrapper)
        return wrapper

    # -- recording ----------------------------------------------------------

    def _stack(self) -> list[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _acquired(self, name: str) -> None:
        stack = self._stack()
        if stack:
            with self._meta:
                for held in stack:
                    if held != name:  # RLock re-entry is not an edge
                        self._edges.setdefault(held, set()).add(name)
        stack.append(name)

    def _released(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                break

    # -- reporting ----------------------------------------------------------

    def edges(self) -> dict[str, set[str]]:
        """Observed (held -> acquired) pairs so far."""
        with self._meta:
            return {a: set(bs) for a, bs in self._edges.items()}

    def reset(self) -> None:
        with self._meta:
            self._edges.clear()

    def contradictions(
        self, static_edges: Mapping[str, Iterable[str]]
    ) -> list[str]:
        """Cycles in (static ∪ observed) that the static graph alone
        does not contain — i.e. real executions acquired locks in an
        order that, combined with the statically-proven orders, can
        deadlock.  Empty list == observed behavior is consistent with
        the static XF007 model."""
        combined: dict[str, set[str]] = {
            a: set(bs) for a, bs in static_edges.items()
        }
        for a, bs in self.edges().items():
            combined.setdefault(a, set()).update(bs)
        out = []
        for cycle in _find_cycles(combined):
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            if all(b in static_edges.get(a, ()) for a, b in pairs):
                continue  # purely static cycle: XF007's finding, not ours
            out.append(" -> ".join(cycle + (cycle[0],)))
        return out


_GLOBAL = LockOrderSanitizer()


def global_sanitizer() -> LockOrderSanitizer:
    """The process-wide instance Config-armed runtime code reports to."""
    return _GLOBAL


def maybe_instrument(
    obj: Any,
    attr: str,
    name: str | None = None,
    sanitizer: LockOrderSanitizer | None = None,
    environ: Mapping[str, str] = os.environ,
) -> _InstrumentedLock | None:
    """Instrument ``obj.<attr>`` only when the sanitizer is armed;
    otherwise a no-op returning None (the plain lock stays — zero
    overhead off)."""
    if sanitizer is None:
        if not armed(environ):
            return None
        sanitizer = _GLOBAL
    return sanitizer.instrument(obj, attr, name)
