"""XF010–XF014 — sharding & memory rules over the symbolic shape/dtype
dataflow (analysis/shapeflow.py).

ROADMAP item 2 (pod-scale embedding sharding at T=2^28) is blocked by
exactly one hazard class: jitted code that silently materializes a
full-table ``[T, ...]`` transient — multi-GB per table at north-star
scale — or narrows the uint64 key space carelessly on the way to the
int32 batch planes.  PR 6 gated the thread fabric before the N-stream
fan-out; these rules gate the shape/dtype/sharding/memory invariants
before the sharding work multiplies the surface:

* **XF010 full-table transient hazard** — a ``zeros_like(table)`` /
  ``zeros((T, ...))`` allocation or a ``one_hot(keys, T)`` expansion
  inside a jitted trace.  The dense update mode allocates ``[T, D]``
  gradient buffers BY DESIGN (small-table form) — those sites carry
  justified pragmas; anything new must be routed through the
  touched-rows machinery (ops/sparse.py) or justified the same way.
* **XF011 dtype discipline** — (a) ad-hoc ``.astype(np.int32)`` /
  ``np.int32(...)`` narrowing of key planes: the uint64 key space must
  narrow through the ONE audited choke point
  (``io/batch.py::narrow_keys_i32``) so a future table-size bump can't
  silently wrap; (b) explicit float64 (``np.float64`` / ``dtype=float``)
  inside traced code — weak-type promotion doubles every downstream
  buffer.
* **XF012 sharding coverage** — ``jax.device_put`` without a sharding
  in hot-path modules, ``NamedSharding``/``PartitionSpec`` constructed
  outside ``parallel/mesh.py`` (the helpers are the one source of
  layout truth), and collective axis names that don't match the mesh's
  declared axes.
* **XF013 donation safety** — a buffer passed in a ``donate_argnums``
  position is dead after the call; reading it afterwards is
  use-after-donate (garbage on TPU, silent aliasing elsewhere).
* **XF014 transient-HBM budget** — per jit entry, the summed bytes of
  every transient the flow can size, evaluated at the north-star
  geometry (T=2^28, flagship D per model family), gated against the
  committed ``memory-budget.json`` baseline-style: estimates over
  budget, entries missing for new jits, and stale entries all fail.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Iterator

from xflow_tpu.analysis.core import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    dotted_name,
    walk_scoped,
)
from xflow_tpu.analysis.rules_concurrency import get_context
from xflow_tpu.analysis.shapeflow import (
    ArrV,
    ConfigV,
    MapV,
    MemoryContext,
    UNK,
    dsym,
    get_memory_context,
    shape_str,
)

DEFAULT_BUDGET = "memory-budget.json"

# the sanctioned u64 -> i32 narrowing choke point (io/batch.py)
NARROW_HELPER = "narrow_keys_i32"

_HOT_PATH_PREFIXES = ("parallel/", "serve/", "ops/", "io/")
_HOT_PATH_FILES = ("trainer.py",)

_COLLECTIVE_LEAVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "axis_index",
    "ppermute", "pshuffle", "all_to_all",
}


def _is_hot_path(rel: str) -> bool:
    if rel in _HOT_PATH_FILES or any(
        rel.endswith("/" + f) for f in _HOT_PATH_FILES
    ):
        return True
    return any(
        rel.startswith(p) or ("/" + p) in rel for p in _HOT_PATH_PREFIXES
    )


# -- seeds -----------------------------------------------------------------
#
# Parameter-name conventions of the jit entries (parallel/step.py): the
# State pytree, the batch plane dict, config.  Callees get their values
# from the call-site flow, so these only matter at entry functions.

_T, _D, _B, _K, _Kh, _H = (
    dsym("T"), dsym("D"), dsym("B"), dsym("K"), dsym("Kh"), dsym("H")
)
# tiered-store dims (store/hot.py): Hc = hot-tier rows
# (cfg.hot_capacity), M = per-batch miss-block capacity (granule-
# bucketed, <= B*K), P = the fixed promotion/demotion transfer width
# (store/hot.py::PROMOTE_CAP)
_Hc, _M, _P = dsym("Hc"), dsym("M"), dsym("P")


def _table() -> MapV:
    return MapV({}, lambda: ArrV((_T, _D), "float32"))


def _hot_table() -> MapV:
    return MapV({}, lambda: ArrV((_Hc, _D), "float32"))


def _batch() -> MapV:
    f32 = "float32"
    return MapV(
        {
            "keys": ArrV((_B, _K), "int32"),
            "slots": ArrV((_B, _K), "int32"),
            "vals": ArrV((_B, _K), f32),
            "mask": ArrV((_B, _K), f32),
            "hot_keys": ArrV((_B, _Kh), "int32"),
            "hot_slots": ArrV((_B, _Kh), "int32"),
            "hot_vals": ArrV((_B, _Kh), f32),
            "hot_mask": ArrV((_B, _Kh), f32),
            "labels": ArrV((_B,), f32),
            "weights": ArrV((_B,), f32),
        },
        None,
    )


def seed_param(name: str) -> Any:
    f32 = "float32"
    if name == "tstate":
        # tiered device state (store/hot.py): tables are [Hc, D]
        return MapV(
            {
                "tables": MapV({}, _hot_table),
                "dense": UNK,
                "step": ArrV((), "int32"),
            },
            None,
        )
    if name == "tbatch":
        # tiered wire (store/tiered.py::plan_batch): refs replace keys;
        # miss blocks are [M, D] per table array
        return MapV(
            {
                "refs": ArrV((_B, _K), "int32"),
                "slots": ArrV((_B, _K), "int32"),
                "vals": ArrV((_B, _K), f32),
                "mask": ArrV((_B, _K), f32),
                "labels": ArrV((_B,), f32),
                "weights": ArrV((_B,), f32),
                "miss": MapV(
                    {}, lambda: MapV({}, lambda: ArrV((_M, _D), f32))
                ),
            },
            None,
        )
    if name == "slots":
        # promotion/demotion slot plane (store/hot.py fill/read)
        return ArrV((_P,), "int32")
    if name == "fill_rows":
        return MapV({}, lambda: MapV({}, lambda: ArrV((_P, _D), f32)))
    if name == "state":
        return MapV(
            {
                "tables": MapV({}, _table),
                "dense": UNK,
                "step": ArrV((), "int32"),
            },
            None,
        )
    if name == "tables":
        return MapV({}, _table)
    if name in ("table", "head", "t"):
        return _table()
    if name in ("batch", "arrays", "bslice", "w"):
        return _batch()
    if name in ("cfg", "config"):
        return ConfigV()
    if name == "w_hot":
        return ArrV((_H, _D), "float32")
    return UNK


def seed_self_attr(attr: str) -> Any:
    if attr in ("cfg", "config"):
        return ConfigV()
    return UNK


def memory_context(index: PackageIndex) -> MemoryContext:
    return get_memory_context(index, seed_param, seed_self_attr)


# -- XF010 -----------------------------------------------------------------


class FullTableTransient(Rule):
    id = "XF010"
    title = "full-table [T, ...] transient inside a jitted trace"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        mem = memory_context(index)
        seen: set[tuple[str, int]] = set()
        for key, transients in sorted(mem.flows.items()):
            for t in transients:
                site = (t.sf.rel, t.line)
                if site in seen:
                    continue
                hazard = None
                if t.kind == "alloc" and t.shape and t.shape[0] == _T:
                    hazard = (
                        f"allocates a full-table {shape_str(t.shape)} "
                        "transient"
                    )
                elif t.kind == "one_hot" and t.shape and t.shape[-1] == _T:
                    hazard = (
                        f"one-hot expands into the T dim "
                        f"({shape_str(t.shape)})"
                    )
                if hazard is None:
                    continue
                seen.add(site)
                yield Finding(
                    rule=self.id,
                    path=t.sf.rel,
                    line=t.line,
                    message=(
                        f"jitted trace {hazard} — multi-GB per table at "
                        "the north-star T=2^28 (ADVICE step.py:945 "
                        "class); route through the touched-rows update "
                        "(ops/sparse.py consolidate + gather/scatter, "
                        "Config.hot_windowend) or justify with a pragma "
                        "(docs/ANALYSIS.md XF010)"
                    ),
                )


# -- XF011 -----------------------------------------------------------------


def _expr_mentions_key(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and "key" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and "key" in node.attr.lower():
            return True
        if isinstance(node, ast.Constant) and isinstance(
            node.value, str
        ) and "key" in node.value.lower():
            return True
    return False


def _is_np_int32(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    if name is not None:
        head, _, leaf = name.rpartition(".")
        return leaf == "int32" and head in ("np", "numpy")
    return isinstance(expr, ast.Constant) and expr.value in ("int32", "i4")


class DtypeDiscipline(Rule):
    id = "XF011"
    title = "uint64-key narrowing / float64 promotion discipline"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        mem = memory_context(index)
        ctx = get_context(index)
        for sf in index.files:
            if sf.tree is None:
                continue
            yield from self._check_key_narrowing(ctx, sf)
        for fn in ctx.fns:
            if id(fn) in mem.traced:
                yield from self._check_float64(fn)

    # -- (a) ad-hoc int32 narrowing of key planes -----------------------

    def _check_key_narrowing(self, ctx, sf: SourceFile) -> Iterator[Finding]:
        # functions named after the helper ARE the choke point
        helper_spans: list[tuple[int, int]] = []
        for node in ast.walk(sf.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name == NARROW_HELPER:
                helper_spans.append(
                    (node.lineno, node.end_lineno or node.lineno)
                )

        def in_helper(lineno: int) -> bool:
            return any(a <= lineno <= b for a, b in helper_spans)

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if in_helper(getattr(node, "lineno", 0)):
                continue
            func = node.func
            # X.astype(np.int32) where X mentions a key plane
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "astype"
                and node.args
                and _is_np_int32(node.args[0])
                and _expr_mentions_key(func.value)
            ):
                yield self.finding(
                    sf, node,
                    "ad-hoc .astype(np.int32) on a key plane — the "
                    "uint64 key space must narrow through "
                    f"io/batch.py::{NARROW_HELPER} (range-checked once, "
                    "auditable everywhere) so a table_size bump past "
                    "2^31 cannot silently wrap (XF011)",
                )
            # np.int32(keys-ish-expr)
            elif (
                _is_np_int32(func)
                and node.args
                and not isinstance(node.args[0], ast.Constant)
                and _expr_mentions_key(node.args[0])
            ):
                yield self.finding(
                    sf, node,
                    "np.int32(...) coercion of a key expression — use "
                    f"io/batch.py::{NARROW_HELPER} for uint64->int32 "
                    "key narrowing (XF011)",
                )

    # -- (b) explicit float64 in traced code ----------------------------

    def _check_float64(self, fn) -> Iterator[Finding]:
        for node in walk_scoped(fn.node):
            bad: str | None = None
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is not None and name.rsplit(".", 1)[-1] == "float64":
                    bad = f"{name}(...)"
                for kw in node.keywords:
                    if kw.arg != "dtype":
                        continue
                    dt = dotted_name(kw.value)
                    if dt is not None and dt.rsplit(".", 1)[-1] in (
                        "float64",
                        "float",
                    ):
                        bad = f"dtype={dt}"
                    elif isinstance(kw.value, ast.Constant) and (
                        kw.value.value == "float64"
                    ):
                        bad = "dtype='float64'"
            if bad:
                yield self.finding(
                    fn.sf, node,
                    f"{bad} inside traced function {fn.qualname!r} — "
                    "float64 weak-type promotion doubles every "
                    "downstream buffer (and x86-emulates on TPU); keep "
                    "traced math in float32/bfloat16 (XF011)",
                )


# -- XF012 -----------------------------------------------------------------


class ShardingCoverage(Rule):
    id = "XF012"
    title = "unsharded device_put / ad-hoc sharding / unknown mesh axis"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        axes = self._declared_axes(index)
        for sf in index.files:
            if sf.tree is None:
                continue
            is_mesh_mod = sf.rel.endswith("mesh.py")
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                leaf = (
                    name.rsplit(".", 1)[-1]
                    if name
                    else (
                        node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else None
                    )
                )
                if leaf == "device_put" and _is_hot_path(sf.rel):
                    if len(node.args) < 2 and not any(
                        kw.arg in ("device", "sharding", "dst")
                        for kw in node.keywords
                    ):
                        yield self.finding(
                            sf, node,
                            "jax.device_put without a sharding in a "
                            "hot-path module — an unsharded put "
                            "replicates (or lands on device 0) and "
                            "silently de-shards table-scale arrays; "
                            "pass a parallel/mesh.py helper sharding "
                            "(table_sharding/batch_sharding/replicated)",
                        )
                elif leaf in (
                    "NamedSharding", "PositionalSharding"
                ) and not is_mesh_mod:
                    yield self.finding(
                        sf, node,
                        f"{leaf} constructed outside parallel/mesh.py — "
                        "layout truth lives in the mesh helpers "
                        "(table_sharding/batch_sharding/replicated); "
                        "ad-hoc shardings drift from the mesh axes "
                        "(XF012)",
                    )
                elif leaf in _COLLECTIVE_LEAVES and axes is not None:
                    ax = self._axis_arg(node)
                    if ax is not None and ax not in axes:
                        yield self.finding(
                            sf, node,
                            f"collective {leaf} over axis {ax!r} which "
                            "parallel/mesh.py never declares (declared: "
                            f"{sorted(axes)}) — an unknown axis name "
                            "fails at trace time only on multi-device "
                            "meshes (XF012)",
                        )

    @staticmethod
    def _axis_arg(node: ast.Call) -> str | None:
        for kw in node.keywords:
            if kw.arg == "axis_name" and isinstance(
                kw.value, ast.Constant
            ) and isinstance(kw.value.value, str):
                return kw.value.value
        if len(node.args) > 1 and isinstance(
            node.args[1], ast.Constant
        ) and isinstance(node.args[1].value, str):
            return node.args[1].value
        return None

    @staticmethod
    def _declared_axes(index: PackageIndex) -> set[str] | None:
        """String axis names declared by the mesh module: ``*_AXIS``
        constants plus literals in ``Mesh(..., (axes,))`` tuples.
        None when no mesh module is in scope (subtree scans)."""
        sf = index.by_rel("parallel/mesh.py") or index.by_rel("mesh.py")
        if sf is None or sf.tree is None:
            return None
        axes: set[str] = set()
        consts: dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant
            ) and isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        consts[tgt.id] = node.value.value
                        if tgt.id.endswith("_AXIS"):
                            axes.add(node.value.value)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name and name.rsplit(".", 1)[-1] == "Mesh":
                    for arg in node.args[1:]:
                        if isinstance(arg, (ast.Tuple, ast.List)):
                            for el in arg.elts:
                                if isinstance(el, ast.Constant) and (
                                    isinstance(el.value, str)
                                ):
                                    axes.add(el.value)
                                elif isinstance(el, ast.Name) and (
                                    el.id in consts
                                ):
                                    axes.add(consts[el.id])
        return axes or None


# -- XF013 -----------------------------------------------------------------


def _same_ref(a: ast.AST, b: ast.AST) -> bool:
    """Both plain Name or self-attribute chains with equal spelling."""
    da, db = dotted_name(a), dotted_name(b)
    return da is not None and da == db


class DonationSafety(Rule):
    id = "XF013"
    title = "donated buffer read after the donating call"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        mem = memory_context(index)
        ctx = get_context(index)
        donating = [b for b in mem.bindings if b.donate]
        if not donating:
            return
        for fn in ctx.fns:
            yield from self._check_fn(fn, donating)

    def _check_fn(self, fn, donating) -> Iterator[Finding]:
        # a donating call nested in an Assign is yielded TWICE by the
        # walk (as the Assign's value and as a bare Call) — claim the
        # Assign association first so the rebind idiom stays exempt
        assigns: dict[int, ast.Assign] = {}
        for node in walk_scoped(fn.node):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                assigns[id(node.value)] = node
        calls: list[tuple[ast.Call, tuple[int, ...], ast.AST | None]] = []
        for node in walk_scoped(fn.node):
            if not isinstance(node, ast.Call):
                continue
            spec = self._binding_for(fn, node, donating)
            if spec is not None:
                calls.append((node, spec, assigns.get(id(node))))
        for call, donate, assign in calls:
            for argnum in donate:
                if argnum >= len(call.args):
                    continue
                arg = call.args[argnum]
                if dotted_name(arg) is None:
                    continue
                if assign is not None and any(
                    self._target_rebinds(t, arg) for t in assign.targets
                ):
                    continue  # `state = self.train(state, ...)` idiom
                read = self._read_after(fn, call, arg)
                if read is not None:
                    yield self.finding(
                        fn.sf, read,
                        f"{dotted_name(arg)} is donated "
                        f"(donate_argnums={argnum}) to the jitted call "
                        f"at line {call.lineno} and read afterwards — "
                        "a donated buffer is dead after dispatch "
                        "(garbage on TPU); rebind the result over it "
                        "(`state = step.train(state, ...)`) or drop "
                        "donation (XF013)",
                    )
                    break

    @staticmethod
    def _binding_for(fn, call: ast.Call, donating):
        func = call.func
        for b in donating:
            # class-bound jits (self.train = jax.jit(...)) are invoked
            # through arbitrary receivers at the real call sites
            # (step.train(...), self.step.train(...)) — match by
            # attribute NAME package-wide, the same fuzzy over-
            # approximation PR 6 uses for thread targets: a donated
            # buffer is rare and explicit, so a false match is a
            # pragma, a missed one is garbage reads on TPU
            if (
                isinstance(func, ast.Attribute)
                and b.bind_cls is not None
                and func.attr == b.bind_name
            ):
                return b.donate
            if (
                isinstance(func, ast.Name)
                and func.id == b.bind_name
                and b.bind_cls is None
                and fn.sf.rel == b.sf.rel
            ):
                return b.donate
        return None

    @staticmethod
    def _target_rebinds(target: ast.AST, arg: ast.AST) -> bool:
        if isinstance(target, (ast.Tuple, ast.List)):
            return any(
                _same_ref(el, arg) for el in target.elts
            )
        return _same_ref(target, arg)

    @staticmethod
    def _read_after(fn, call: ast.Call, arg: ast.AST) -> ast.AST | None:
        call_end = getattr(call, "end_lineno", call.lineno)
        for node in walk_scoped(fn.node):
            if getattr(node, "lineno", 0) <= call_end:
                continue
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ) and _same_ref(node, arg):
                return node
        return None


# -- XF014 -----------------------------------------------------------------


def find_budget(index: PackageIndex) -> str | None:
    """memory-budget.json next to (or one level above) a scan root —
    repo layout: roots=[REPO/xflow_tpu], budget at REPO/."""
    for root in index.roots:
        for base in (root, os.path.dirname(root)):
            cand = os.path.join(base, DEFAULT_BUDGET)
            if os.path.exists(cand):
                return cand
    return None


def load_budget(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    for field in ("geometry", "budgets"):
        if field not in doc:
            raise ValueError(f"{path}: budget file missing {field!r}")
    geo = doc["geometry"]
    if "families" not in geo:
        raise ValueError(f"{path}: geometry missing 'families'")
    return doc


def estimate_transients(
    index: PackageIndex, budget_doc: dict
) -> dict[str, dict[str, dict]]:
    """{jit_key: {family: {"bytes": int, "sites": [...], "unsized": n}}}
    — the per-jit peak-transient estimate at the budget's geometry: a
    static upper bound summing every transient the flow sized across
    all branches of the trace (dense/sparse/hot paths included; an
    estimate is config-independent by design — the budget gates the
    worst reachable path)."""
    mem = memory_context(index)
    geo = budget_doc["geometry"]
    base_env = {
        k: int(v) for k, v in geo.items()
        if k != "families" and isinstance(v, (int, float))
    }
    out: dict[str, dict[str, dict]] = {}
    for key, transients in sorted(mem.flows.items()):
        per_family: dict[str, dict] = {}
        for family, d in sorted(geo["families"].items()):
            env = dict(base_env)
            env["D"] = int(d)
            total = 0
            sites = []
            unsized = 0
            for t in transients:
                nb = t.nbytes(env)
                if nb is None:
                    unsized += 1
                    continue
                total += nb
                sites.append(
                    {
                        "path": t.sf.rel,
                        "line": t.line,
                        "shape": shape_str(t.shape),
                        "kind": t.kind,
                        "bytes": nb,
                    }
                )
            sites.sort(key=lambda s: -s["bytes"])
            per_family[family] = {
                "bytes": total,
                "sites": sites,
                "unsized": unsized,
            }
        out[key] = per_family
    return out


class TransientBudget(Rule):
    id = "XF014"
    title = "per-jit transient-HBM estimate vs memory-budget.json"

    def run(self, index: PackageIndex) -> Iterator[Finding]:
        path = find_budget(index)
        if path is None:
            return  # no budget in scope (subtree/fixture scan);
            # scripts/check_memory.py requires the committed file
        try:
            doc = load_budget(path)
        except (ValueError, json.JSONDecodeError) as e:
            yield Finding(
                rule=self.id, path=DEFAULT_BUDGET, line=0,
                message=f"unreadable budget file: {e}",
            )
            return
        estimates = estimate_transients(index, doc)
        budgets: dict[str, dict] = doc["budgets"]
        mem = memory_context(index)
        lines = {
            b.key: (b.sf.rel, getattr(b.node, "lineno", 0))
            for b in mem.bindings
            if b.impl is not None
        }
        for key, per_family in sorted(estimates.items()):
            rel, lineno = lines.get(key, (DEFAULT_BUDGET, 0))
            entry = budgets.get(key)
            if entry is None:
                yield Finding(
                    rule=self.id, path=rel, line=lineno,
                    message=(
                        f"jit entry {key} has no {DEFAULT_BUDGET} entry "
                        "— every jitted function needs a committed "
                        "per-family transient budget (run scripts/"
                        "check_memory.py --write-budget and review the "
                        "numbers; docs/ANALYSIS.md XF014)"
                    ),
                )
                continue
            for family, est in sorted(per_family.items()):
                allowed = entry.get(family)
                if allowed is None:
                    yield Finding(
                        rule=self.id, path=rel, line=lineno,
                        message=(
                            f"jit entry {key} has no budget for model "
                            f"family {family!r} (estimate "
                            f"{est['bytes']} B at the north-star "
                            "geometry)"
                        ),
                    )
                elif est["bytes"] > int(allowed):
                    top = est["sites"][0] if est["sites"] else None
                    where = (
                        f"; largest: {top['shape']} {top['kind']} at "
                        f"{top['path']}:{top['line']}"
                        if top
                        else ""
                    )
                    yield Finding(
                        rule=self.id, path=rel, line=lineno,
                        message=(
                            f"jit entry {key} transient estimate "
                            f"{est['bytes']} B exceeds the committed "
                            f"budget {int(allowed)} B for family "
                            f"{family!r} at T=2^28{where} — route the "
                            "new transient through the touched-rows "
                            "path or deliberately raise the budget "
                            "(docs/ANALYSIS.md XF014 policy)"
                        ),
                    )
            # stale families: a numeric budget line for a family the
            # geometry no longer declares is dead weight that would
            # silently re-arm if the family name ever returns
            for family in sorted(entry):
                if family in per_family or not isinstance(
                    entry[family], (int, float)
                ):
                    continue  # live family, or a comment field
                yield Finding(
                    rule=self.id, path=DEFAULT_BUDGET, line=0,
                    message=(
                        f"stale budget family {family!r} under {key} "
                        "matches no geometry family — delete it"
                    ),
                )
        # stale entries: a budget line matching no live jit silently
        # grandfathers a future regression under the same key
        for key in sorted(budgets):
            if key not in estimates:
                yield Finding(
                    rule=self.id, path=DEFAULT_BUDGET, line=0,
                    message=(
                        f"stale budget entry {key} matches no jit "
                        "entry in the scanned tree — delete it"
                    ),
                )


__all__ = [
    "DEFAULT_BUDGET",
    "NARROW_HELPER",
    "DonationSafety",
    "DtypeDiscipline",
    "FullTableTransient",
    "ShardingCoverage",
    "TransientBudget",
    "estimate_transients",
    "find_budget",
    "load_budget",
    "memory_context",
    "seed_param",
    "seed_self_attr",
]
