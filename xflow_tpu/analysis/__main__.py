"""CLI: ``python -m xflow_tpu.analysis [paths...]``.

Exit codes: 0 — clean (or every finding grandfathered/pragma'd),
1 — new findings, 2 — usage error.

Examples:

    python -m xflow_tpu.analysis xflow_tpu/
    python -m xflow_tpu.analysis xflow_tpu/ --format json
    python -m xflow_tpu.analysis xflow_tpu/serve --select XF003
    python -m xflow_tpu.analysis xflow_tpu/ --write-baseline
"""

from __future__ import annotations

import argparse
import os
import sys

from xflow_tpu.analysis.baseline import (
    DEFAULT_BASELINE,
    load_baseline,
    split_baselined,
    write_baseline,
)
from xflow_tpu.analysis.core import all_rules, run_analysis
from xflow_tpu.analysis.report import render_json, render_text


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m xflow_tpu.analysis",
        description=(
            "JAX-aware static analysis enforcing xflow-tpu's "
            "performance and thread-safety invariants (docs/ANALYSIS.md)"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["xflow_tpu"],
        help="files or directories to scan (default: xflow_tpu)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help=(
            f"baseline file (default: ./{DEFAULT_BASELINE} when it "
            "exists)"
        ),
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="record the current findings as the new baseline and exit 0",
    )
    parser.add_argument(
        "--select",
        default=None,
        help="comma-separated rule ids to run (e.g. XF001,XF003)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.title}")
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select
        else None
    )
    try:
        findings, pragma_suppressed = run_analysis(args.paths, select=select)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        # carry hand-written justification fields across regeneration
        write_baseline(out, findings, previous=load_baseline(out))
        print(f"wrote {len(findings)} finding(s) to {out}")
        return 0

    entries = load_baseline(baseline_path)
    new, grandfathered, stale = split_baselined(findings, entries)
    render = render_json if args.format == "json" else render_text
    print(render(new, grandfathered, pragma_suppressed, stale))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
