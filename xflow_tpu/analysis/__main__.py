"""CLI: ``python -m xflow_tpu.analysis [paths...]``.

Exit codes: 0 — clean (or every finding grandfathered/pragma'd),
1 — new findings, 2 — usage error.

Examples:

    python -m xflow_tpu.analysis xflow_tpu/
    python -m xflow_tpu.analysis xflow_tpu/ --format json
    python -m xflow_tpu.analysis xflow_tpu/serve --select XF003
    python -m xflow_tpu.analysis xflow_tpu/ --write-baseline
    python -m xflow_tpu.analysis xflow_tpu/ --changed-only   # pre-commit
"""

from __future__ import annotations

import argparse
import os
import sys

from xflow_tpu.analysis.baseline import (
    DEFAULT_BASELINE,
    load_baseline,
    split_baselined,
    write_baseline,
)
from xflow_tpu.analysis.core import all_rules, run_analysis
from xflow_tpu.analysis.report import render_json, render_text


def _git_changed_files() -> set[str] | None:
    """Absolute paths of files changed vs HEAD (staged + unstaged)
    plus untracked files, or None when not in a usable git work tree.
    Runs git in the CURRENT directory — --changed-only is a pre-commit
    convenience, invoked from the repo being committed."""
    import subprocess

    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True,
            text=True,
        )
    except OSError:
        return None
    if top.returncode != 0:
        return None
    root = top.stdout.strip()
    changed: set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        # run from the repo ROOT: ls-files prints paths relative to
        # (and limited to) its cwd, so invoking the CLI from a subdir
        # would otherwise mis-resolve — and silently drop — untracked
        # files when joined against the root
        proc = subprocess.run(
            cmd, capture_output=True, text=True, cwd=root
        )
        if proc.returncode != 0:
            return None  # e.g. a repo with no HEAD yet
        changed.update(
            os.path.abspath(os.path.join(root, line))
            for line in proc.stdout.splitlines()
            if line.strip()
        )
    return changed


def _abspath_of(rel: str, paths: list[str]) -> str:
    """Resolve a scan-relative finding/baseline path against the scan
    roots."""
    for p in paths:
        p = os.path.abspath(p)
        base = p if os.path.isdir(p) else os.path.dirname(p)
        cand = os.path.join(base, rel)
        if os.path.exists(cand):
            return os.path.abspath(cand)
    return os.path.abspath(rel)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m xflow_tpu.analysis",
        description=(
            "JAX-aware static analysis enforcing xflow-tpu's "
            "performance and thread-safety invariants (docs/ANALYSIS.md)"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["xflow_tpu"],
        help="files or directories to scan (default: xflow_tpu)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help=(
            f"baseline file (default: ./{DEFAULT_BASELINE} when it "
            "exists)"
        ),
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="record the current findings as the new baseline and exit 0",
    )
    parser.add_argument(
        "--select",
        default=None,
        help="comma-separated rule ids to run (e.g. XF001,XF003)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help=(
            "report findings only for files changed vs git HEAD "
            "(staged, unstaged, and untracked) — the fast pre-commit "
            "mode.  The WHOLE tree is still scanned (cross-file rules "
            "and the concurrency context need it); only the report is "
            "scoped."
        ),
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.title}")
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select
        else None
    )
    try:
        findings, pragma_suppressed = run_analysis(args.paths, select=select)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    changed: set[str] | None = None
    if args.changed_only:
        if args.write_baseline:
            print(
                "error: --changed-only cannot be combined with "
                "--write-baseline (regenerating the baseline needs the "
                "FULL finding set — a scoped write would silently drop "
                "every entry for unchanged files)",
                file=sys.stderr,
            )
            return 2
        changed = _git_changed_files()
        if changed is None:
            print(
                "error: --changed-only requires a git work tree",
                file=sys.stderr,
            )
            return 2
        findings = [
            f for f in findings
            if _abspath_of(f.path, args.paths) in changed
        ]
        pragma_suppressed = [
            f
            for f in pragma_suppressed
            if _abspath_of(f.path, args.paths) in changed
        ]

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        # carry hand-written justification fields across regeneration
        write_baseline(out, findings, previous=load_baseline(out))
        print(f"wrote {len(findings)} finding(s) to {out}")
        return 0

    entries = load_baseline(baseline_path)
    new, grandfathered, stale = split_baselined(findings, entries)
    if changed is not None:
        # scoped run: an entry for an UNCHANGED file has no findings to
        # match only because they were filtered out above, not because
        # it was fixed — staleness can only be judged for changed files
        stale = [
            e
            for e in stale
            if _abspath_of(e["path"], args.paths) in changed
        ]
    render = render_json if args.format == "json" else render_text
    print(render(new, grandfathered, pragma_suppressed, stale))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
