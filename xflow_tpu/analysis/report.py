"""Finding reporters: human text and machine JSON (--format json is
the contract future dashboards consume — stable keys, no prose-only
information)."""

from __future__ import annotations

import json
from typing import Any

from xflow_tpu.analysis.core import Finding


def render_text(
    new: list[Finding],
    grandfathered: list[Finding],
    pragma_suppressed: list[Finding],
    stale_baseline: list[dict],
) -> str:
    lines: list[str] = []
    for f in new:
        lines.append(f"{f.path}:{f.line}: {f.rule} {f.message}")
    if grandfathered:
        lines.append(
            f"note: {len(grandfathered)} finding(s) grandfathered by "
            "the baseline"
        )
    if pragma_suppressed:
        lines.append(
            f"note: {len(pragma_suppressed)} finding(s) suppressed by "
            "xf: ignore pragmas"
        )
    for e in stale_baseline:
        lines.append(
            f"note: stale baseline entry no longer matches anything: "
            f"{e['rule']} {e['path']}: {e['message'][:60]}... — delete it"
        )
    if new:
        lines.append(f"FAIL: {len(new)} new finding(s)")
    else:
        lines.append("OK: no new findings")
    return "\n".join(lines)


def render_json(
    new: list[Finding],
    grandfathered: list[Finding],
    pragma_suppressed: list[Finding],
    stale_baseline: list[dict],
) -> str:
    by_rule: dict[str, int] = {}
    for f in new:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    doc: dict[str, Any] = {
        "ok": not new,
        "counts": {
            "new": len(new),
            "grandfathered": len(grandfathered),
            "pragma_suppressed": len(pragma_suppressed),
            "stale_baseline": len(stale_baseline),
            "by_rule": by_rule,
        },
        "findings": [f.to_dict() for f in new],
        "grandfathered": [f.to_dict() for f in grandfathered],
        "stale_baseline": stale_baseline,
    }
    return json.dumps(doc, indent=2, sort_keys=True)
