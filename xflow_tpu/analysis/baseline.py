"""Committed baseline of grandfathered findings.

The baseline lets the analyzer gate CI from day one without forcing a
big-bang cleanup: known findings are recorded (rule, path, message —
no line numbers, so unrelated edits don't invalidate entries) and
subtracted from the failure set.  Policy (docs/ANALYSIS.md): the
shipped baseline stays empty or near-empty, every entry carries a
justification in the file itself, and entries only ever get REMOVED —
new findings must be fixed or pragma'd with an inline justification.

Regenerate after a deliberate grandfathering decision with:

    python -m xflow_tpu.analysis xflow_tpu/ --write-baseline
"""

from __future__ import annotations

import json
import os

from xflow_tpu.analysis.core import Finding

DEFAULT_BASELINE = "analysis-baseline.json"


def load_baseline(path: str | None) -> list[dict]:
    """Baseline entries ([] when the file doesn't exist)."""
    if path is None or not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("findings", [])
    for e in entries:
        for field in ("rule", "path", "message"):
            if field not in e:
                raise ValueError(
                    f"{path}: baseline entry missing {field!r}: {e}"
                )
    return entries


def write_baseline(
    path: str,
    findings: list[Finding],
    previous: list[dict] | None = None,
) -> None:
    """Record ``findings`` as the baseline.  Pass the previously loaded
    entries as ``previous`` so hand-written fields (``justification``)
    survive regeneration for findings that still match."""
    carry = {
        (e["rule"], e["path"], e["message"]): {
            k: v
            for k, v in e.items()
            if k not in ("rule", "path", "message", "line_at_capture")
        }
        for e in previous or []
    }
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
            # not used for matching; aids the human reviewing the file
            "line_at_capture": f.line,
            **carry.get(f.key(), {}),
        }
        for f in findings
    ]
    with open(path, "w") as f:
        json.dump(
            {
                "comment": (
                    "Grandfathered xflow_tpu.analysis findings. Keep "
                    "this empty or near-empty; justify every entry "
                    "with a 'justification' field. Matching ignores "
                    "line numbers (rule+path+message)."
                ),
                "findings": entries,
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")


def split_baselined(
    findings: list[Finding], entries: list[dict]
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """(new, grandfathered, stale_entries): ``new`` fails the run,
    ``grandfathered`` matched the baseline, ``stale_entries`` matched
    nothing (fixed findings whose entries should now be deleted)."""
    keys = {(e["rule"], e["path"], e["message"]) for e in entries}
    new = [f for f in findings if f.key() not in keys]
    grandfathered = [f for f in findings if f.key() in keys]
    live = {f.key() for f in findings}
    stale = [
        e
        for e in entries
        if (e["rule"], e["path"], e["message"]) not in live
    ]
    return new, grandfathered, stale
