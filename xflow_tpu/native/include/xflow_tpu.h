/* C ABI for embedding xflow-tpu (see native/src/c_api.cc).
 *
 * The live counterpart of the reference's intended-but-dead C API
 * (c_api.h:26-41).  Link against libxflow_tpu.so; ensure the xflow_tpu
 * package is importable by the embedded interpreter (PYTHONPATH).
 *
 * Minimal use:
 *   XFHandle h = XFCreate("data/train", "data/test",
 *                         "{\"model\": \"lr\", \"epochs\": 5}");
 *   if (!h) { fprintf(stderr, "%s\n", XFLastError()); return 1; }
 *   XFStartTrain(h);
 *   double ll, auc;
 *   XFEvaluate(h, &ll, &auc);
 *   XFDestroy(h);
 */
#ifndef XFLOW_TPU_C_API_H_
#define XFLOW_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef void* XFHandle;

/* config_json: JSON object of xflow_tpu.config.Config fields, or NULL. */
XFHandle XFCreate(const char* train_path, const char* test_path,
                  const char* config_json);
int XFStartTrain(XFHandle h);
int XFEvaluate(XFHandle h, double* logloss, double* auc);
void XFDestroy(XFHandle h);
const char* XFLastError(void);

/* -- serving (xflow_tpu/serve; docs/SERVING.md) --------------------------
 *
 * The lean scoring path: export a trained model to an artifact dir,
 * then score through a PredictEngine — no Trainer, loader, or
 * optimizer state in the serving process, and batch shapes snap onto
 * precompiled buckets so concurrent scoring never recompiles.
 *
 *   XFExportArtifact(h, "artifacts/v1");       // training side
 *   XFHandle e = XFEngineCreate("artifacts/v1");
 *   double pctr;
 *   XFEngineScore(e, "0\t1:42:1 2:77:1", &pctr);
 *   XFDestroy(e);                              // engines share XFDestroy
 */
int XFExportArtifact(XFHandle h, const char* directory);
XFHandle XFEngineCreate(const char* artifact_dir);
int XFEngineScore(XFHandle engine, const char* libffm_line, double* pctr);

#ifdef __cplusplus
}
#endif

#endif /* XFLOW_TPU_C_API_H_ */
