/* C ABI for embedding xflow-tpu (see native/src/c_api.cc).
 *
 * The live counterpart of the reference's intended-but-dead C API
 * (c_api.h:26-41).  Link against libxflow_tpu.so; ensure the xflow_tpu
 * package is importable by the embedded interpreter (PYTHONPATH).
 *
 * Minimal use:
 *   XFHandle h = XFCreate("data/train", "data/test",
 *                         "{\"model\": \"lr\", \"epochs\": 5}");
 *   if (!h) { fprintf(stderr, "%s\n", XFLastError()); return 1; }
 *   XFStartTrain(h);
 *   double ll, auc;
 *   XFEvaluate(h, &ll, &auc);
 *   XFDestroy(h);
 */
#ifndef XFLOW_TPU_C_API_H_
#define XFLOW_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef void* XFHandle;

/* config_json: JSON object of xflow_tpu.config.Config fields, or NULL. */
XFHandle XFCreate(const char* train_path, const char* test_path,
                  const char* config_json);
int XFStartTrain(XFHandle h);
int XFEvaluate(XFHandle h, double* logloss, double* auc);
void XFDestroy(XFHandle h);
const char* XFLastError(void);

#ifdef __cplusplus
}
#endif

#endif /* XFLOW_TPU_C_API_H_ */
