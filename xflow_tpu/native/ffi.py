"""ctypes bindings for the native parser (no pybind11 in this image —
plain C ABI + ctypes, the same "embed as a library" shape the
reference's C API intended, c_api.h:26-41).

The shared library is built on demand with g++ (see build.py) and
cached next to the sources.  Everything degrades gracefully: if no
toolchain is available, ``available()`` is False and callers fall back
to the pure-Python parser.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from xflow_tpu.io.batch import ParsedBlock

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def load_library() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            from xflow_tpu.native.build import build_if_needed

            path = build_if_needed()
            lib = ctypes.CDLL(str(path))
        except Exception:
            _load_failed = True
            return None
        lib.xf_murmur64.restype = ctypes.c_uint64
        lib.xf_murmur64.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_uint64,
        ]
        lib.xf_parse_block.restype = ctypes.c_int64
        lib.xf_parse_block.argtypes = [
            ctypes.c_char_p,  # data
            ctypes.c_int64,  # len
            ctypes.c_int64,  # table_size
            ctypes.c_int,  # hash_mode
            ctypes.c_uint64,  # seed
            ctypes.POINTER(ctypes.c_float),  # labels
            ctypes.c_int64,  # max_rows
            ctypes.POINTER(ctypes.c_int64),  # row_ptr
            ctypes.POINTER(ctypes.c_int64),  # keys
            ctypes.POINTER(ctypes.c_int32),  # slots
            ctypes.POINTER(ctypes.c_float),  # vals
            ctypes.c_int64,  # max_nnz
            ctypes.POINTER(ctypes.c_int64),  # out_nnz
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load_library() is not None


def native_murmur64(data: bytes, seed: int = 0) -> int:
    lib = load_library()
    assert lib is not None, "native library unavailable"
    return int(lib.xf_murmur64(data, len(data), seed))


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def native_parse_block(
    data: bytes,
    table_size: int,
    hash_mode: bool = True,
    hash_seed: int = 0,
) -> ParsedBlock:
    """Drop-in replacement for io.libffm.parse_block (parity enforced by
    tests/test_native.py)."""
    lib = load_library()
    assert lib is not None, "native library unavailable"
    # capacity bounds: every sample has one line; every feature token has
    # exactly 2 of the block's ':' bytes
    max_rows = data.count(b"\n") + 1
    max_nnz = data.count(b":") // 2 + 1
    labels = np.empty(max_rows, dtype=np.float32)
    row_ptr = np.empty(max_rows + 1, dtype=np.int64)
    keys = np.empty(max_nnz, dtype=np.int64)
    slots = np.empty(max_nnz, dtype=np.int32)
    vals = np.empty(max_nnz, dtype=np.float32)
    out_nnz = np.zeros(1, dtype=np.int64)
    n_rows = lib.xf_parse_block(
        data,
        len(data),
        table_size,
        1 if hash_mode else 0,
        hash_seed,
        _ptr(labels, ctypes.c_float),
        max_rows,
        _ptr(row_ptr, ctypes.c_int64),
        _ptr(keys, ctypes.c_int64),
        _ptr(slots, ctypes.c_int32),
        _ptr(vals, ctypes.c_float),
        max_nnz,
        _ptr(out_nnz, ctypes.c_int64),
    )
    if n_rows < 0:
        raise RuntimeError("native parser capacity overflow (bound bug)")
    nnz = int(out_nnz[0])
    return ParsedBlock(
        labels=labels[:n_rows].copy(),
        row_ptr=row_ptr[: n_rows + 1].copy(),
        keys=keys[:nnz].copy(),
        slots=slots[:nnz].copy(),
        vals=vals[:nnz].copy(),
    )
