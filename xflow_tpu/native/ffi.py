"""ctypes bindings for the native parser (no pybind11 in this image —
plain C ABI + ctypes, the same "embed as a library" shape the
reference's C API intended, c_api.h:26-41).

The shared library is built on demand with g++ (see build.py) and
cached next to the sources.  Everything degrades gracefully: if no
toolchain is available, ``available()`` is False and callers fall back
to the pure-Python parser.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from xflow_tpu.io.batch import ParsedBlock

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False
_has_dict_encode = False


def load_library() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            from xflow_tpu.native.build import build_if_needed

            path = build_if_needed()
            lib = ctypes.CDLL(str(path))
            _bind(lib)
        except Exception:
            _load_failed = True
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    """Declare signatures; raises (caught by load_library) if a symbol
    is missing — e.g. a stale cached .so from an older source version
    whose mtime check passed (equal-mtime extraction)."""
    lib.xf_murmur64.restype = ctypes.c_uint64
    lib.xf_murmur64.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_uint64,
    ]
    lib.xf_parse_block.restype = ctypes.c_int64
    lib.xf_parse_block.argtypes = [
        ctypes.c_char_p,  # data
        ctypes.c_int64,  # len
        ctypes.c_int64,  # table_size
        ctypes.c_int,  # hash_mode
        ctypes.c_uint64,  # seed
        ctypes.POINTER(ctypes.c_float),  # labels
        ctypes.c_int64,  # max_rows
        ctypes.POINTER(ctypes.c_int64),  # row_ptr
        ctypes.POINTER(ctypes.c_int64),  # keys
        ctypes.POINTER(ctypes.c_int32),  # slots
        ctypes.POINTER(ctypes.c_float),  # vals
        ctypes.c_int64,  # max_nnz
        ctypes.POINTER(ctypes.c_int64),  # out_nnz
    ]
    f32p = ctypes.POINTER(ctypes.c_float)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    # Optional (added after the first shipped .so): a cached library
    # missing it must still serve the parser/pack fast paths, so bind
    # it best-effort instead of letting a missing symbol fail _bind.
    global _has_dict_encode
    try:
        lib.xf_dict_encode.restype = ctypes.c_int64
        lib.xf_dict_encode.argtypes = [
            i64p,  # keys
            ctypes.c_int64,  # n
            ctypes.c_int64,  # dict_cap
            i64p,  # uniq_out
            ctypes.POINTER(ctypes.c_uint32),  # code_out
        ]
        _has_dict_encode = True
    except AttributeError:
        _has_dict_encode = False
    lib.xf_pack_batch.restype = ctypes.c_int64
    lib.xf_pack_batch.argtypes = [
        i64p,  # row_ptr
        f32p,  # labels_in
        i64p,  # keys_in
        i32p,  # slots_in
        f32p,  # vals_in
        ctypes.c_int64,  # start
        ctypes.c_int64,  # end
        ctypes.c_int64,  # batch_size
        i32p,  # remap (nullable)
        ctypes.c_int64,  # hot_size
        ctypes.c_int64,  # hot_nnz
        ctypes.c_int64,  # cold_nnz
        i32p, i32p, f32p, f32p,  # keys, slots, vals, mask
        i32p, i32p, f32p, f32p,  # hot_keys/slots/vals/mask (nullable)
        f32p,  # labels
        f32p,  # weights
    ]


def available() -> bool:
    return load_library() is not None


def has_dict_encode() -> bool:
    return load_library() is not None and _has_dict_encode


def native_dict_encode(
    keys: np.ndarray, dict_cap: int
) -> tuple[np.ndarray, np.ndarray]:
    """Drop-in replacement for io.compact.dedup_select's numpy path
    (same selected SET by construction; dictionary order differs —
    parity enforced by tests/test_compact.py)."""
    lib = load_library()
    assert lib is not None and _has_dict_encode, "xf_dict_encode unavailable"
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    n = len(keys)
    uniq = np.empty(dict_cap, np.int64)
    codes = np.empty(n, np.uint32)
    nd = lib.xf_dict_encode(
        _ptr(keys, ctypes.c_int64),
        n,
        dict_cap,
        _ptr(uniq, ctypes.c_int64),
        _ptr(codes, ctypes.c_uint32),
    )
    if nd < 0:
        raise MemoryError("xf_dict_encode: allocation failed")
    return uniq[:nd].copy(), codes


def native_murmur64(data: bytes, seed: int = 0) -> int:
    lib = load_library()
    assert lib is not None, "native library unavailable"
    return int(lib.xf_murmur64(data, len(data), seed))


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def native_parse_block(
    data: bytes,
    table_size: int,
    hash_mode: bool = True,
    hash_seed: int = 0,
) -> ParsedBlock:
    """Drop-in replacement for io.libffm.parse_block (parity enforced by
    tests/test_native.py)."""
    lib = load_library()
    assert lib is not None, "native library unavailable"
    # Keys must survive the downstream int32 batch cast (xf_pack_batch);
    # Config guards table_size_log2 <= 30 on the CLI path, but this
    # entry point is callable directly (round-2 advisor finding).
    # table_size == 0 = no reduction (full 64-bit keys for the binary
    # block cache / collision accounting — never fed to pack directly).
    if table_size != 0 and not 0 < table_size <= (1 << 31):
        raise ValueError(
            f"table_size {table_size} out of range (0, 2^31] — parsed "
            "keys must fit int32 batch arrays (0 = keep full keys)"
        )
    # capacity bounds: every sample has one line; every feature token has
    # exactly 2 of the block's ':' bytes
    max_rows = data.count(b"\n") + 1
    max_nnz = data.count(b":") // 2 + 1
    labels = np.empty(max_rows, dtype=np.float32)
    row_ptr = np.empty(max_rows + 1, dtype=np.int64)
    keys = np.empty(max_nnz, dtype=np.int64)
    slots = np.empty(max_nnz, dtype=np.int32)
    vals = np.empty(max_nnz, dtype=np.float32)
    out_nnz = np.zeros(1, dtype=np.int64)
    n_rows = lib.xf_parse_block(
        data,
        len(data),
        table_size,
        1 if hash_mode else 0,
        hash_seed,
        _ptr(labels, ctypes.c_float),
        max_rows,
        _ptr(row_ptr, ctypes.c_int64),
        _ptr(keys, ctypes.c_int64),
        _ptr(slots, ctypes.c_int32),
        _ptr(vals, ctypes.c_float),
        max_nnz,
        _ptr(out_nnz, ctypes.c_int64),
    )
    if n_rows < 0:
        raise RuntimeError("native parser capacity overflow (bound bug)")
    nnz = int(out_nnz[0])
    return ParsedBlock(
        labels=labels[:n_rows].copy(),
        row_ptr=row_ptr[: n_rows + 1].copy(),
        keys=keys[:nnz].copy(),
        slots=slots[:nnz].copy(),
        vals=vals[:nnz].copy(),
    )


def native_pack_batch(
    block: ParsedBlock,
    start: int,
    end: int,
    batch_size: int,
    max_nnz: int,
    hot_size: int = 0,
    hot_nnz: int = 0,
    remap: np.ndarray | None = None,
):
    """Drop-in replacement for io.batch.pack_batch with the frequency
    remap folded in (parity enforced by tests/test_native.py).  ``block``
    must hold RAW (un-remapped) keys when ``remap`` is given."""
    from xflow_tpu.io.batch import Batch

    lib = load_library()
    assert lib is not None, "native library unavailable"
    n = end - start
    assert 0 < n <= batch_size
    kh = hot_nnz if hot_size else 0
    row_ptr = np.ascontiguousarray(block.row_ptr, dtype=np.int64)
    labels_in = np.ascontiguousarray(block.labels, dtype=np.float32)
    keys_in = np.ascontiguousarray(block.keys, dtype=np.int64)
    slots_in = np.ascontiguousarray(block.slots, dtype=np.int32)
    vals_in = np.ascontiguousarray(block.vals, dtype=np.float32)
    if remap is not None:
        remap = np.ascontiguousarray(remap, dtype=np.int32)

    keys = np.empty((batch_size, max_nnz), np.int32)
    slots = np.empty((batch_size, max_nnz), np.int32)
    vals = np.empty((batch_size, max_nnz), np.float32)
    mask = np.empty((batch_size, max_nnz), np.float32)
    hot_keys = np.empty((batch_size, kh), np.int32)
    hot_slots = np.empty((batch_size, kh), np.int32)
    hot_vals = np.empty((batch_size, kh), np.float32)
    hot_mask = np.empty((batch_size, kh), np.float32)
    labels = np.empty(batch_size, np.float32)
    weights = np.empty(batch_size, np.float32)
    null_i32 = ctypes.POINTER(ctypes.c_int32)()
    rc = lib.xf_pack_batch(
        _ptr(row_ptr, ctypes.c_int64),
        _ptr(labels_in, ctypes.c_float),
        _ptr(keys_in, ctypes.c_int64),
        _ptr(slots_in, ctypes.c_int32),
        _ptr(vals_in, ctypes.c_float),
        start,
        end,
        batch_size,
        _ptr(remap, ctypes.c_int32) if remap is not None else null_i32,
        hot_size if kh else 0,
        kh,
        max_nnz,
        _ptr(keys, ctypes.c_int32),
        _ptr(slots, ctypes.c_int32),
        _ptr(vals, ctypes.c_float),
        _ptr(mask, ctypes.c_float),
        _ptr(hot_keys, ctypes.c_int32),
        _ptr(hot_slots, ctypes.c_int32),
        _ptr(hot_vals, ctypes.c_float),
        _ptr(hot_mask, ctypes.c_float),
        _ptr(labels, ctypes.c_float),
        _ptr(weights, ctypes.c_float),
    )
    if rc == -2:
        raise ValueError(
            "pack_batch: a (remapped) key exceeds int32 — table_size or "
            "remap values too large for the int32 batch arrays"
        )
    if rc < 0:
        raise RuntimeError(f"native pack_batch failed (rc={rc})")
    if not kh:
        return Batch(
            keys=keys, slots=slots, vals=vals, mask=mask,
            labels=labels, weights=weights,
        )
    return Batch(
        keys=keys, slots=slots, vals=vals, mask=mask,
        labels=labels, weights=weights,
        hot_keys=hot_keys, hot_slots=hot_slots,
        hot_vals=hot_vals, hot_mask=hot_mask,
    )
