from xflow_tpu.native.ffi import (
    available,
    load_library,
    native_murmur64,
    native_pack_batch,
    native_parse_block,
)

__all__ = [
    "available",
    "load_library",
    "native_murmur64",
    "native_pack_batch",
    "native_parse_block",
]
