from xflow_tpu.native.ffi import (
    available,
    has_dict_encode,
    load_library,
    native_dict_encode,
    native_murmur64,
    native_pack_batch,
    native_parse_block,
)

__all__ = [
    "available",
    "has_dict_encode",
    "load_library",
    "native_dict_encode",
    "native_murmur64",
    "native_pack_batch",
    "native_parse_block",
]
