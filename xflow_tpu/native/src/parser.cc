// Native libffm block parser + MurmurHash64A feature hasher.
//
// TPU-native counterpart of the reference's C++ IO layer
// (src/io/load_data_from_disk.cc:103-210, the fread block loader, and
// the std::hash<string> feature hashing at :151 / io.h:53): host-side
// text parsing is the throughput bottleneck when feeding an
// accelerator from libffm text shards (SURVEY §7 hard part c), so the
// tokenize+hash hot loop lives in C++ behind a C ABI consumed via
// ctypes (no pybind11 dependency).
//
// Semantics mirror xflow_tpu/io/libffm.py::parse_block exactly —
// parity is enforced by tests/test_native.py over toy, fuzzed, and
// malformed inputs:
//   * lines split on '\n'; tokens on spaces/tabs/CR
//   * label = first token parsed as float (full consume), else line
//     skipped; binarized y > 1e-7 -> 1
//   * feature token must be fgid:fid:val with integer fgid; in hash
//     mode fid is hashed as a string (MurmurHash64A, seed given) and
//     val is DISCARDED (features binary, vals=1); in numeric mode fid
//     must parse as integer and val as float, both kept
//   * malformed tokens are skipped, not fatal
//   * keys reduced modulo table_size; table_size == 0 keeps FULL keys
//     (the 64-bit hash as two's-complement int64 / the raw fid) for the
//     binary block cache (io/binary.py) and collision accounting

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

constexpr uint64_t kMulm = 0xc6a4a7935bd1e995ULL;
constexpr int kShift = 47;

uint64_t murmur64a(const char* data, int64_t len, uint64_t seed) {
  uint64_t h = seed ^ (static_cast<uint64_t>(len) * kMulm);
  const int64_t nblocks = len / 8;
  for (int64_t i = 0; i < nblocks; ++i) {
    uint64_t k;
    std::memcpy(&k, data + i * 8, 8);
    k *= kMulm;
    k ^= k >> kShift;
    k *= kMulm;
    h ^= k;
    h *= kMulm;
  }
  const unsigned char* tail =
      reinterpret_cast<const unsigned char*>(data + nblocks * 8);
  uint64_t k = 0;
  switch (len & 7) {
    case 7: k |= static_cast<uint64_t>(tail[6]) << 48; [[fallthrough]];
    case 6: k |= static_cast<uint64_t>(tail[5]) << 40; [[fallthrough]];
    case 5: k |= static_cast<uint64_t>(tail[4]) << 32; [[fallthrough]];
    case 4: k |= static_cast<uint64_t>(tail[3]) << 24; [[fallthrough]];
    case 3: k |= static_cast<uint64_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k |= static_cast<uint64_t>(tail[1]) << 8; [[fallthrough]];
    case 1:
      k |= static_cast<uint64_t>(tail[0]);
      h ^= k;
      h *= kMulm;
  }
  h ^= h >> kShift;
  h *= kMulm;
  h ^= h >> kShift;
  return h;
}

inline bool is_space(char c) {
  // Python bytes.split() splits on these.
  return c == ' ' || c == '\t' || c == '\r' || c == '\x0b' || c == '\f';
}

// Parse [p, end) fully as a float; false if empty or trailing junk.
// Mirrors Python float(tok): leading/trailing whitespace already
// stripped by tokenization.
bool parse_float_full(const char* p, const char* end, float* out) {
  if (p == end) return false;
  // strtof accepts hex floats ("0x5") and "nan(...)"; Python float() does
  // not — reject them for parity.
  const char* q = p;
  if (*q == '+' || *q == '-') ++q;
  if (end - q >= 2 && q[0] == '0' && (q[1] == 'x' || q[1] == 'X')) return false;
  if (std::memchr(p, '(', static_cast<size_t>(end - p)) != nullptr) return false;
  // strtod needs NUL-terminated input; stack buffer for the common case,
  // heap for pathological token lengths (Python float() has no limit).
  char buf[64];
  size_t n = static_cast<size_t>(end - p);
  char* heap = nullptr;
  char* s = buf;
  if (n >= sizeof(buf)) {
    heap = static_cast<char*>(std::malloc(n + 1));
    if (heap == nullptr) return false;
    s = heap;
  }
  std::memcpy(s, p, n);
  s[n] = '\0';
  char* parse_end = nullptr;
  errno = 0;
  // Parse as double then narrow, matching the Python parser's
  // float(tok) -> float32 double rounding exactly (np.float32(float(tok))).
  double v = std::strtod(s, &parse_end);
  bool ok = (parse_end == s + n);
  if (heap != nullptr) std::free(heap);
  if (!ok) return false;
  *out = static_cast<float>(v);
  return true;
}

// Parse [p, end) fully as a base-10 integer (Python int(tok) semantics
// minus underscores: optional sign, digits only).  Values outside int64
// are rejected (the Python parser skips them too — see libffm.py's
// range guards), never silently wrapped.
bool parse_int_full(const char* p, const char* end, int64_t* out) {
  if (p == end) return false;
  bool neg = false;
  if (*p == '+' || *p == '-') {
    neg = (*p == '-');
    ++p;
    if (p == end) return false;
  }
  uint64_t v = 0;
  constexpr uint64_t kMax = 0x7fffffffffffffffULL;  // int64 max
  for (; p != end; ++p) {
    if (*p < '0' || *p > '9') return false;
    uint64_t d = static_cast<uint64_t>(*p - '0');
    if (v > (kMax - d) / 10) return false;  // would overflow int64
    v = v * 10 + d;
  }
  *out = neg ? -static_cast<int64_t>(v) : static_cast<int64_t>(v);
  return true;
}

// fgid must fit int32 (slot arrays are int32 in both parsers).
bool parse_fgid(const char* p, const char* end, int32_t* out) {
  int64_t v;
  if (!parse_int_full(p, end, &v)) return false;
  if (v < INT32_MIN || v > INT32_MAX) return false;
  *out = static_cast<int32_t>(v);
  return true;
}

}  // namespace

extern "C" {

uint64_t xf_murmur64(const char* data, int64_t len, uint64_t seed) {
  return murmur64a(data, len, seed);
}

// Parses one text block.  Outputs are caller-allocated with capacities
// max_rows / max_nnz; returns the number of parsed samples, or -1 if a
// capacity would overflow (caller should re-bound and retry).
// row_ptr has max_rows+1 slots; *out_nnz receives the total nnz.
int64_t xf_parse_block(const char* data, int64_t len, int64_t table_size,
                       int hash_mode, uint64_t seed, float* labels,
                       int64_t max_rows, int64_t* row_ptr, int64_t* keys,
                       int32_t* slots, float* vals, int64_t max_nnz,
                       int64_t* out_nnz) {
  int64_t n_rows = 0;
  int64_t nnz = 0;
  row_ptr[0] = 0;
  const char* p = data;
  const char* data_end = data + len;
  while (p < data_end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(data_end - p)));
    if (line_end == nullptr) line_end = data_end;
    const char* q = p;
    p = line_end + 1;  // advance for next iteration

    // tokenize: first token = label
    while (q < line_end && is_space(*q)) ++q;
    if (q == line_end) continue;  // blank line
    const char* tok_end = q;
    while (tok_end < line_end && !is_space(*tok_end)) ++tok_end;
    float y;
    if (!parse_float_full(q, tok_end, &y)) continue;  // bad label: skip line
    if (n_rows == max_rows) return -1;
    labels[n_rows] = (y > 1e-7f) ? 1.0f : 0.0f;

    // feature tokens
    q = tok_end;
    while (q < line_end) {
      while (q < line_end && is_space(*q)) ++q;
      if (q == line_end) break;
      const char* t_end = q;
      while (t_end < line_end && !is_space(*t_end)) ++t_end;
      // split fgid:fid:val — exactly 3 pieces
      const char* c1 = static_cast<const char*>(
          std::memchr(q, ':', static_cast<size_t>(t_end - q)));
      if (c1 != nullptr) {
        const char* c2 = static_cast<const char*>(
            std::memchr(c1 + 1, ':', static_cast<size_t>(t_end - c1 - 1)));
        if (c2 != nullptr &&
            std::memchr(c2 + 1, ':', static_cast<size_t>(t_end - c2 - 1)) ==
                nullptr) {
          int32_t fgid;
          if (parse_fgid(q, c1, &fgid)) {
            if (hash_mode) {
              if (nnz == max_nnz) return -1;
              uint64_t h = murmur64a(c1 + 1, c2 - c1 - 1, seed);
              keys[nnz] = static_cast<int64_t>(
                  table_size > 0 ? h % static_cast<uint64_t>(table_size)
                                 : h);
              slots[nnz] = fgid;
              vals[nnz] = 1.0f;  // value field discarded: binary features
              ++nnz;
            } else {
              int64_t fid;
              float val;
              if (parse_int_full(c1 + 1, c2, &fid) &&
                  parse_float_full(c2 + 1, t_end, &val) &&
                  // reject values not finite in float32 (inf/nan
                  // literals and 1e39/1e999-style overflows) — matches
                  // libffm.py's finite-in-float32 rule exactly
                  std::isfinite(val)) {
                if (nnz == max_nnz) return -1;
                int64_t k = fid;
                if (table_size > 0) {
                  k = fid % table_size;
                  if (k < 0) k += table_size;
                }
                keys[nnz] = k;
                slots[nnz] = fgid;
                vals[nnz] = val;
                ++nnz;
              }
            }
          }
        }
      }
      q = t_end;
    }
    ++n_rows;
    row_ptr[n_rows] = nnz;
  }
  *out_nnz = nnz;
  return n_rows;
}

// Packs samples [start, end) of a parsed CSR block into padded
// row-major batch arrays, folding in the optional frequency remap
// (io/freq.py) and hot/cold steering (io/batch.py::split_hot) in one
// pass.  Native counterpart of io/batch.py::pack_batch — the numpy
// version's cumsum/nonzero/fancy-index pipeline is the host bottleneck
// at large batch sizes; parity enforced by tests/test_native.py.
//
// Layout contract (matches pack_batch exactly):
//   * per sample, at most (cold_nnz + hot_nnz) leading CSR entries are
//     considered (the rest truncate, as the Python ktot cap);
//   * among those, hot entries (remapped key < hot_size) fill the hot
//     section in order up to hot_nnz; overflow spills to cold;
//   * cold entries fill up to cold_nnz, then truncate;
//   * pad feature slots are key/slot/val/mask = 0; pad samples (index
//     >= end-start) are fully zero with weight 0.
// Outputs may be uninitialized (np.empty): every slot is written.
// hot_* pointers may be null when hot_nnz == 0.  remap may be null.
//
// Returns -2 if any (remapped) key falls outside int32 — the batch
// arrays are int32, and Config's table_size_log2 <= 30 guard only
// covers the CLI path; this entry point is callable directly, so the
// narrowing cast must be checked here, not assumed.
int64_t xf_pack_batch(const int64_t* row_ptr, const float* labels_in,
                      const int64_t* keys_in, const int32_t* slots_in,
                      const float* vals_in, int64_t start, int64_t end,
                      int64_t batch_size, const int32_t* remap,
                      int64_t hot_size, int64_t hot_nnz, int64_t cold_nnz,
                      int32_t* keys, int32_t* slots, float* vals, float* mask,
                      int32_t* hot_keys, int32_t* hot_slots, float* hot_vals,
                      float* hot_mask, float* labels, float* weights) {
  const int64_t n = end - start;
  const int64_t ktot = cold_nnz + hot_nnz;
  for (int64_t i = 0; i < batch_size; ++i) {
    int32_t* krow = keys + i * cold_nnz;
    int32_t* srow = slots + i * cold_nnz;
    float* vrow = vals + i * cold_nnz;
    float* mrow = mask + i * cold_nnz;
    int64_t cold = 0;
    int64_t hot = 0;
    if (i < n) {
      labels[i] = labels_in[start + i];
      weights[i] = 1.0f;
      const int64_t lo = row_ptr[start + i];
      int64_t hi = row_ptr[start + i + 1];
      if (hi - lo > ktot) hi = lo + ktot;  // Python ktot truncation
      for (int64_t e = lo; e < hi; ++e) {
        int64_t k = keys_in[e];
        if (remap != nullptr) k = remap[k];
        if (k < 0 || k > INT32_MAX) return -2;  // would wrap in int32 cast
        if (k < hot_size && hot < hot_nnz) {
          hot_keys[i * hot_nnz + hot] = static_cast<int32_t>(k);
          hot_slots[i * hot_nnz + hot] = slots_in[e];
          hot_vals[i * hot_nnz + hot] = vals_in[e];
          hot_mask[i * hot_nnz + hot] = 1.0f;
          ++hot;
        } else if (cold < cold_nnz) {
          krow[cold] = static_cast<int32_t>(k);
          srow[cold] = slots_in[e];
          vrow[cold] = vals_in[e];
          mrow[cold] = 1.0f;
          ++cold;
        }  // else: cold capacity truncation (split_hot semantics)
      }
    } else {
      labels[i] = 0.0f;
      weights[i] = 0.0f;
    }
    // zero-fill pad slots (outputs may be np.empty)
    const size_t cpad = static_cast<size_t>(cold_nnz - cold);
    std::memset(krow + cold, 0, cpad * sizeof(int32_t));
    std::memset(srow + cold, 0, cpad * sizeof(int32_t));
    std::memset(vrow + cold, 0, cpad * sizeof(float));
    std::memset(mrow + cold, 0, cpad * sizeof(float));
    if (hot_nnz > 0) {
      const size_t hpad = static_cast<size_t>(hot_nnz - hot);
      std::memset(hot_keys + i * hot_nnz + hot, 0, hpad * sizeof(int32_t));
      std::memset(hot_slots + i * hot_nnz + hot, 0, hpad * sizeof(int32_t));
      std::memset(hot_vals + i * hot_nnz + hot, 0, hpad * sizeof(float));
      std::memset(hot_mask + i * hot_nnz + hot, 0, hpad * sizeof(float));
    }
  }
  return n;
}

// Host-side batch compaction kernel (io/compact.py::dedup_select):
// deduplicate n int64 keys into a frequency-capped dictionary.
// Emits the dictionary keys (first-touch order over a deterministic
// hash walk) to uniq_out and, per element, a u32 code — the element's
// index into the dictionary, or 0xFFFFFFFF when its key's occurrence
// count fell below the cap threshold (the smallest t with
// |{count >= t}| <= dict_cap, so the selected SET matches the numpy
// fallback exactly; only the within-dictionary order differs, which
// expansion/training are invariant to).  Returns the dictionary size,
// or -1 on allocation failure.
//
// Cost: two linear passes over an open-addressing table sized 2x the
// element count — ~15 ns/element on one host core, i.e. "free relative
// to the link" (the whole point of compacting host-side).
int64_t xf_dict_encode(const int64_t* keys, int64_t n, int64_t dict_cap,
                       int64_t* uniq_out, uint32_t* code_out) {
  if (n <= 0) return 0;
  uint64_t cap = 1;
  while (cap < static_cast<uint64_t>(n) * 2) cap <<= 1;
  const uint64_t mask = cap - 1;
  int64_t* slot_key = static_cast<int64_t*>(std::malloc(cap * sizeof(int64_t)));
  uint32_t* slot_cnt =
      static_cast<uint32_t*>(std::malloc(cap * sizeof(uint32_t)));
  uint32_t* slot_id =
      static_cast<uint32_t*>(std::malloc(cap * sizeof(uint32_t)));
  if (slot_key == nullptr || slot_cnt == nullptr || slot_id == nullptr) {
    std::free(slot_key);
    std::free(slot_cnt);
    std::free(slot_id);
    return -1;
  }
  std::memset(slot_cnt, 0, cap * sizeof(uint32_t));
  // pass 1: count occurrences per unique key
  int64_t n_unique = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = keys[i];
    uint64_t h = static_cast<uint64_t>(k) * kMulm;
    h ^= h >> kShift;
    uint64_t s = h & mask;
    while (slot_cnt[s] != 0 && slot_key[s] != k) s = (s + 1) & mask;
    if (slot_cnt[s] == 0) {
      slot_key[s] = k;
      ++n_unique;
    }
    ++slot_cnt[s];
  }
  // threshold: smallest t with |{count >= t}| <= dict_cap (counts
  // clamped into the histogram's last bucket; a key with count >
  // dict_cap is certainly selected)
  uint32_t t = 1;
  if (n_unique > dict_cap) {
    const uint32_t hist_n = static_cast<uint32_t>(dict_cap) + 2;
    uint64_t* ge = static_cast<uint64_t*>(std::calloc(hist_n, sizeof(uint64_t)));
    if (ge == nullptr) {
      std::free(slot_key);
      std::free(slot_cnt);
      std::free(slot_id);
      return -1;
    }
    for (uint64_t s = 0; s < cap; ++s) {
      if (slot_cnt[s] != 0) {
        uint32_t c = slot_cnt[s];
        if (c > hist_n - 1) c = hist_n - 1;
        ++ge[c];
      }
    }
    for (uint32_t c = hist_n - 1; c > 0; --c) ge[c - 1] += ge[c];
    while (t < hist_n - 1 && ge[t] > static_cast<uint64_t>(dict_cap)) ++t;
    std::free(ge);
  }
  // pass 2: assign dictionary ids in slot-scan order (deterministic)
  uint32_t nd = 0;
  for (uint64_t s = 0; s < cap; ++s) {
    if (slot_cnt[s] == 0) continue;
    // nd guard: unreachable below ~(dict_cap+1)^2 elements, but the
    // caller's uniq_out is sized dict_cap — never overrun it
    if (slot_cnt[s] >= t && nd < static_cast<uint32_t>(dict_cap)) {
      uniq_out[nd] = slot_key[s];
      slot_id[s] = nd++;
    } else {
      slot_id[s] = 0xFFFFFFFFu;
    }
  }
  // pass 3: code every element
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = keys[i];
    uint64_t h = static_cast<uint64_t>(k) * kMulm;
    h ^= h >> kShift;
    uint64_t s = h & mask;
    while (slot_key[s] != k || slot_cnt[s] == 0) s = (s + 1) & mask;
    code_out[i] = slot_id[s];
  }
  std::free(slot_key);
  std::free(slot_cnt);
  std::free(slot_id);
  return static_cast<int64_t>(nd);
}

}  // extern "C"
