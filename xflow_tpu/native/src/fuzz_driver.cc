// Standalone sanitizer harness for the native parser + packer.
//
// Built with -fsanitize=address,undefined by tests/test_native.py
// (test_sanitizer_fuzz) and fed the fuzz corpus; any heap overflow,
// OOB read, or UB aborts the process non-zero.  A standalone binary
// (not the .so) so no LD_PRELOAD/asan-runtime gymnastics are needed.
//
// Usage: fuzz_driver FILE...   (each file = one raw parse block)

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
int64_t xf_parse_block(const char* data, int64_t len, int64_t table_size,
                       int hash_mode, uint64_t seed, float* labels,
                       int64_t max_rows, int64_t* row_ptr, int64_t* keys,
                       int32_t* slots, float* vals, int64_t max_nnz,
                       int64_t* out_nnz);
int64_t xf_pack_batch(const int64_t* row_ptr, const float* labels_in,
                      const int64_t* keys_in, const int32_t* slots_in,
                      const float* vals_in, int64_t start, int64_t end,
                      int64_t batch_size, const int32_t* remap,
                      int64_t hot_size, int64_t hot_nnz, int64_t cold_nnz,
                      int32_t* keys, int32_t* slots, float* vals, float* mask,
                      int32_t* hot_keys, int32_t* hot_slots, float* hot_vals,
                      float* hot_mask, float* labels, float* weights);
}

namespace {

void drive(const std::string& data, int hash_mode) {
  constexpr int64_t kTable = 1 << 12;
  // capacity bounds mirror ffi.py: lines <= '\n' count + 1, features
  // have exactly 2 ':' bytes each
  int64_t max_rows = std::count(data.begin(), data.end(), '\n') + 1;
  int64_t max_nnz = std::count(data.begin(), data.end(), ':') / 2 + 1;
  std::vector<float> labels(max_rows);
  std::vector<int64_t> row_ptr(max_rows + 1);
  std::vector<int64_t> keys(max_nnz);
  std::vector<int32_t> slots(max_nnz);
  std::vector<float> vals(max_nnz);
  int64_t nnz = 0;
  int64_t n = xf_parse_block(data.data(), data.size(), kTable, hash_mode,
                             /*seed=*/7, labels.data(), max_rows,
                             row_ptr.data(), keys.data(), slots.data(),
                             vals.data(), max_nnz, &nnz);
  if (n < 0) {
    std::fprintf(stderr, "capacity overflow (bound bug)\n");
    std::exit(2);
  }
  // pack every prefix/suffix window through hot and non-hot paths
  std::vector<int32_t> remap(kTable);
  for (int64_t i = 0; i < kTable; ++i)
    remap[i] = static_cast<int32_t>(kTable - 1 - i);
  const int64_t bs = 16, cold = 5, hot_nnz = 3, hot_size = 64;
  std::vector<int32_t> bkeys(bs * cold), bslots(bs * cold);
  std::vector<float> bvals(bs * cold), bmask(bs * cold);
  std::vector<int32_t> hkeys(bs * hot_nnz), hslots(bs * hot_nnz);
  std::vector<float> hvals(bs * hot_nnz), hmask(bs * hot_nnz);
  std::vector<float> blabels(bs), bweights(bs);
  for (int64_t s = 0; s < n; s += bs) {
    int64_t e = std::min(n, s + bs);
    xf_pack_batch(row_ptr.data(), labels.data(), keys.data(), slots.data(),
                  vals.data(), s, e, bs, nullptr, 0, 0, cold, bkeys.data(),
                  bslots.data(), bvals.data(), bmask.data(), nullptr, nullptr,
                  nullptr, nullptr, blabels.data(), bweights.data());
    xf_pack_batch(row_ptr.data(), labels.data(), keys.data(), slots.data(),
                  vals.data(), s, e, bs, remap.data(), hot_size, hot_nnz,
                  cold, bkeys.data(), bslots.data(), bvals.data(),
                  bmask.data(), hkeys.data(), hslots.data(), hvals.data(),
                  hmask.data(), blabels.data(), bweights.data());
  }
}

}  // namespace

int main(int argc, char** argv) {
  for (int i = 1; i < argc; ++i) {
    std::FILE* f = std::fopen(argv[i], "rb");
    if (f == nullptr) {
      std::fprintf(stderr, "cannot open %s\n", argv[i]);
      return 1;
    }
    std::string data;
    char buf[1 << 16];
    size_t got;
    while ((got = std::fread(buf, 1, sizeof(buf), f)) > 0)
      data.append(buf, got);
    std::fclose(f);
    drive(data, /*hash_mode=*/1);
    drive(data, /*hash_mode=*/0);
  }
  return 0;
}
