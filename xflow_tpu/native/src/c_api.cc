// C ABI for embedding xflow-tpu in C/C++ programs.
//
// The reference's src/c_api/{c_api.h,c_api.cc} declared
// XFCreate(handle, train, test) / XFStartTrain(handle) around LRWorker
// but was dead code (build commented out, stale includes).  This is the
// live TPU-native equivalent: the library embeds a CPython interpreter
// and drives xflow_tpu.capi_impl, so the whole framework (any model,
// any optimizer, hot table, checkpointing) is reachable from C with
// four functions.  Configuration beyond the two paths is passed as a
// JSON object string matching xflow_tpu.config.Config fields.
//
// Thread-model: the interpreter is initialized lazily on first
// XFCreate (and the GIL released immediately after), so the library
// also works inside a host process that ALREADY embeds Python.  Every
// API body acquires the GIL via PyGILState_Ensure, so calls may come
// from any thread; concurrent calls serialize on the GIL.  Errors
// return NULL/-1; XFLastError() returns a description of the most
// recent failure (read it from the thread that observed the error).

#include <Python.h>

#include <string>

namespace {

std::string g_last_error;

// RAII GIL acquisition: correct both when this library initialized
// Python (we released the GIL after init) and when the host app did.
class GilGuard {
 public:
  GilGuard() : state_(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state_); }
  GilGuard(const GilGuard&) = delete;
  GilGuard& operator=(const GilGuard&) = delete;

 private:
  PyGILState_STATE state_;
};

void capture_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      g_last_error = c != nullptr ? c : "<unprintable python error>";
      Py_DECREF(s);
    }
  } else {
    g_last_error = "<unknown python error>";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

bool ensure_python() {
  if (Py_IsInitialized() != 0) return true;
  Py_InitializeEx(0);  // no signal handlers: the host app owns them
  if (Py_IsInitialized() == 0) return false;
  // Py_InitializeEx leaves this thread holding the GIL; release it so
  // every API body (any thread, including this one) can acquire it
  // symmetrically through PyGILState_Ensure.
  PyEval_SaveThread();
  return true;
}

// Call xflow_tpu.capi_impl.<fn>(args...); returns a new reference or
// nullptr with g_last_error set.
PyObject* call_impl(const char* fn, PyObject* args) {
  PyObject* mod = PyImport_ImportModule("xflow_tpu.capi_impl");
  if (mod == nullptr) {
    capture_py_error();
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) {
    capture_py_error();
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  if (out == nullptr) capture_py_error();
  return out;
}

}  // namespace

extern "C" {

typedef void* XFHandle;

const char* XFLastError() { return g_last_error.c_str(); }

// config_json: optional JSON object of xflow_tpu.config.Config fields
// ({"model": "fm", "epochs": 5, ...}); NULL or "" for defaults.
XFHandle XFCreate(const char* train_path, const char* test_path,
                  const char* config_json) {
  if (!ensure_python()) {
    g_last_error = "failed to initialize embedded python";
    return nullptr;
  }
  GilGuard gil;
  PyObject* args = Py_BuildValue(
      "(sss)", train_path != nullptr ? train_path : "",
      test_path != nullptr ? test_path : "",
      config_json != nullptr ? config_json : "");
  if (args == nullptr) {
    capture_py_error();
    return nullptr;
  }
  PyObject* xf = call_impl("create", args);
  Py_DECREF(args);
  return static_cast<XFHandle>(xf);  // new reference owned by the handle
}

int XFStartTrain(XFHandle h) {
  if (h == nullptr || Py_IsInitialized() == 0) return -1;
  GilGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(h));
  if (args == nullptr) {
    capture_py_error();
    return -1;
  }
  PyObject* out = call_impl("train", args);
  Py_DECREF(args);
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

int XFEvaluate(XFHandle h, double* logloss, double* auc) {
  if (h == nullptr || Py_IsInitialized() == 0) return -1;
  GilGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(h));
  if (args == nullptr) {
    capture_py_error();
    return -1;
  }
  PyObject* out = call_impl("evaluate", args);
  Py_DECREF(args);
  if (out == nullptr) return -1;
  double ll = 0.0, a = 0.0;
  if (PyArg_ParseTuple(out, "dd", &ll, &a) == 0) {
    capture_py_error();
    Py_DECREF(out);
    return -1;
  }
  Py_DECREF(out);
  if (logloss != nullptr) *logloss = ll;
  if (auc != nullptr) *auc = a;
  return 0;
}

void XFDestroy(XFHandle h) {
  if (h == nullptr || Py_IsInitialized() == 0) return;
  GilGuard gil;
  Py_DECREF(static_cast<PyObject*>(h));
}

// -- serving (xflow_tpu/serve) -------------------------------------------

int XFExportArtifact(XFHandle h, const char* directory) {
  if (h == nullptr || directory == nullptr || Py_IsInitialized() == 0)
    return -1;
  GilGuard gil;
  PyObject* args =
      Py_BuildValue("(Os)", static_cast<PyObject*>(h), directory);
  if (args == nullptr) {
    capture_py_error();
    return -1;
  }
  PyObject* out = call_impl("export_artifact", args);
  Py_DECREF(args);
  if (out == nullptr) return -1;
  Py_DECREF(out);
  return 0;
}

XFHandle XFEngineCreate(const char* artifact_dir) {
  if (artifact_dir == nullptr) {
    g_last_error = "artifact_dir is NULL";
    return nullptr;
  }
  if (!ensure_python()) {
    g_last_error = "failed to initialize embedded python";
    return nullptr;
  }
  GilGuard gil;
  PyObject* args = Py_BuildValue("(s)", artifact_dir);
  if (args == nullptr) {
    capture_py_error();
    return nullptr;
  }
  PyObject* eng = call_impl("engine_create", args);
  Py_DECREF(args);
  return static_cast<XFHandle>(eng);  // new reference owned by the handle
}

int XFEngineScore(XFHandle engine, const char* libffm_line, double* pctr) {
  if (engine == nullptr || libffm_line == nullptr ||
      Py_IsInitialized() == 0)
    return -1;
  GilGuard gil;
  PyObject* args =
      Py_BuildValue("(Os)", static_cast<PyObject*>(engine), libffm_line);
  if (args == nullptr) {
    capture_py_error();
    return -1;
  }
  PyObject* out = call_impl("engine_score_line", args);
  Py_DECREF(args);
  if (out == nullptr) return -1;
  double p = PyFloat_AsDouble(out);
  Py_DECREF(out);
  if (p == -1.0 && PyErr_Occurred() != nullptr) {
    capture_py_error();
    return -1;
  }
  if (pctr != nullptr) *pctr = p;
  return 0;
}

}  // extern "C"
