"""On-demand build of the native parser shared library.

Replaces the reference's CMake build of its io static lib
(src/io/CMakeLists.txt): one translation unit, built with the system
g++ the first time it's needed, cached beside the sources, rebuilt when
the source is newer than the cached .so.  A Makefile with the same
flags lives in this directory for manual builds.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from pathlib import Path

_DIR = Path(__file__).resolve().parent
SRC = _DIR / "src" / "parser.cc"
LIB = _DIR / "libxflow_io.so"

CXXFLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-Wall"]


def build_if_needed(force: bool = False) -> Path:
    if not force and LIB.exists() and LIB.stat().st_mtime >= SRC.stat().st_mtime:
        return LIB
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(_DIR))
    os.close(fd)
    try:
        subprocess.run(
            ["g++", *CXXFLAGS, "-o", tmp, str(SRC)],
            check=True,
            capture_output=True,
            text=True,
        )
        os.replace(tmp, LIB)  # atomic: concurrent builders race benignly
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return LIB
