"""On-demand build of the native parser shared library.

Replaces the reference's CMake build of its io static lib
(src/io/CMakeLists.txt): one translation unit, built with the system
g++ the first time it's needed, cached beside the sources, rebuilt when
the source is newer than the cached .so.  A Makefile with the same
flags lives in this directory for manual builds.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from pathlib import Path

_DIR = Path(__file__).resolve().parent
SRC = _DIR / "src" / "parser.cc"
LIB = _DIR / "libxflow_io.so"

CXXFLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-Wall"]


def _compile(
    src: Path, out: Path, extra_flags: list[str], link_flags: list[str] = ()
) -> Path:
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(_DIR))
    os.close(fd)
    try:
        subprocess.run(
            # link libraries must follow the source file
            ["g++", *CXXFLAGS, *extra_flags, "-o", tmp, str(src), *link_flags],
            check=True,
            capture_output=True,
            text=True,
        )
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out


def build_if_needed(force: bool = False) -> Path:
    if not force and LIB.exists() and LIB.stat().st_mtime >= SRC.stat().st_mtime:
        return LIB
    return _compile(SRC, LIB, [])


CAPI_SRC = _DIR / "src" / "c_api.cc"
CAPI_LIB = _DIR / "libxflow_tpu.so"


def build_capi(force: bool = False) -> Path:
    """Build the embed-CPython C ABI library (include/xflow_tpu.h).
    Needs python3-config (python headers); raises on failure — callers
    of the C API opted into the native toolchain."""
    if (
        not force
        and CAPI_LIB.exists()
        and CAPI_LIB.stat().st_mtime >= CAPI_SRC.stat().st_mtime
    ):
        return CAPI_LIB

    def cfg(*args: str) -> list[str]:
        out = subprocess.run(
            ["python3-config", *args], check=True, capture_output=True,
            text=True,
        )
        return out.stdout.split()

    return _compile(
        CAPI_SRC, CAPI_LIB, cfg("--includes"), cfg("--ldflags", "--embed")
    )
