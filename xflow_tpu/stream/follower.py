"""ShardFollower — streaming ingestion over a growing shard directory.

The batch loader (io/loader.py) walks a FIXED shard list per epoch; a
continuous trainer instead *tails* a directory that another process
keeps appending packed-v2 shards to.  Two contracts make that safe:

* **Presence == complete.**  Every writer in this repo (io/packed.py
  ``write_shard``/``write_shard_v2``, io/binary.py, checkpoints)
  writes to a ``*.tmp.*`` name and ``os.replace``s on finalize, so a
  directory listing can never surface a half-written shard.  The
  follower additionally skips any name containing ``.tmp`` — a foreign
  writer that parks temp files next to the stream never feeds the
  trainer garbage.
* **Durable ingestion cursor, at-least-once.**  The
  :class:`IngestCursor` records finished shard names plus the
  (current shard, byte offset) position, flushed through the same
  atomic tmp + ``os.replace`` discipline as checkpoints — at every
  shard boundary and by ``Trainer.close()`` (preemption path).  A
  restart resumes exactly where the cursor says; a hard kill between
  shard-complete and cursor-write replays AT MOST ONE SHARD (the
  at-least-once contract, docs/CONTINUOUS.md "Cursor & resume").
  FTRL/SGD updates are not idempotent under replay, so the replayed
  shard trains twice — bounded, loud (the cursor logs the rewind),
  and the price of never *skipping* data.

Each batch is stamped with the wall-clock instant its shard was first
observed (``StreamMeta.ingest_unix``) — the event-time anchor behind
the ``freshness`` metric (newest-event-age at swap commit).

Self-healing: the directory poll rides the ``stream.poll`` chaos
failpoint + bounded retry (chaos/heal.py — ``recovered:io_retry``
health rows); per-record corruption inside a shard rides the loader's
own quarantine/retry fabric unchanged (ShardLoader is the reader).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Iterator

from xflow_tpu.chaos import failpoint, retry_call
from xflow_tpu.obs import NULL_OBS


@dataclasses.dataclass
class StreamMeta:
    """Per-batch ingestion provenance, yielded alongside every batch."""

    shard: str  # shard file name (basename, the cursor's key)
    resume_offset: int  # loader resume offset AFTER this batch
    ingest_unix: float  # when the shard was first observed
    shard_index: int  # 0-based ingestion order across the stream


class IngestCursor:
    """Durable stream position: finished shard names + (current shard,
    offset).  ``flush()`` is atomic (tmp + ``os.replace`` — the
    checkpoint discipline); callers flush at shard boundaries and on
    ``Trainer.close()``, which bounds replay after a hard kill to one
    shard (at-least-once)."""

    def __init__(self, path: str):
        self.path = path
        self.done: set[str] = set()
        self.current: str | None = None
        self.offset: int = 0
        self._dirty = False
        # chaos site: cursor read fault on restart — replay stays
        # bounded by the at-least-once contract (XF018)
        failpoint("stream.cursor")
        if os.path.exists(path):
            with open(path) as f:
                raw = json.load(f)
            self.done = set(raw.get("done", []))
            self.current = raw.get("current")
            self.offset = int(raw.get("offset", 0))

    def note(self, shard: str, offset: int) -> None:
        """In-memory position update (one per yielded batch — cheap);
        durability happens at flush()."""
        self.current = shard
        self.offset = int(offset)
        self._dirty = True

    def mark_done(self, shard: str) -> None:
        self.done.add(shard)
        if self.current == shard:
            self.current = None
            self.offset = 0
        self._dirty = True

    def payload(self) -> dict:
        """JSON-ready snapshot — embedded into trainer checkpoints so
        a restored model rewinds the cursor to ITS stream position
        (stream/driver.py): model state and ingestion position move as
        one, or replay is unbounded/skipping (docs/CONTINUOUS.md)."""
        return {
            "done": sorted(self.done),
            "current": self.current,
            "offset": self.offset,
        }

    def load_payload(self, payload: dict) -> None:
        """Rewind/replace the cursor from a checkpoint snapshot and
        persist it — shards trained after the checkpoint REPLAY on the
        restored model (at-least-once, never skip)."""
        self.done = set(payload.get("done", []))
        self.current = payload.get("current")
        self.offset = int(payload.get("offset", 0))
        self._dirty = True
        self.flush()

    def flush(self) -> None:
        """Atomic durable write — same tmp + ``os.replace`` path as
        checkpoints (utils/checkpoint.py), so a kill mid-flush leaves
        the previous cursor intact, never a torn file."""
        if not self._dirty:
            return
        # chaos site: kill mid-flush — tmp + os.replace must leave the
        # previous cursor intact (XF018)
        failpoint("stream.cursor")
        tmp = f"{self.path}.tmp.{os.getpid()}"
        payload = self.payload()
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._dirty = False


class ShardFollower:
    """Tail ``directory`` for complete shard files and stream their
    batches through ``loader_factory`` (a ``path -> ShardLoader``
    callable, so the follower inherits the loader's quarantine/retry
    healing and format sniffing — text, CSR-binary, packed v1/v2).

    Synchronous by design: ``batches()`` is a plain generator the
    training loop drains — no threads, no queues, no shared state
    (the trainer's own prefetch/transfer machinery stays the
    concurrency layer).  Files are consumed in NAME order; writers
    must use monotonically sortable names (the ``prefix-NNNNN``
    convention already does).
    """

    def __init__(
        self,
        directory: str,
        loader_factory: Callable,
        cursor: IngestCursor,
        poll_interval_s: float = 0.5,
        idle_stop_s: float | None = None,
        stop: Callable[[], bool] | None = None,
        obs=None,
        io_retries: int = 2,
        io_retry_backoff_s: float = 0.05,
    ):
        if poll_interval_s <= 0:
            raise ValueError("poll_interval_s must be > 0")
        self.directory = directory
        self.loader_factory = loader_factory
        self.cursor = cursor
        self.poll_interval_s = poll_interval_s
        # stop after this much continuous idle (no new complete shards);
        # None = follow forever (production tail mode)
        self.idle_stop_s = idle_stop_s
        self._stop = stop if stop is not None else lambda: False
        self.obs = obs if obs is not None else NULL_OBS
        self.io_retries = io_retries
        self.io_retry_backoff_s = io_retry_backoff_s
        # shard -> first-observed wall clock (the event-time anchor);
        # shards already finished per the cursor never re-enter, so
        # this map is bounded by the in-flight window
        self._first_seen: dict[str, float] = {}
        self.shards_ingested = 0
        self.polls = 0

    # -- discovery ----------------------------------------------------------

    def _poll_once(self) -> list[str]:
        """One directory listing through the chaos + retry fabric.
        ``stream.poll`` is the injection site (scripts/check_chaos.py
        grammar); a transient listing failure heals with a bounded
        retry and a ``recovered:io_retry`` health row — a persistent
        one propagates (the stream source is gone, which is not a
        skippable fault)."""

        def attempt() -> list[str]:
            failpoint("stream.poll")
            names = []
            for name in os.listdir(self.directory):
                if ".tmp" in name:
                    continue  # writer scratch — never complete
                if not os.path.isfile(os.path.join(self.directory, name)):
                    continue
                names.append(name)
            return sorted(names)

        return retry_call(
            attempt,
            attempts=self.io_retries,
            backoff_s=self.io_retry_backoff_s,
            channel="stream",
            site=f"poll:{self.directory}",
            obs=self.obs,
        )

    def pending_shards(self) -> list[str]:
        """Complete shards not yet fully ingested, in consumption
        order (cursor's current shard first when resuming)."""
        self.polls += 1
        names = self._poll_once()
        now = time.time()
        out = []
        for name in names:
            if name in self.cursor.done:
                continue
            self._first_seen.setdefault(name, now)
            out.append(name)
        return out

    # -- streaming ----------------------------------------------------------

    def batches(self) -> Iterator[tuple]:
        """Yield ``(batch, StreamMeta)`` forever (or until the stop/
        idle condition): drain every pending shard in order, then poll
        again.  The cursor advances in memory per batch and flushes
        durably per finished shard."""
        idle_since: float | None = None
        while True:
            if self._stop():
                return
            pending = self.pending_shards()
            if not pending:
                now = time.time()
                if idle_since is None:
                    idle_since = now
                if (
                    self.idle_stop_s is not None
                    and now - idle_since >= self.idle_stop_s
                ):
                    return
                time.sleep(self.poll_interval_s)
                continue
            idle_since = None
            for name in pending:
                if self._stop():
                    return
                yield from self._ingest_shard(name)

    def _ingest_shard(self, name: str) -> Iterator[tuple]:
        path = os.path.join(self.directory, name)
        start = (
            self.cursor.offset if self.cursor.current == name else 0
        )
        loader = self.loader_factory(path)
        ingest_unix = self._first_seen.get(name, time.time())
        index = self.shards_ingested
        for batch, resume in loader.iter_batches(start):
            yield batch, StreamMeta(
                shard=name,
                resume_offset=resume,
                ingest_unix=ingest_unix,
                shard_index=index,
            )
            # the cursor advances only HERE — at generator resumption,
            # i.e. after the consumer came back for the next batch, so
            # the yielded one was trained.  A dispatch that raises
            # never resumes this generator, the cursor stays on the
            # previous batch, and the close()-path flush replays the
            # failed batch instead of skipping it (at-least-once).
            self.cursor.note(name, resume)
        self.shards_ingested += 1
        self._first_seen.pop(name, None)
        self.cursor.mark_done(name)
        # durable at every shard boundary: the at-least-once bound —
        # a kill right here (after training, before the flush) replays
        # exactly this one shard on restart
        self.cursor.flush()
