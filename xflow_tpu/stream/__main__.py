"""``python -m xflow_tpu.stream`` — the continuous-training CLI.

    python -m xflow_tpu.stream run --stream-dir DIR --workdir DIR \
        --model lr --table-size-log2 22 [--metrics-out RUN.jsonl] \
        [--export-every-steps N] [--compact-every K] [--replicas R] \
        [--freshness-slo-s S] [--resume auto] ...

Tails ``--stream-dir`` for packed-v2 shards, trains continuously, cuts
incremental delta exports, and hot-swaps them onto an in-process
replica fleet through the staged-rollout canary gate, reporting
``freshness`` rows (docs/CONTINUOUS.md).  SIGTERM/SIGINT stop the loop
gracefully: the ingestion cursor and metrics flush, so a restarted run
resumes mid-stream.
"""

from __future__ import annotations

import argparse
import signal
import sys

from xflow_tpu.config import Config
from xflow_tpu.stream.driver import StreamDriver
from xflow_tpu.train import build_parser, config_from_args


def _stream_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="xflow_tpu.stream",
        description="continuous training: streaming ingestion + delta "
        "export + SLO-gated hot-swap (docs/CONTINUOUS.md)",
    )
    sub = p.add_subparsers(dest="command", required=True)
    run = sub.add_parser(
        "run", help="run the continuous train→export→swap loop",
        # inherit every trainer config flag (--model, --table-size-log2,
        # --metrics-out, --chaos-spec, --store-mode, ...) so the stream
        # CLI never forks the config surface
        parents=[build_parser()], add_help=False, conflict_handler="resolve",
    )
    run.add_argument(
        "--stream-dir", required=True,
        help="directory another process appends complete shards to "
        "(atomic-rename writers; io/packed.py)",
    )
    run.add_argument(
        "--workdir", required=True,
        help="driver state: ingestion cursor + exported artifacts",
    )
    run.add_argument("--replicas", type=int, default=2)
    run.add_argument(
        "--export-every-steps", type=int, default=50,
        help="cut a servable export every N train steps",
    )
    run.add_argument(
        "--compact-every", type=int, default=8,
        help="cut a fresh FULL base after this many deltas",
    )
    run.add_argument("--canary-frac", type=float, default=0.25)
    run.add_argument("--min-canary-requests", type=int, default=16)
    run.add_argument("--max-error-frac", type=float, default=0.0)
    run.add_argument("--max-p99-ms", type=float, default=None)
    run.add_argument(
        "--freshness-slo-s", type=float, default=60.0,
        help="event-to-servable SLO stamped into freshness rows "
        "(obs doctor ranks a stream past it as servable_stale)",
    )
    run.add_argument("--rollout-timeout-s", type=float, default=60.0)
    run.add_argument("--poll-interval-s", type=float, default=0.5)
    run.add_argument(
        "--idle-stop-s", type=float, default=None,
        help="stop after this much idle with no new shards "
        "(default: follow forever)",
    )
    run.add_argument("--max-steps", type=int, default=None)
    run.add_argument("--max-commits", type=int, default=None)
    return p


def main(argv: list[str] | None = None) -> int:
    args = _stream_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    cfg = config_from_args(args)
    driver = StreamDriver(
        cfg,
        args.stream_dir,
        args.workdir,
        replicas=args.replicas,
        export_every_steps=args.export_every_steps,
        compact_every=args.compact_every,
        canary_frac=args.canary_frac,
        min_canary_requests=args.min_canary_requests,
        max_error_frac=args.max_error_frac,
        max_p99_ms=args.max_p99_ms,
        freshness_slo_s=args.freshness_slo_s,
        rollout_timeout_s=args.rollout_timeout_s,
        poll_interval_s=args.poll_interval_s,
        idle_stop_s=args.idle_stop_s,
        max_steps=args.max_steps,
        max_commits=args.max_commits,
        resume=args.resume,
        log=lambda s: print(s, file=sys.stderr),
    )

    def on_signal(signum, frame):
        print(
            f"signal {signum}: draining the stream loop (cursor + "
            "metrics flush on close)",
            file=sys.stderr,
        )
        driver.request_stop()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, on_signal)
    summary = driver.run()
    print(
        f"stream run: {summary['steps']} steps over "
        f"{summary['shards_ingested']} shard(s), {summary['exports']} "
        f"export(s), {summary['commits']} commit(s), "
        f"{summary['aborts']} abort(s), servable {summary['servable']}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
