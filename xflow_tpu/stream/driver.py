"""StreamDriver — the continuous train→export→canary→swap loop.

One single-host process owns the whole loop (the topology the
reference's online deployments run per model: a trainer pod feeding a
serving fleet):

    ShardFollower ──batches──▶ Trainer.train_stream
         │                         │ every export_every_steps
         │ durable IngestCursor    ▼
         │                    export_delta (or a full base every
         │                    compact_every deltas / after an abort)
         │                         │
         │                         ▼
         │                ReplicaFleet.rollout_delta / begin_rollout
         │                  canary → health gate → commit (or abort)
         │                         │
         └── ingest timestamps ───▶ `freshness` row: newest-event-age
                                    at swap commit (obs/schema.py)

Design points:

* **Single thread of control.**  The driver never spawns threads: the
  follower is a synchronous generator, rollouts are driven by probe
  requests submitted inline while polling the gate (real traffic works
  too — probes just guarantee the canary gate accumulates on an
  otherwise-idle toy fleet).  Concurrency stays where PR 6/10 already
  gated it (loader prefetch, batcher workers).
* **Freshness is measured, not assumed.**  Every batch carries the
  wall-clock instant its shard appeared; an export records the newest
  such instant it covers; the ``freshness`` row at commit reports
  ``now - newest_covered`` — the true event-to-servable latency the
  SLO is about (docs/CONTINUOUS.md "Freshness SLO").
* **Abort recovery = compaction.**  A delta whose rollout aborts
  leaves the fleet on the older servable; the next refresh detects the
  broken chain (exported step != fleet servable step) and cuts a
  fresh FULL base instead of wedging on digest-chain refusals.
"""

from __future__ import annotations

import os
import shutil
import time

import numpy as np

import jax

from xflow_tpu.config import Config
from xflow_tpu.obs.live import AlertEvaluator
from xflow_tpu.serve.artifact import export_artifact, servable_digest
from xflow_tpu.serve.fleet import ReplicaFleet, ShedError
from xflow_tpu.stream.delta import (
    TouchedLedger,
    delta_nbytes,
    export_delta,
)
from xflow_tpu.stream.follower import IngestCursor, ShardFollower
from xflow_tpu.trainer import Trainer


class StreamDriver:
    """``python -m xflow_tpu.stream run`` in library form (the gate
    script and tests drive it directly)."""

    def __init__(
        self,
        cfg: Config,
        stream_dir: str,
        workdir: str,
        *,
        replicas: int = 2,
        export_every_steps: int = 20,
        compact_every: int = 8,
        canary_frac: float = 0.25,
        min_canary_requests: int = 16,
        max_error_frac: float = 0.0,
        max_p99_ms: float | None = None,
        freshness_slo_s: float = 60.0,
        rollout_timeout_s: float = 60.0,
        probe_batch: int = 8,
        poll_interval_s: float = 0.25,
        idle_stop_s: float | None = None,
        max_steps: int | None = None,
        max_commits: int | None = None,
        buckets=(1, 8, 64),
        resume: str | None = None,
        log=None,
    ):
        if export_every_steps < 1:
            raise ValueError("export_every_steps must be >= 1")
        if compact_every < 1:
            raise ValueError("compact_every must be >= 1")
        self.cfg = cfg
        self.stream_dir = stream_dir
        self.workdir = workdir
        self.replicas = replicas
        self.export_every_steps = export_every_steps
        self.compact_every = compact_every
        self.canary_frac = canary_frac
        self.min_canary_requests = min_canary_requests
        self.max_error_frac = max_error_frac
        self.max_p99_ms = max_p99_ms
        self.freshness_slo_s = freshness_slo_s
        self.rollout_timeout_s = rollout_timeout_s
        self.probe_batch = probe_batch
        self.max_steps = max_steps
        self.max_commits = max_commits
        self.buckets = tuple(buckets)
        self._log = log if log is not None else (lambda s: None)
        os.makedirs(workdir, exist_ok=True)
        self.trainer = Trainer(cfg)
        self.cursor = IngestCursor(
            os.path.join(workdir, "ingest-cursor.json")
        )
        # Model-state durability pairs with the ingestion cursor: with
        # --checkpoint-dir the driver checkpoints at every export cut,
        # EMBEDDING the cursor snapshot, and a restore rewinds the
        # cursor file to it — shards trained after the checkpoint
        # replay on the restored model (at-least-once), and a restart
        # can never train new shards on fresh weights while the cursor
        # skips the old ones (docs/CONTINUOUS.md "Cursor & resume").
        restored = None
        if resume:
            restored = self.trainer.restore(auto=(resume == "auto"))
            if restored is not None:
                self._log(f"resumed model state at {restored}")
                snap = restored.get("stream")
                if snap is not None:
                    self.cursor.load_payload(snap)
                    self._log(
                        f"rewound ingestion cursor to the checkpoint "
                        f"({len(self.cursor.done)} shard(s) done)"
                    )
        if restored is None and (self.cursor.done or self.cursor.current):
            self._log(
                "WARNING: the ingestion cursor resumes the stream but "
                "the MODEL starts fresh — earlier shards' training is "
                "lost; run with --checkpoint-dir and --resume auto for "
                "a consistent restart (docs/CONTINUOUS.md)"
            )
        self.trainer.register_stream_cursor(self.cursor)
        self._stop_requested = False
        self.follower = ShardFollower(
            stream_dir,
            self.trainer._loader,
            self.cursor,
            poll_interval_s=poll_interval_s,
            idle_stop_s=idle_stop_s,
            stop=self._should_stop,
            obs=self.trainer.obs,
            io_retries=cfg.io_retries,
            io_retry_backoff_s=cfg.io_retry_backoff_s,
        )
        self.ledger = TouchedLedger()
        self.fleet: ReplicaFleet | None = None
        self._newest_ingest = 0.0
        # step of the newest export on disk vs the step the fleet
        # actually serves: divergence (an aborted rollout) forces the
        # next refresh to cut a full base — the chain self-heals
        self._last_export_step = -1
        self._fleet_step = -1
        self.deltas_since_base = 0
        self._base_steps: list[int] = []
        self.commits = 0
        self.aborts = 0
        self.exports = 0
        self.probe_errors = 0
        self._rng = np.random.default_rng(cfg.seed + 1)
        self._closed = False
        # the live train_stream generator chain: an early break (max
        # commits) suspends it mid-shard with the shard file open —
        # close() shuts it down explicitly instead of waiting on GC
        # (the Trainer._live_prefetch discipline, generator edition)
        self._stream_gen = None
        # SLO alert rules over the driver's own freshness rows
        # (obs/live.py): a stale servable fires `freshness_age` into
        # the same metrics stream the doctor reads — the driver is
        # single-threaded, so evaluation rides the commit path inline
        self.alerts = AlertEvaluator(
            metrics_logger=self.trainer.metrics_logger
        )
        # test/gate hook: called as on_commit(driver, export_info)
        # right after a rollout commits, while the trainer state still
        # sits at the committed step — the parity check's window
        self.on_commit = None

    # -- control ------------------------------------------------------------

    def request_stop(self) -> None:
        """Graceful stop (the CLI's SIGTERM/SIGINT hook): the follower
        returns at its next batch boundary and run() drains."""
        self._stop_requested = True

    def _should_stop(self) -> bool:
        if self._stop_requested:
            return True
        if (
            self.max_steps is not None
            and self.trainer._global_steps >= self.max_steps
        ):
            return True
        if self.max_commits is not None and (
            self.commits >= self.max_commits
        ):
            return True
        return False

    # -- ingestion tagging --------------------------------------------------

    def _tagged_batches(self):
        """Follower stream with the driver's two per-batch hooks: the
        touched-row ledger (delta export) and the newest-event stamp
        (freshness)."""
        for batch, meta in self.follower.batches():
            self.ledger.mark(batch)
            if meta.ingest_unix > self._newest_ingest:
                self._newest_ingest = meta.ingest_unix
            yield batch, meta

    # -- export / rollout ---------------------------------------------------

    def _step_now(self) -> int:
        return int(jax.device_get(self.trainer.state["step"]))

    def _export_path(self, kind: str, step: int) -> str:
        return os.path.join(
            self.workdir, "exports", f"{kind}-{step:010d}"
        )

    def _cut_export(self) -> dict:
        """Cut the next servable artifact: an incremental delta when
        the chain is intact and under the compaction budget, else a
        full base.  Resets the ledger — an aborted rollout of the
        result is recovered by the base fallback, never by replaying
        the ledger."""
        step = self._step_now()
        need_base = (
            self.fleet is None
            or self.deltas_since_base >= self.compact_every
            or self._last_export_step != self._fleet_step
        )
        newest = self._newest_ingest
        if need_base:
            path = self._export_path("base", step)
            export_artifact(self.trainer, path)
            self.deltas_since_base = 0
            self._base_steps.append(step)
            self._gc_exports()
            kind = "base"
            rows = self.cfg.table_size
        else:
            path = self._export_path("delta", step)
            manifest = export_delta(
                self.trainer, path, self.ledger, self._last_export_step
            )
            self.deltas_since_base += 1
            kind = "delta"
            rows = manifest["rows"]
        self.ledger.reset()
        self._last_export_step = step
        self.exports += 1
        if self.cfg.checkpoint_dir:
            # model durability at export cadence, cursor snapshot
            # embedded (restore rewinds the stream to this exact point)
            self.trainer.save(extra={"stream": self.cursor.payload()})
        info = {
            "kind": kind,
            "path": path,
            "step": step,
            "rows": int(rows),
            "bytes": delta_nbytes(path),
            "newest_ingest": newest,
            "deltas_since_base": self.deltas_since_base,
        }
        self._log(
            f"export[{self.exports}] {kind} step={step} rows={rows} "
            f"bytes={info['bytes']}"
        )
        self._freshness_row("export", info)
        return info

    def _gc_exports(self) -> None:
        """Retention mirroring checkpoint_keep=2 (Config): keep the
        chains of the newest TWO bases; anything older serves no
        replayable purpose (a cold start loads the newest base, the
        previous one is the mid-commit safety margin).  Without this a
        follow-forever run accumulates GB-scale bases until the disk
        fills and the export write takes the loop down."""
        if len(self._base_steps) < 2:
            return
        floor = self._base_steps[-2]
        exp = os.path.join(self.workdir, "exports")
        for name in os.listdir(exp):
            try:
                step = int(name.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                continue
            if step < floor:
                shutil.rmtree(
                    os.path.join(exp, name), ignore_errors=True
                )

    def _ensure_fleet(self, base: dict) -> None:
        assert base["kind"] == "base"
        self.fleet = ReplicaFleet.load(
            base["path"],
            replicas=self.replicas,
            buckets=self.buckets,
            metrics_logger=self.trainer.metrics_logger,
            flight=self.trainer._flight,
            warm=True,
        )
        self._fleet_step = base["step"]
        self._log(
            f"fleet up: {self.replicas} replica(s) on servable "
            f"{self.fleet.servable}"
        )
        self._freshness_row("commit", base)

    def _probe_keys(self):
        n = int(self._rng.integers(1, max(2, self.cfg.max_nnz // 4)))
        return self._rng.integers(
            0, self.cfg.table_size, size=n, dtype=np.int64
        )

    def _drive_rollout(self, info: dict) -> bool:
        """Roll ``info``'s artifact onto the fleet through the canary
        health gate, feeding probe traffic while polling; returns True
        on commit.  A gate that cannot pass within
        ``rollout_timeout_s`` aborts — the fleet stays on the
        incumbent and the next refresh cuts a base."""
        fleet = self.fleet
        gate = dict(
            canary_frac=self.canary_frac,
            min_canary_requests=self.min_canary_requests,
            max_error_frac=self.max_error_frac,
            max_p99_ms=self.max_p99_ms,
        )
        if info["kind"] == "delta":
            fleet.rollout_delta(info["path"], **gate)
        else:
            fleet.begin_rollout(info["path"], **gate)
        deadline = time.monotonic() + self.rollout_timeout_s
        committed = False
        while True:
            futs = []
            for _ in range(self.probe_batch):
                try:
                    futs.append(fleet.submit(self._probe_keys()))
                except ShedError:
                    pass  # admission control defending the budget
            for f in futs:
                try:
                    f.result(timeout=30.0)
                except Exception:  # booked by the fleet's own counters
                    self.probe_errors += 1
            state = fleet.rollout_state()
            if state is None:
                # resolved underneath us (a concurrent auto tick or an
                # operator commit/abort): only the servable identity
                # says WHICH way — an external abort must not book a
                # commit (the digest-chain would silently break)
                committed = fleet.servable == servable_digest(
                    fleet.digest, info["step"]
                )
                break
            if state["healthy"]:
                health = fleet.commit_rollout()
                self._log(f"rollout commit: {health}")
                committed = True
                break
            if time.monotonic() > deadline:
                health = fleet.abort_rollout(
                    detail="stream driver: health gate timeout"
                )
                self._log(f"rollout ABORT (gate timeout): {health}")
                break
        if committed:
            self.commits += 1
            self._fleet_step = info["step"]
            self._freshness_row("commit", info)
            if self.on_commit is not None:
                self.on_commit(self, info)
        else:
            self.aborts += 1
            self._freshness_row("abort", info)
        return committed

    def _freshness_row(self, event: str, info: dict) -> None:
        """The event-to-servable metric (obs/schema.py ``freshness``):
        at commit, ``newest_event_age_s`` is wall-clock now minus the
        newest ingest instant the swapped servable covers — the
        latency an advertiser's newest click waited to influence live
        scores."""
        logger = self.trainer.metrics_logger
        age = max(0.0, time.time() - info["newest_ingest"]) if (
            info["newest_ingest"] > 0
        ) else 0.0
        row = {
            "event": event,
            "newest_event_age_s": round(age, 3),
            "slo_s": round(self.freshness_slo_s, 3),
            "servable": (
                self.fleet.servable if self.fleet is not None else "?"
            ),
            "export_kind": info["kind"],
            "step": int(info["step"]),
            "rows": int(info["rows"]),
            "delta_bytes": int(info["bytes"]),
            "deltas_since_base": int(info["deltas_since_base"]),
        }
        if logger is not None:
            logger.log("freshness", row)
        # the freshness_age burn-rate rule sees every row, logger or
        # not — firing/resolved transitions land as `alert` rows
        self.alerts.observe_rows([dict(row, kind="freshness")])

    # -- the loop -----------------------------------------------------------

    def run(self) -> dict:
        """Run the continuous loop until the stop/idle condition;
        returns a summary dict (the gate script's surface)."""
        try:
            self._stream_gen = self.trainer.train_stream(
                self._tagged_batches()
            )
            for steps, _meta in self._stream_gen:
                if steps % self.export_every_steps:
                    continue
                info = self._cut_export()
                if self.fleet is None:
                    self._ensure_fleet(info)
                    continue
                self._drive_rollout(info)
                if self._should_stop():
                    break
            return self.summary()
        finally:
            self.close()

    def summary(self) -> dict:
        out = {
            "steps": self.trainer._global_steps,
            "shards_ingested": self.follower.shards_ingested,
            "exports": self.exports,
            "commits": self.commits,
            "aborts": self.aborts,
            "probe_errors": self.probe_errors,
            "servable": (
                self.fleet.servable if self.fleet is not None else None
            ),
        }
        if self.fleet is not None:
            out["fleet"] = self.fleet.stats()
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._stream_gen is not None:
            self._stream_gen.close()  # releases the open shard file
            self._stream_gen = None
        if self.fleet is not None:
            self.fleet.close()
        self.trainer.close()

    def __enter__(self) -> "StreamDriver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
