"""Incremental delta export — ship only the rows training touched.

A full serving artifact at north-star geometry is ~GBs (2^28 rows × D
× 4 B per table); between two exports minutes apart a continuous
trainer touches a small fraction of those rows (zipf traffic), so
shipping the full table per refresh wastes ~the whole artifact.  A
**delta** holds exactly:

* ``delta.keys.npy`` — the sorted logical row ids touched since the
  base (the :class:`TouchedLedger`'s accumulated set — fed per batch
  from ``Batch`` masks or ``CompactBatch.touched_rows()``; the tiered
  store's cold ledger + hot ``key_of`` name the same rows);
* ``delta.<table>.param.npy`` — the CURRENT param rows for those ids,
  param plane ONLY (FTRL n/z never serve — same exclusion as the full
  artifact, serve/artifact.py);
* ``dense.<name>.npy`` — replicated dense params in full (MLP weights
  change every step and are tiny next to one table chunk);
* ``delta_manifest.json`` — config + digest chain + a content sha.

**Digest chain.**  Every servable has an identity
``servable_digest(config_digest, step)`` (serve/artifact.py): a full
export at step S and base + deltas applied through step S are the same
model (the bitwise round-trip test pins it), so they share the
identity.  A delta records the chain edge ``base_digest →
delta_digest``; ``apply_delta`` refuses a delta whose ``base_digest``
is not the engine's current servable — out-of-order or cross-model
application fails loudly with the fix in the message, never silently
skews weights.

**Compaction.**  Deltas grow with the union of touched rows since the
base; the loop driver (stream/driver.py) cuts a fresh FULL base every
``compact_every`` deltas and resets the ledger, bounding both delta
size and the chain an operator must replay after a cold start.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time

import numpy as np

import jax
import jax.numpy as jnp

from xflow_tpu.chaos import failpoint
from xflow_tpu.serve.artifact import servable_digest

DELTA_MANIFEST = "delta_manifest.json"
DELTA_FORMAT = 1


class TouchedLedger:
    """Union of big-table row ids touched since the last export.

    Fed per ingested batch on the host side (the batch is in hand
    anyway — ``mark()`` is one masked-unique over planes already in
    cache), which makes the ledger identical for every store mode:
    dense, MXU-hot (hot-section ids ARE table rows [0, hot_size)),
    and tiered (the driver marks the same batches the store plans).
    """

    def __init__(self):
        self._keys: set[int] = set()

    def __len__(self) -> int:
        return len(self._keys)

    def mark(self, batch) -> None:
        """Accumulate one Batch or CompactBatch's touched rows."""
        if hasattr(batch, "touched_rows"):  # CompactBatch — no expand
            self._keys.update(
                np.unique(batch.touched_rows()).tolist()
            )
            return
        touched = batch.keys[batch.mask > 0]
        if batch.hot_nnz:
            touched = np.concatenate(
                [touched, batch.hot_keys[batch.hot_mask > 0]]
            )
        self._keys.update(np.unique(touched).tolist())

    def mark_rows(self, rows: np.ndarray) -> None:
        self._keys.update(np.asarray(rows).tolist())

    def keys(self) -> np.ndarray:
        """Sorted int64 ids — the delta's key plane."""
        return np.asarray(sorted(self._keys), np.int64)

    def reset(self) -> None:
        self._keys.clear()


def _param_rows(trainer, table: str, keys: np.ndarray) -> np.ndarray:
    """Current param rows for logical ids ``keys``, either store mode:
    tiered reads through the two-tier logical view (store/tiered.py —
    flushes the pending write-back first), dense gathers on device so
    only the touched rows cross back to the host."""
    store = getattr(trainer.step, "store", None)
    if store is not None:
        return np.asarray(
            store.logical_rows(trainer.state, table, keys)["param"],
            np.float32,
        )
    param = trainer.state["tables"][table]["param"]
    rows = jnp.take(param, jnp.asarray(keys, jnp.int32), axis=0)
    return np.asarray(jax.device_get(rows), np.float32)


def export_delta(
    trainer,
    directory: str,
    ledger: TouchedLedger,
    base_step: int,
) -> dict:
    """Freeze the rows ``ledger`` accumulated since the export at
    ``base_step`` into a delta artifact at ``directory`` (atomic tmp +
    rename, replacing any previous delta there); returns the manifest.
    Single-process (the continuous driver's topology; multi-host
    export stays the full-artifact path)."""
    if jax.process_count() > 1:
        raise RuntimeError(
            "export_delta is single-process — multi-host runs export "
            "full artifacts (serve/artifact.py)"
        )
    cfg = trainer.cfg
    # chaos site: writer fault mid-delta — the tmp-dir + rename
    # atomicity below is what it exercises (XF018)
    failpoint("delta.export")
    step = int(jax.device_get(trainer.state["step"]))
    keys = ledger.keys()
    parent = os.path.dirname(os.path.abspath(directory))
    tmp = os.path.join(
        parent, f".tmp-delta-{os.path.basename(directory)}"
    )
    os.makedirs(parent, exist_ok=True)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    sha = hashlib.sha256()
    sha.update(keys.tobytes())
    np.save(os.path.join(tmp, "delta.keys.npy"), keys)
    arrays_meta: dict = {}
    # name-sorted table order: apply_delta folds the content sha in
    # sorted(state["tables"]) order, so export must hash the same way
    for spec in sorted(trainer.model.tables(), key=lambda s: s.name):
        rows = _param_rows(trainer, spec.name, keys)
        sha.update(rows.tobytes())
        arrays_meta[f"{spec.name}.param"] = {
            "shape": list(rows.shape),
            "dtype": "float32",
        }
        np.save(os.path.join(tmp, f"delta.{spec.name}.param.npy"), rows)
    dense_names = sorted(trainer.state.get("dense", {}))
    for dname in dense_names:
        host = np.asarray(
            jax.device_get(trainer.state["dense"][dname])
        )
        sha.update(host.tobytes())
        np.save(os.path.join(tmp, f"dense.{dname}.npy"), host)
    manifest = {
        "format": DELTA_FORMAT,
        "kind": "delta",
        "model": cfg.model,
        "config": cfg.to_json(),
        "config_digest": cfg.digest(),
        "step": step,
        "base_step": int(base_step),
        "base_digest": servable_digest(cfg.digest(), base_step),
        "delta_digest": servable_digest(cfg.digest(), step),
        "rows": int(len(keys)),
        "arrays": arrays_meta,
        "dense": dense_names,
        "content_sha256": sha.hexdigest(),
        "created_unix": round(time.time(), 3),
    }
    with open(os.path.join(tmp, DELTA_MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2)
    if os.path.exists(directory):
        shutil.rmtree(directory)
    os.rename(tmp, directory)
    return manifest


def delta_nbytes(directory: str) -> int:
    """Total artifact bytes on disk (delta or full — the number behind
    the "delta bytes < 25% of a full export" acceptance check)."""
    total = 0
    for name in os.listdir(directory):
        total += os.path.getsize(os.path.join(directory, name))
    return total


def load_delta_manifest(directory: str) -> dict:
    """Parse + integrity-check a delta manifest (the full-artifact
    ``load_manifest`` counterpart): format, digest-chain consistency
    with the embedded config, and the content sha over keys + rows."""
    from xflow_tpu.config import Config

    failpoint("delta.load")
    path = os.path.join(directory, DELTA_MANIFEST)
    if not os.path.exists(path):
        raise ValueError(
            f"{directory}: no delta manifest ({DELTA_MANIFEST}) — a "
            "FULL artifact loads via PredictEngine.load, not apply_delta"
        )
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("format") != DELTA_FORMAT:
        raise ValueError(
            f"{directory}: unsupported delta format "
            f"{manifest.get('format')!r} (expected {DELTA_FORMAT})"
        )
    try:
        cfg = Config.from_json(manifest["config"])
    except TypeError as e:
        # corrupted/transposed manifest keys reach Config.__init__ as
        # bad kwargs — surface as the same typed refusal as any other
        # malformed manifest, not a decoder crash
        raise ValueError(
            f"{directory}: delta manifest config is malformed: {e}"
        ) from e
    if cfg.digest() != manifest.get("config_digest"):
        raise ValueError(
            f"{directory}: delta config_digest "
            f"{manifest.get('config_digest')!r} does not match the "
            f"embedded config ({cfg.digest()}) — artifact corrupt or "
            "tampered"
        )
    want = servable_digest(cfg.digest(), manifest["step"])
    if manifest.get("delta_digest") != want:
        raise ValueError(
            f"{directory}: delta_digest {manifest.get('delta_digest')!r}"
            f" does not match servable identity {want} for step "
            f"{manifest['step']} — artifact corrupt or tampered"
        )
    return manifest


def apply_delta(engine, directory: str):
    """Fold a delta onto ``engine``'s servable and return a NEW
    engine at the delta's step.

    The returned engine is a :meth:`PredictEngine.clone` with a fresh
    param-state (shared AOT executables — applying a delta never
    recompiles; the state is an executable argument) whose tables have
    the delta rows scattered in place.  The source engine is
    untouched: fleets canary the new engine through the staged-rollout
    gate before any traffic converges on it (serve/fleet.py
    ``rollout_delta``).

    Refusals (all actionable): config-digest mismatch (wrong model),
    digest-chain mismatch (this delta was cut against a different
    servable — apply the intervening deltas in order, or load the
    fresh full base the compaction policy cut), content-sha mismatch
    (bytes corrupt)."""
    failpoint("delta.apply")
    manifest = load_delta_manifest(directory)
    if manifest["config_digest"] != engine.digest:
        raise ValueError(
            f"delta {directory} was exported from config "
            f"{manifest['config_digest']}, engine serves "
            f"{engine.digest} — refusing to apply across models"
        )
    base = manifest["base_digest"]
    if base != engine.servable_digest:
        raise ValueError(
            f"digest-chain mismatch: delta {directory} was cut against "
            f"servable {base} (step {manifest['base_step']}), but the "
            f"engine currently serves {engine.servable_digest} (step "
            f"{engine.servable_step}) — apply the intervening deltas "
            "in export order, or load the newest full base artifact "
            "(docs/CONTINUOUS.md \"Delta chain\")"
        )
    # Load + integrity-check EVERY host array before any device work:
    # a corrupt delta must cost a sha pass, not a full table scatter
    # plus device_puts, before refusal.
    keys = np.load(os.path.join(directory, "delta.keys.npy"))
    sha = hashlib.sha256()
    sha.update(np.ascontiguousarray(keys, np.int64).tobytes())
    table_rows: dict[str, np.ndarray] = {}
    for tname in sorted(engine.state["tables"]):
        if manifest["arrays"].get(f"{tname}.param") is None:
            raise ValueError(
                f"delta {directory} missing rows for table {tname!r}"
            )
        rows = np.load(
            os.path.join(directory, f"delta.{tname}.param.npy")
        )
        sha.update(np.ascontiguousarray(rows, np.float32).tobytes())
        table_rows[tname] = rows
    dense_host: dict[str, np.ndarray] = {}
    for dname in manifest["dense"]:
        host = np.load(os.path.join(directory, f"dense.{dname}.npy"))
        sha.update(np.ascontiguousarray(host).tobytes())
        if dname not in engine.state["dense"]:
            raise ValueError(
                f"delta {directory} carries dense array {dname!r} the "
                "engine does not have — wrong model family"
            )
        dense_host[dname] = host
    if sha.hexdigest() != manifest["content_sha256"]:
        raise ValueError(
            f"delta {directory}: content sha mismatch — the delta "
            "files were corrupted after export; re-export or fall "
            "back to the newest full base"
        )
    new_tables = {}
    for tname, rows in table_rows.items():
        param = engine.state["tables"][tname]["param"]
        if len(keys):
            param = param.at[jnp.asarray(keys, jnp.int32)].set(
                jnp.asarray(rows, param.dtype)
            )
        new_tables[tname] = {"param": param}
    new_dense = {
        dname: jax.device_put(
            host, engine.state["dense"][dname].sharding
        )
        for dname, host in dense_host.items()
    }
    out = engine.clone()
    out.state = {
        "tables": new_tables,
        "dense": new_dense,
        "step": jnp.asarray(manifest["step"], jnp.int32),
    }
    out.servable_step = int(manifest["step"])
    return out
