"""Continuous training (docs/CONTINUOUS.md) — the event-to-servable
loop the batch trainer cannot close.

Ads models decay in hours (the online-advertising framework paper,
arXiv:2201.05500, and Google's ads training/serving stack,
arXiv:2501.10546, both make continuous train→export→swap the core
production loop).  This package closes that loop end to end over the
subsystems the previous PRs landed:

* :mod:`xflow_tpu.stream.follower` — ``ShardFollower`` tails a growing
  packed-v2 shard directory (atomic-rename writers mean presence ==
  complete) behind a durable ``IngestCursor``, so a restarted run
  resumes mid-stream without re-training or skipping shards
  (at-least-once: replay is bounded by one shard).
* :mod:`xflow_tpu.stream.delta` — ``export_delta`` ships only the rows
  touched since the last export as a digest-chained artifact
  (``base_digest`` → ``delta_digest``); ``apply_delta`` folds it onto a
  loaded ``PredictEngine`` in place (param-only, FTRL slots never
  ship).
* :mod:`xflow_tpu.stream.driver` — ``StreamDriver`` wires follower →
  trainer → periodic delta export → ``ReplicaFleet`` staged rollout
  (PR 10's canary health gate), stamping every ingested batch so the
  ``freshness`` metric (newest-event-age at swap commit) is measured,
  not estimated.  ``python -m xflow_tpu.stream run`` is the CLI.
"""

from xflow_tpu.stream.delta import (
    TouchedLedger,
    apply_delta,
    export_delta,
    load_delta_manifest,
)
from xflow_tpu.stream.follower import IngestCursor, ShardFollower

__all__ = [
    "IngestCursor",
    "ShardFollower",
    "TouchedLedger",
    "apply_delta",
    "export_delta",
    "load_delta_manifest",
]
