"""Static-shape sparse primitives: per-unique-key gradient consolidation
and gather/update/scatter row application.

This module is the TPU replacement for the ps-lite Push path.  In the
reference, a worker thread sorts the minibatch's (sid, fid) pairs,
uniques the keys (lr_worker.cc:147-166), pushes per-unique-key summed
gradients, and the server applies the optimizer recurrence per key
inside the request handler (ftrl.h:54-79).  Here the same dataflow runs
inside one XLA program with static shapes:

* ``consolidate`` replaces sort+unique: argsort the M flattened keys,
  mark segment starts, segment-sum gradients.  The output is M slots of
  which only the first U (U = number of unique keys) are real; the rest
  carry an out-of-range sentinel key so downstream scatters drop them.
* ``gather_rows`` / ``scatter_rows`` replace Pull / the server-side
  state mutation: gather optimizer state rows at the unique keys, apply
  the pure update, scatter the new rows back.  Out-of-range sentinel
  scatters are dropped (XLA scatter ``mode=drop``), so padding never
  touches the table.

Padding safety argument: a padded consolidation slot carries g=0 and a
sentinel key.  Its gathered row (clamped by XLA gather semantics) is
updated with g=0 — for FTRL that recomputes w from unchanged (z, n),
which is exactly what the reference server does on a zero-gradient push
(ftrl.h:58-74 runs unconditionally) — and then the write is dropped.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def PAD_SENTINEL_FOR(table_size: int) -> int:
    """Key value used for padding entries: one past the last row, so
    gathers clamp and scatters drop."""
    return table_size


def consolidate(
    keys: jax.Array, grads: jax.Array, table_size: int
) -> tuple[jax.Array, jax.Array]:
    """Sum gradient contributions per unique key, statically shaped.

    Args:
      keys: int32 [M]; padding entries must already carry the sentinel
        ``table_size``.
      grads: float [M, D] per-occurrence gradients (0 for padding).
      table_size: number of real table rows.

    Returns:
      (ukeys [M] int32, gsum [M, D]): slot i holds the i-th unique key in
      sorted order with its summed gradient; unused slots hold the
      sentinel key and g=0.
    """
    order, seg, ukeys = consolidate_plan(keys, table_size)
    # Sentinel inputs (padding) form the last segment(s); their ukey is the
    # sentinel itself, so they stay inert.
    return ukeys, consolidate_apply(grads, order, seg)


def consolidate_plan(
    keys: jax.Array, table_size: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The key-only half of ``consolidate``, computed ONCE per batch and
    shared across a model's tables (they index with the same keys):
    returns (order [M], seg [M], ukeys [M]).  Apply per table with
    ``consolidate_apply``.

    Motivation (docs/PERF.md "Cold consolidation"): zipf batches carry
    heavy duplication even after hot steering — measured 53% duplicate
    cold occurrences at the FM flagship geometry, 90% hot-off — and
    multi-lane (D>1) scatter-add costs ~85-107 ns/slice, so collapsing
    duplicates ahead of the scatter removes over half its slices at the
    price of one shared argsort."""
    m = keys.shape[0]
    order = jnp.argsort(keys)
    sk = jnp.take(keys, order)
    is_start = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sk[1:] != sk[:-1]]
    )
    seg = jnp.cumsum(is_start) - 1
    sentinel = jnp.int32(table_size)
    ukeys = jnp.full((m,), sentinel, dtype=jnp.int32).at[seg].set(
        sk, mode="drop"
    )
    return order, seg, ukeys


def consolidate_apply(
    grads: jax.Array, order: jax.Array, seg: jax.Array
) -> jax.Array:
    """Per-table half of the shared consolidation: permute [M, D]
    gradients into key-sorted order and segment-sum; slot i of the
    result pairs with ``ukeys[i]`` from the plan (sentinel slots get
    g=0 because padding gradients are 0 and duplicates collapse into
    their segment head)."""
    sg = jnp.take(grads, order, axis=0)
    return jax.ops.segment_sum(sg, seg, num_segments=order.shape[0])


def consolidate_indexed(
    grads: jax.Array, uidx: jax.Array, num_slots: int
) -> jax.Array:
    """Consolidation with the plan computed on the HOST: sum [M, D]
    per-occurrence gradients into ``num_slots`` unique-key slots via a
    precomputed u32 index (io/compact.py's dictionary codes, shipped
    on the wire).  Entries carrying ``uidx == num_slots`` (padding /
    tail-tier occurrences) are dropped.

    This is ``consolidate_plan`` + ``consolidate_apply`` minus the
    device argsort — the dedup moved to the host, where it is free
    relative to the link (docs/PERF.md "Wire format and compaction").
    Slot i pairs with the wire's dictionary key i.
    """
    return jax.ops.segment_sum(
        grads, uidx, num_segments=num_slots + 1
    )[:num_slots]


def gather_rows(table: jax.Array, ukeys: jax.Array) -> jax.Array:
    """Gather [U, D] state rows; sentinel keys clamp to the last row
    (their updates are dropped on scatter, see module docstring)."""
    return table.at[ukeys].get(mode="clip")


def scatter_rows(table: jax.Array, ukeys: jax.Array, rows: jax.Array) -> jax.Array:
    """Write updated rows back; sentinel (out-of-range) keys are dropped."""
    return table.at[ukeys].set(rows, mode="drop")
