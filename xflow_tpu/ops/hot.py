"""Two-level one-hot MXU gather/scatter for the frequency-hot table head.

XLA TPU gather/scatter cost is per *slice* (~8-14 ns of DMA descriptor
issue each, independent of slice width — docs/PERF.md), so a step over
M = B*nnz feature occurrences pays ~18 ns/occurrence of round-trip DMA
no matter what.  CTR key distributions are zipfian: after the frequency
remap (io/freq.py) the head of the distribution lives in table rows
[0, H).  For those occurrences we replace per-slice DMA with two-level
one-hot matmuls that ride the MXU:

    key = hi * h2 + lo            (H = h1 * h2)
    gather:  rows = ((onehot_hi @ W) . reshape  *  onehot_lo) sum over lo
    scatter: W'   = onehot_hi^T @ (g * onehot_lo)

Traffic is M*(h1 + h2*D) one-hot elements instead of M DMA descriptors;
measured ~2x (f32, exact) to ~4x (bf16) over the DMA path for the hot
fraction on v5e (scripts/probe_hot2.py; docs/PERF.md "The win").

One-hot intermediates are built in chunks under ``lax.scan`` so the
[C, h2*D] temporaries stay within a few MiB regardless of M or D.

Numerics: with ``dtype=float32`` the gather is *exact* (each one-hot row
selects a single W element; no accumulation), and the scatter differs
from ``.at[].add`` only in summation order.  ``bfloat16`` trades W/g
mantissa for ~2x more speed; the default is float32.

Sentinel behavior: any key outside [0, H) produces an all-zero onehot_hi
row, so out-of-range/padding keys gather a zero row and scatter nothing
— mirroring the drop/clip semantics of ops/sparse.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def hot_factors(hot_size: int) -> tuple[int, int]:
    """Split H = h1 * h2 with h1 >= h2, both powers of two.

    h1 is the matmul contraction width for level 1 (oh_hi @ W) and h2
    the lane-select width for level 2; near-square minimizes
    h1 + h2*D traffic per occurrence.
    """
    log2 = hot_size.bit_length() - 1
    if hot_size != 1 << log2:
        raise ValueError(f"hot_size must be a power of two, got {hot_size}")
    h1 = 1 << ((log2 + 1) // 2)
    return h1, hot_size // h1


def _chunk(h1: int, h2: int, d: int, m: int) -> int:
    """Rows per scan chunk: bound the [C, max(h1, h2*D)] temporaries to
    ~2^21 f32 elements (8 MiB), and never pad a small M (e.g. an online-
    inference batch) up to a huge chunk."""
    width = max(h1, h2 * d)
    c = max(256, (1 << 21) // width)
    c = 1 << (c.bit_length() - 1)  # round down to a power of two
    m_pow2 = 1 << max(m - 1, 1).bit_length()  # round M up to a power of two
    return min(c, m_pow2)


def _pad_to(x: jax.Array, m_pad: int, fill) -> jax.Array:
    m = x.shape[0]
    if m_pad == m:
        return x
    pad_shape = (m_pad - m,) + x.shape[1:]
    return jnp.concatenate([x, jnp.full(pad_shape, fill, x.dtype)])


def hot_gather(
    w_hot: jax.Array,
    keys: jax.Array,
    *,
    dtype=jnp.float32,
    impl: str = "mxu",
) -> jax.Array:
    """Gather rows of the hot table via two-level one-hot matmuls.

    Args:
      w_hot: [H, D] hot-table rows (H a power of two).
      keys: int32 [M]; entries outside [0, H) yield zero rows.
      dtype: matmul input dtype (float32 exact, bfloat16 fast).
      impl: "mxu" — the one-hot matmul path (the TPU win this module
        exists for); "seg" — a plain clip-gather with zero fill.  Same
        contract, exact in float32 either way; "seg" is the CPU-fast
        form (one-hot matmuls are an MXU trick — measured 3.3x slower
        than the gather on the CPU backend, docs/PERF.md "Wire format
        and compaction") and ignores ``dtype`` (always exact).
        TrainStep picks per platform via Config.hot_impl.

    Returns: [M, D] gathered rows, float32.
    """
    h, d = w_hot.shape
    if impl == "seg":
        rows = w_hot[jnp.clip(keys, 0, h - 1)]
        ok = (keys >= 0) & (keys < h)
        return jnp.where(ok[:, None], rows, 0.0).astype(jnp.float32)
    h1, h2 = hot_factors(h)
    m = keys.shape[0]
    c = _chunk(h1, h2, d, m)
    m_pad = ((m + c - 1) // c) * c
    kp = _pad_to(keys, m_pad, h)  # sentinel: all-zero one-hot
    wr = w_hot.reshape(h1, h2 * d).astype(dtype)
    ar1 = jnp.arange(h1, dtype=kp.dtype)
    ar2 = jnp.arange(h2, dtype=kp.dtype)

    def body(_, k):
        hi = k // h2
        lo = k % h2
        oh_hi = (hi[:, None] == ar1[None, :]).astype(dtype)  # [C, h1]
        rows = jnp.dot(
            oh_hi, wr, preferred_element_type=jnp.float32
        ).reshape(c, h2, d)
        oh_lo = (lo[:, None] == ar2[None, :]).astype(jnp.float32)  # [C, h2]
        return None, jnp.einsum("chd,ch->cd", rows, oh_lo)

    _, out = jax.lax.scan(body, None, kp.reshape(-1, c))
    return out.reshape(m_pad, d)[:m]


def hot_scatter(
    keys: jax.Array,
    grads: jax.Array,
    hot_size: int,
    *,
    dtype=jnp.float32,
    impl: str = "mxu",
) -> jax.Array:
    """Sum per-occurrence gradients into a dense [H, D] buffer via
    two-level one-hot matmuls (the MXU replacement for
    ``zeros([H, D]).at[keys].add(grads)``).

    Args:
      keys: int32 [M]; entries outside [0, H) are dropped.
      grads: float [M, D].
      hot_size: H (power of two).
      dtype: matmul input dtype for the [h1, M]@[M, h2*D] contraction.
      impl: "mxu" (one-hot matmuls) or "seg" (segment-sum into the
        [H, D] buffer — the CPU-fast form; same sums, summation order
        differs like the MXU path differs from ``.at[].add``).

    Returns: [H, D] float32 gradient sums.
    """
    m, d = grads.shape
    if impl == "seg":
        seg = jnp.where(
            (keys >= 0) & (keys < hot_size), keys, jnp.int32(hot_size)
        )
        return jax.ops.segment_sum(
            grads.astype(jnp.float32), seg, num_segments=hot_size + 1
        )[:hot_size]
    h1, h2 = hot_factors(hot_size)
    c = _chunk(h1, h2, d, m)
    m_pad = ((m + c - 1) // c) * c
    kp = _pad_to(keys, m_pad, hot_size)
    gp = _pad_to(grads, m_pad, 0)
    ar1 = jnp.arange(h1, dtype=kp.dtype)
    ar2 = jnp.arange(h2, dtype=kp.dtype)

    def body(acc, xs):
        k, g = xs
        hi = k // h2
        lo = k % h2
        oh_hi = (hi[:, None] == ar1[None, :]).astype(dtype)  # [C, h1]
        oh_lo = (lo[:, None] == ar2[None, :]).astype(g.dtype)  # [C, h2]
        glo = (g[:, :, None] * oh_lo[:, None, :]).reshape(c, d * h2)
        # accumulate in f32 regardless of input dtype
        acc = acc + jnp.dot(
            oh_hi.T, glo.astype(dtype), preferred_element_type=jnp.float32
        )
        return acc, None

    acc0 = jnp.zeros((h1, d * h2), jnp.float32)
    acc, _ = jax.lax.scan(
        body, acc0, (kp.reshape(-1, c), gp.reshape(-1, c, d))
    )
    # glo flattened [C, d, h2] -> acc is [h1, (d, h2)]; reorder to
    # [h1, h2, d] so row hi*h2+lo lands at table row `key`.
    return acc.reshape(h1, d, h2).transpose(0, 2, 1).reshape(h1 * h2, d)
