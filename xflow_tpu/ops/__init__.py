from xflow_tpu.ops.sparse import consolidate, gather_rows, scatter_rows, PAD_SENTINEL_FOR

__all__ = ["consolidate", "gather_rows", "scatter_rows", "PAD_SENTINEL_FOR"]
