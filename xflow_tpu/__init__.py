"""xflow_tpu — a TPU-native sparse CTR-prediction training framework.

A ground-up JAX/XLA re-design of the capabilities of the xflow
parameter-server trainer (sparse Logistic Regression, Factorization
Machine, and Multi-View Machine with server-side FTRL-proximal / SGD
updates over ps-lite; see reference src/model, src/optimizer).

Design stance (TPU-first, not a port):

* The parameter server disappears.  The hashed feature weight table —
  and the FTRL state (n, z) next to it — are ``jax.Array``s row-sharded
  across a ``jax.sharding.Mesh``.  What the reference did with
  ``KVWorker::Pull`` becomes an in-step gather of touched rows; what it
  did with ``KVWorker::Push`` + a server-side handler becomes a
  consolidate-per-unique-key + gather/update/scatter inside the same
  pjit'd step (reference: ps-lite Push/Pull at lr_worker.cc:170,175 and
  the FTRL handler at ftrl.h:38-85).
* Workers' async Hogwild interleaving is intentionally replaced by
  synchronous SPMD data parallelism; parity is judged on convergence
  (logloss/AUC), not update ordering.
* Everything inside the step is static-shape: minibatches are padded
  COO (keys / slots / vals / mask), per-key gradient consolidation uses
  a sort + segment-sum trick instead of dynamic ``unique``.
"""

from xflow_tpu.config import Config
from xflow_tpu.api import XFlow

__version__ = "0.1.0"

__all__ = ["Config", "XFlow", "__version__"]
