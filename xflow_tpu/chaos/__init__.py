"""Chaos fabric: seeded deterministic fault injection + the shared
self-healing primitives (docs/ROBUSTNESS.md).

* :func:`failpoint` — named injection sites threaded through
  io/loader.py, store/, utils/checkpoint.py, and serve/; zero overhead
  disarmed, every fire logged as a ``chaos`` JSONL row.
* :func:`arm` / :func:`disarm` — arm from a chaos-spec string
  (``Config.chaos_spec`` or the ``XFLOW_CHAOS`` env var).
* :func:`retry_call` / :func:`emit_health` — the retry-with-backoff and
  loud-recovery helpers every healed layer shares.
* ``scripts/check_chaos.py`` — the tier-1 gate that drives a seeded
  fault schedule through train→checkpoint→kill→auto-resume→export and
  a loadgen-driven fleet and demands output parity + full fault
  accounting.
"""

from xflow_tpu.chaos.heal import emit_health, retry_call
from xflow_tpu.chaos.registry import (
    ChaosError,
    ChaosRegistry,
    arm,
    arm_from_env,
    armed,
    attach_logger,
    detach_logger,
    disarm,
    failpoint,
    fired,
    parse_spec,
)

__all__ = [
    "ChaosError",
    "ChaosRegistry",
    "arm",
    "arm_from_env",
    "armed",
    "attach_logger",
    "detach_logger",
    "disarm",
    "emit_health",
    "failpoint",
    "fired",
    "parse_spec",
    "retry_call",
]
