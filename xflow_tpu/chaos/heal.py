"""Self-healing primitives shared by the layers the failpoints thread
through: bounded retry-with-exponential-backoff and loud ``health``-row
reporting (docs/ROBUSTNESS.md "Policies").

The design rule for every healer in this package: **recovery is never
silent**.  A retried read, a quarantined record, a restarted worker,
an evicted replica each leave a ``health`` JSONL row, so `obs doctor`
can tell a fault storm from an isolated absorbed fault — and analysis
rule XF015 enforces the same discipline on every worker-context
exception handler in the tree.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from xflow_tpu.chaos.registry import ChaosError
from xflow_tpu.obs import NULL_OBS

# exponential backoff is capped so a misconfigured retry count can
# never park a hot path for more than ~a second per attempt
BACKOFF_CAP_S = 1.0


def emit_health(
    obs,
    cause: str,
    channel: str,
    detail: str,
    silence_seconds: float = 0.0,
    threshold_seconds: float = 0.0,
) -> None:
    """Best-effort ``health`` row through the obs bundle (the loader/
    store/serve healers all report this way): ``obs.metrics_logger``
    when the run has a metrics stream, falling back to the flight
    recorder's logger; no logger anywhere = skipped — the healing
    itself never depends on observability being on."""
    flight = getattr(obs, "flight", None)
    logger = getattr(obs, "metrics_logger", None)
    if logger is None:
        logger = getattr(flight, "metrics_logger", None)
    if logger is None:
        return
    from xflow_tpu.obs.schema import health_row

    logger.log("health", health_row(
        cause=cause,
        channel=channel,
        silence_seconds=silence_seconds,
        threshold_seconds=threshold_seconds,
        detail=detail,
        channels=(
            flight.snapshot()["channels"] if flight is not None else {}
        ),
    ))


def retry_call(
    fn: Callable[[], Any],
    *,
    attempts: int,
    backoff_s: float,
    channel: str,
    site: str,
    obs=NULL_OBS,
    retry_on: tuple = (OSError, ChaosError),
) -> Any:
    """Call ``fn`` with up to ``attempts`` retries on ``retry_on``
    (exponential backoff, capped at :data:`BACKOFF_CAP_S`).  A call
    that eventually succeeds after failures books a
    ``<channel>.retries`` counter per retry and ONE
    ``recovered:io_retry`` health row; exhausted retries re-raise the
    last error for the caller's quarantine/abort policy."""
    failures = 0
    while True:
        try:
            out = fn()
        except retry_on:
            failures += 1
            if failures > attempts:
                raise
            obs.counter(f"{channel}.retries")
            time.sleep(min(backoff_s * 2.0 ** (failures - 1), BACKOFF_CAP_S))
            continue
        if failures:
            emit_health(
                obs,
                cause="recovered:io_retry",
                channel=channel,
                detail=f"{site}: healed after {failures} retried "
                f"failure(s)",
            )
        return out
