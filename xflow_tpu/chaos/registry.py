"""Seeded deterministic failpoint registry — the injection half of the
chaos fabric (docs/ROBUSTNESS.md).

Production ads stacks gate releases on fault tolerance, not just
throughput (the terabyte-scale online-advertising framework,
arXiv:2201.05500, and Google's ads serving tier, arXiv:2501.10546).
The failure paths they exercise — corrupt shard records, transient
reads, half-written checkpoints, dead workers, sick replicas — are
exactly the paths that rot silently in a repo whose tests only ever
run the happy path.  This module makes those paths *drivable*: named
``failpoint(site)`` call sites threaded through the fragile layers
(io/loader.py, store/, utils/checkpoint.py, serve/) raise an injected
:class:`ChaosError` on a seeded, fully deterministic schedule, so the
same spec + seed reproduces the same fault sequence on every run —
``scripts/check_chaos.py`` gates on it in tier-1.

Arming (``Config.chaos_spec`` or the ``XFLOW_CHAOS`` env var)::

    seed=7;loader.read_block:nth=2;serve.replica_score:p=1,times=4

Grammar: an optional ``seed=<int>`` then ``;``-separated site rules,
each ``<site>:<arg>(,<arg>)*`` with args

* ``nth=<k>``   — fire on exactly the k-th hit of the site;
* ``every=<k>`` — fire when the hit count is a multiple of k;
* ``p=<f>``     — fire with probability f per hit, decided by a
  splitmix64 hash of (seed, site, hit) — no RNG stream, so concurrent
  threads hitting other sites never perturb the schedule;
* ``times=<n>`` — cap total fires at n (combines with any of the
  above; a rule with only ``times`` fires on every hit until the cap).

Disarmed (the default), ``failpoint()`` is one module-global load and
a ``None`` compare — zero allocation, zero locking, no logging.  Armed,
every FIRE logs a schema-valid ``chaos`` JSONL row (obs/schema.py)
through the attached metrics logger before raising, so the metrics
stream is the audit trail the chaos gate reconciles against: every
injected fault must be accounted for by a matching ``chaos`` row and a
``health`` row from the layer that healed it.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass

_M64 = (1 << 64) - 1
_SITE_RE = re.compile(r"^[a-z0-9_.]+$")


class ChaosError(RuntimeError):
    """An injected fault.  Deliberately its own type (NOT OSError):
    self-healing layers must name it in their retry/except lists, so a
    handler broad enough to swallow injected faults by accident is a
    handler broad enough to swallow real ones — which is what analysis
    rule XF015 exists to catch."""

    def __init__(self, site: str, hit: int):
        super().__init__(
            f"chaos: injected fault at failpoint {site!r} (hit {hit})"
        )
        self.site = site
        self.hit = hit


def _mix64(x: int) -> int:
    """splitmix64 finalizer over python ints (the deterministic
    per-(seed, site, hit) coin for ``p=`` rules)."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _tag(s: str) -> int:
    """FNV-1a of the site name — independent fire schedules per site
    under one seed."""
    h = 0xCBF29CE484222325
    for b in s.encode():
        h = ((h ^ b) * 0x100000001B3) & _M64
    return h


@dataclass
class _SiteRule:
    p: float | None = None
    nth: int | None = None
    every: int | None = None
    times: int | None = None
    hits: int = 0
    fires: int = 0


def parse_spec(spec: str) -> tuple[int, dict[str, _SiteRule]]:
    """(seed, {site: rule}) for a chaos-spec string; raises ValueError
    with the grammar on any malformed input (Config.__post_init__
    validates specs through here, so a bad spec fails at config time,
    not mid-run)."""
    seed = 0
    rules: dict[str, _SiteRule] = {}
    parts = [p.strip() for p in spec.split(";") if p.strip()]
    if not parts:
        raise ValueError(
            "empty chaos spec (grammar: [seed=<int>;]<site>:<arg>,...)"
        )
    if parts[0].startswith("seed="):
        seed = int(parts[0][len("seed="):])
        parts = parts[1:]
    if not parts:
        raise ValueError("chaos spec has a seed but no site rules")
    for part in parts:
        site, sep, argstr = part.partition(":")
        site = site.strip()
        if not sep or not _SITE_RE.match(site):
            raise ValueError(
                f"bad chaos site rule {part!r} (want "
                "<site>:<arg>(,<arg>)* with site matching [a-z0-9_.]+)"
            )
        if site in rules:
            raise ValueError(f"duplicate chaos site {site!r}")
        rule = _SiteRule()
        for arg in argstr.split(","):
            key, sep, val = arg.strip().partition("=")
            if not sep:
                raise ValueError(f"bad chaos arg {arg!r} (want key=value)")
            if key == "p":
                rule.p = float(val)
                if not 0.0 < rule.p <= 1.0:
                    raise ValueError(f"chaos p={rule.p} not in (0, 1]")
            elif key == "nth":
                rule.nth = int(val)
                if rule.nth < 1:
                    raise ValueError("chaos nth must be >= 1")
            elif key == "every":
                rule.every = int(val)
                if rule.every < 1:
                    raise ValueError("chaos every must be >= 1")
            elif key == "times":
                rule.times = int(val)
                if rule.times < 1:
                    raise ValueError("chaos times must be >= 1")
            else:
                raise ValueError(
                    f"unknown chaos arg {key!r} (want p/nth/every/times)"
                )
        if sum(x is not None for x in (rule.p, rule.nth, rule.every)) > 1:
            raise ValueError(
                f"chaos site {site!r}: p/nth/every are mutually exclusive"
            )
        rules[site] = rule
    return seed, rules


class ChaosRegistry:
    """One armed fault schedule.  All mutable state under ``_lock``
    (hit counters are shared across every thread that crosses a
    failpoint); the ``chaos`` row is logged OUTSIDE the lock."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed, self.rules = parse_spec(spec)
        self._lock = threading.Lock()
        self._logger = None
        self._dropped_rows = 0

    def attach_logger(self, logger) -> None:
        with self._lock:
            self._logger = logger

    def detach_logger(self, logger) -> None:
        """Detach iff ``logger`` is the attached one (a Trainer closing
        its MetricsLogger must not detach a logger someone else
        attached after it)."""
        with self._lock:
            if self._logger is logger:
                self._logger = None

    def _should_fire(self, rule: _SiteRule, site: str, hit: int) -> bool:
        if rule.times is not None and rule.fires >= rule.times:
            return False
        if rule.nth is not None:
            return hit == rule.nth
        if rule.every is not None:
            return hit % rule.every == 0
        if rule.p is not None:
            coin = (_mix64(self.seed ^ _tag(site) ^ hit) >> 11) * 2.0**-53
            return coin < rule.p
        return True

    def hit(self, site: str) -> None:
        """One crossing of ``site``: count it and raise ChaosError when
        the rule says to fire (logging the ``chaos`` row first)."""
        rule = self.rules.get(site)
        if rule is None:
            return
        with self._lock:
            rule.hits += 1
            hit = rule.hits
            fire = self._should_fire(rule, site, hit)
            if fire:
                rule.fires += 1
                fires = rule.fires
            logger = self._logger
        if not fire:
            return
        if logger is not None:
            try:
                logger.log("chaos", {
                    "site": site,
                    "hit": hit,
                    "fires": fires,
                    "detail": f"seed={self.seed}",
                })
            except Exception:
                # the audit row must never mask the injected fault
                # itself (a closed logger during teardown is normal);
                # the drop is still countable  xf: ignore[XF015]
                with self._lock:
                    self._dropped_rows += 1
        raise ChaosError(site, hit)

    def fired(self) -> dict[str, int]:
        """{site: total fires} — the in-memory half the chaos gate
        reconciles against the ``chaos`` JSONL rows."""
        with self._lock:
            return {
                site: rule.fires
                for site, rule in self.rules.items()
                if rule.fires
            }

    def hits(self) -> dict[str, int]:
        with self._lock:
            return {site: rule.hits for site, rule in self.rules.items()}

    def dropped_rows(self) -> int:
        """Chaos rows that failed to log (raising/closed logger) — the
        gate names this count when fires and rows disagree, so a
        lossy audit trail is distinguishable from a real accounting
        bug."""
        with self._lock:
            return self._dropped_rows


_REG: ChaosRegistry | None = None
_ARM_LOCK = threading.Lock()


def arm(spec: str) -> ChaosRegistry:
    """Arm the process-wide registry from a chaos spec (replacing any
    previous one — counters restart).  Trainer arms from
    ``Config.chaos_spec`` / ``XFLOW_CHAOS`` at construction."""
    global _REG
    reg = ChaosRegistry(spec)
    with _ARM_LOCK:
        _REG = reg
    return reg


def arm_from_env() -> ChaosRegistry | None:
    """Arm from the XFLOW_CHAOS env var if set (else no-op, keeping
    whatever is armed).  Trainer and the serve CLI both call this, so
    the env var reaches every entry point a chaos run drives."""
    import os

    spec = os.environ.get("XFLOW_CHAOS", "")
    return arm(spec) if spec else None


def disarm() -> None:
    global _REG
    with _ARM_LOCK:
        _REG = None


def armed() -> ChaosRegistry | None:
    return _REG


def failpoint(site: str) -> None:
    """Named fault-injection site.  Disarmed: one global load + None
    compare (the zero-overhead contract — sites sit on block/record/
    batch granularity paths, never per-example).  Armed: count the hit
    and raise :class:`ChaosError` when the site's rule fires."""
    reg = _REG
    if reg is not None:
        reg.hit(site)


def attach_logger(logger) -> None:
    reg = _REG
    if reg is not None:
        reg.attach_logger(logger)


def detach_logger(logger) -> None:
    reg = _REG
    if reg is not None:
        reg.detach_logger(logger)


def fired() -> dict[str, int]:
    reg = _REG
    return reg.fired() if reg is not None else {}
