"""Run configuration.

The reference keeps every hyperparameter as a compile-time global
(ftrl.h:15-20 ``alpha/beta/lambda1/lambda2/w_dim/v_dim``, sgd.h:16
``learning_rate``, lr_worker.h:68 ``block_size``) plus positional argv
(main.cc:27-45) and DMLC_* env vars (scripts/local.sh:8-19).  Here the
whole surface is one dataclass, constructible from CLI flags or JSON.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any


@dataclasses.dataclass
class Config:
    # -- model selection (reference: main.cc:27-45, argv[3] '0'/'1'/'2';
    # everything past lr/fm/mvm is a capability extension).  Valid
    # names come from the model registry (models/__init__.py) — a new
    # family registers there once and is config-valid everywhere.
    model: str = "lr"  # models.model_names()

    # -- data (reference: argv[1]/argv[2] shard prefixes, lr_worker.cc:210) --
    train_path: str = ""
    test_path: str = ""
    epochs: int = 60  # reference default: lr_worker.h:63
    # Text block size in MiB fed to the streaming loader per pass
    # (reference: lr_worker.h:68 block_size=2 → 2 MiB at lr_worker.cc:184;
    # predict uses 4 MiB, lr_worker.cc:80).
    block_mib: int = 2
    # Hash mode discards the value field — features are implicitly binary
    # (reference loader load_minibatch_hash_data_fread,
    # load_data_from_disk.cc:151 hashes the fid token and never stores val).
    # With hash_mode=False fids are parsed as integers and vals are kept
    # (reference loaders load_all_data/load_minibatch_data,
    # load_data_from_disk.cc:11-57).
    hash_mode: bool = True

    # -- feature space --
    # log2 of the hashed weight-table row count.  The reference's table is
    # an unbounded unordered_map on each server (ftrl.h:84,151); on TPU the
    # table is a dense HBM-resident array, so the hash space is explicit.
    # North-star target is 2^28 rows pod-sharded (BASELINE.md).
    table_size_log2: int = 22
    # Latent factor count for FM/MVM (reference: ftrl.h:16 v_dim=10).
    v_dim: int = 10
    # FFM per-field latent dim (its v table is max_fields * ffm_v_dim wide).
    ffm_v_dim: int = 4
    # Wide&deep / two_tower / dcn embedding dim and MLP hidden width.
    emb_dim: int = 8
    hidden_dim: int = 64
    # two_tower (models/two_tower.py): fields < tower_split_field are
    # user-side, the rest item-side; tower_dim is each tower's output
    # (= the serve-time item-index row width, serve/artifact.py).
    tower_split_field: int = 16
    tower_dim: int = 16
    # dcn (models/dcn.py): explicit cross-network depth.
    cross_layers: int = 2
    # Static padded features-per-sample inside the jit step.  Samples with
    # more features than this are truncated (reference has no limit —
    # features-per-sample is whatever the text line holds).
    max_nnz: int = 64
    # Static padded field (fgid/slot) count for MVM's per-field sums
    # (reference sizes slot arrays from the per-sample max fgid,
    # mvm_worker.cc:225-243).
    max_fields: int = 32

    # -- batching --
    # Examples per device step.  The reference's "minibatch" is whatever a
    # 2 MiB text block parses to; on TPU the batch must be static.
    batch_size: int = 1024

    # -- optimizer (reference: ftrl.h:15-20, sgd.h:16) --
    optimizer: str = "ftrl"  # {"ftrl", "sgd"}
    alpha: float = 5e-2
    beta: float = 1.0
    lambda1: float = 5e-5
    lambda2: float = 10.0
    sgd_lr: float = 0.001
    # Lazy server-side init of latent factors is N(0,1)*1e-2 on first touch
    # (ftrl.h:114-120); we pre-initialize the whole v table with the same
    # distribution, which is numerically equivalent (untouched rows never
    # participate; see optim/ftrl.py docstring).
    v_init_scale: float = 1e-2
    seed: int = 0

    # -- parallelism --
    # Devices in the 1-D mesh ('data' axis).  0 = use all available.
    num_devices: int = 0

    # -- observability (SURVEY §5: reference has stdout only) --
    # JSONL file receiving structured records (schema: obs/schema.py,
    # docs/OBSERVABILITY.md): run_start header, per-epoch phase-timed
    # train_epoch rows, eval, per-shard loader throughput, device
    # memory.  Setting this also enables the pipeline-health metrics
    # registry (per-phase seconds, stall accounting, step-time
    # percentiles).  Summarize with `python -m xflow_tpu.obs summarize`.
    metrics_out: str = ""
    # Capture a jax.profiler trace (viewable in TensorBoard/Perfetto) of
    # profile_steps training steps starting at step profile_start_step.
    profile_dir: str = ""
    profile_steps: int = 5
    profile_start_step: int = 10
    # Host-side span tracer (obs/trace.py): Chrome trace-event JSON
    # written here on close ("" = off).  Complements profile_dir — the
    # XLA profile shows device internals for a few steps; these spans
    # show the host loop (parse/pack/h2d/dispatch/stall) for the whole
    # run.  Multi-host appends "-r<rank>".  Open in ui.perfetto.dev.
    obs_trace_out: str = ""
    # Span ring-buffer capacity: only the newest N spans are kept, so
    # long runs cannot grow host memory.
    obs_trace_capacity: int = 65536
    # Emit a per-epoch device_mem JSONL row (jax.local_devices()
    # memory_stats) when metrics_out is set.
    obs_device_memory: bool = True
    # Flight recorder (obs/flight.py): crash/hang forensics dump path
    # ("" = off).  The recorder itself is an always-on bounded ring of
    # recent state (phase transitions, batch shapes, checkpoint steps,
    # heartbeats); on unhandled exception, preemption, or watchdog trip
    # the whole record — plus per-thread stacks, the live metrics
    # snapshot, and the span-trace tail — is written here atomically.
    # Multi-host appends "-r<rank>".  Read it with `python -m
    # xflow_tpu.obs doctor RUN.jsonl --flight DUMP`.
    obs_flight_out: str = ""
    # Flight-recorder event-ring capacity (newest N notes kept).
    obs_flight_events: int = 256
    # Stall watchdog (obs/watchdog.py): a monitor thread fed by the
    # hot paths' heartbeats that classifies silence into input
    # starvation / device hang / serve queue stall, emits `health`
    # JSONL rows + instant trace events, and escalates to a flight
    # dump when the silence persists (2x threshold).
    obs_watchdog: bool = False
    # Per-cause silence thresholds, seconds.  input: the main loop has
    # been waiting on the input iterator; device: it has been inside
    # dispatch/h2d/device_block/checkpoint; serve: the MicroBatcher
    # has pending requests but finished no batch.
    obs_watchdog_input_s: float = 30.0
    obs_watchdog_device_s: float = 120.0
    obs_watchdog_serve_s: float = 10.0
    # Request-scoped tracing (obs/reqtrace.py, docs/OBSERVABILITY.md
    # "Tracing a request"): head-sampling keep fraction in [0, 1] for
    # healthy requests.  Errors, sheds, and the window's slowest-k
    # exemplars are ALWAYS kept regardless of this rate; 0.01 keeps
    # 1% of the rest.  The serve CLI's --reqtrace-sample attaches the
    # sink; this is the default rate it samples at.
    obs_reqtrace_sample: float = 0.01
    # Monitor poll interval (0 = auto: a quarter of the tightest
    # threshold, so a stall is classified within its threshold).
    obs_watchdog_poll_s: float = 0.0
    # Lock-order sanitizer (analysis/sanitizer.py): instrument the
    # obs-stack locks (MetricsLogger/FlightRecorder/Watchdog/registry)
    # so actual acquisition orders are recorded and cross-checkable
    # against the static XF007 graph.  Debug/stress tooling — off in
    # production (zero overhead when off: plain threading.Lock stays).
    # The XFLOW_LOCK_SANITIZER env var arms the same machinery.
    obs_lock_sanitizer: bool = False
    # Standalone Prometheus-style exposition (obs/export.py): serve
    # `GET /metrics` on 127.0.0.1:<port> from the live metrics
    # registry for training/stream runs, which have no HTTP surface of
    # their own (the serving tier exposes /metrics on its own port
    # instead).  0 = off.  The exporter thread is owned and reaped by
    # Trainer.close().  Multi-host runs add the rank to the port so N
    # trainers on one box never collide.
    obs_export_port: int = 0
    # Host resource sampler (obs/export.py): emit a `resource` JSONL
    # row (RSS, CPU seconds, threads, open fds, GC collections) every
    # N seconds while training, plus one at start and one at close.
    # 0 = off.  Requires metrics_out (the rows need somewhere to go).
    obs_resource_every_s: float = 0.0

    # -- eval / artifacts --
    # Prediction dump target.  With pred_style="single" (default) rank 0
    # writes one file of "(label, pctr)" lines at pred_out —
    # information-equivalent to the reference.  With
    # pred_style="per_block", pred_out is a DIRECTORY and every host
    # writes pred_<rank>_<block>.txt per eval batch, the reference's
    # exact artifact granularity (lr_worker.cc:74-78).
    pred_out: str = ""
    pred_style: str = "single"  # {"single", "per_block"}
    # Evaluate on test_path every N epochs during training (0 = only the
    # final eval after all epochs, the reference's behavior —
    # lr_worker.cc:212-215).  Convergence curves (BASELINE.md) use this.
    eval_every_epochs: int = 0
    # Checkpoint directory ("" = checkpointing off). Capability gap filled:
    # the reference has no model save/load at all (SURVEY §5).
    checkpoint_dir: str = ""
    checkpoint_every_steps: int = 0  # 0 = only at epoch ends
    # Keep only the newest K ckpt-* dirs (0 = keep all).  At north-star
    # scale a single FM checkpoint is ~13 GB (2^28 rows x (1+10) cols x
    # 3 arrays x 4 B), so unbounded accumulation fills the disk fast.
    # Default 2: the committed generation plus its predecessor, so a
    # kill mid-commit (the generation a crash-atomic save was
    # replacing) always leaves a complete fallback for
    # `--resume auto` (utils/checkpoint.py::latest_complete).
    checkpoint_keep: int = 2

    # -- robustness (xflow_tpu/chaos/; docs/ROBUSTNESS.md) --
    # Seeded failpoint schedule, e.g.
    # "seed=7;loader.read_block:nth=2;serve.replica_score:p=1,times=4"
    # ("" = disarmed, zero overhead).  The XFLOW_CHAOS env var arms the
    # same machinery.  Every fire logs a `chaos` JSONL row; the tier-1
    # chaos gate (scripts/check_chaos.py) reconciles rows against the
    # schedule and demands model-output parity with the fault-free run.
    chaos_spec: str = ""
    # Bounded retry for transient shard-read/parse and cold-store
    # fetch/write failures (exponential backoff from
    # io_retry_backoff_s, capped at 1s).  A block that still fails is
    # QUARANTINED: skipped with a `health` row, not fatal.
    io_retries: int = 2
    io_retry_backoff_s: float = 0.05
    # Quarantine budget: abort the shard stream (health row
    # `quarantine_budget_exceeded`) once quarantined blocks/records
    # exceed max(1, ceil(frac * blocks_seen)) — one bad block is
    # survivable, a corrupt stream is not trainable.
    max_quarantined_frac: float = 0.05

    # -- serve tier timeout discipline (serve/server.py; analysis rule
    # XF017: no blocking wait in the serve path may be unbounded) --
    # How long a request handler waits on its scoring futures before
    # answering 504 (admitted-but-slow is a gateway timeout, not a
    # server bug — serve/server.py::_do_post).
    serve_score_timeout_s: float = 60.0
    # Per-connection socket timeout on handler reads/writes: a client
    # that stops mid-request (half-open TCP, stalled upload) releases
    # its handler thread after this long instead of pinning it forever.
    serve_socket_timeout_s: float = 30.0
    # Client-side HTTP timeout for the loadgen's remote mode
    # (serve/loadgen.py::HttpTarget → http.client.HTTPConnection
    # timeout=): bounds connect + each socket op against a wedged tier.
    serve_client_timeout_s: float = 30.0
    # -- QoS-classed admission (serve/fleet.py QOS_CLASSES) --
    # Each request carries a class (bidding/normal/best_effort — the
    # XFB1 frame byte, the X-XFlow-QoS header, or the fleet default).
    # All classes share one queue; lower classes see SCALED admission
    # budgets, so under pressure best_effort sheds first and bidding
    # last.  These fractions scale the fleet's deadline/depth budgets
    # per class (bidding always gets the full budget).
    serve_qos_normal_frac: float = 0.75
    serve_qos_best_effort_frac: float = 0.45
    # Hot-key score cache capacity in entries (serve/scache.py);
    # 0 disables the cache.  Keyed by (servable_digest, row bytes),
    # evicted atomically on rollout commit/delta — see SERVING.md.
    serve_cache_capacity: int = 0
    # Client-side pipelining depth per connection for the binary
    # transport (serve/loadgen.py::BinaryTarget): max in-flight XFB1
    # frames before the sender blocks.
    serve_pipeline_depth: int = 32

    # -- host data path --
    # Use the native C++ parser (xflow_tpu/native) when a toolchain is
    # available; falls back to the pure-Python parser silently.
    native_parser: bool = True
    # Parse/pack batches on a background thread, this many batches ahead
    # (0 = synchronous).  Replaces the reference's worker-side ThreadPool
    # (thread_pool.h) as the host-side parallelism mechanism: here the
    # device does the math, so host threads overlap parsing with device
    # compute instead of splitting the minibatch.
    prefetch_batches: int = 2
    # Concurrent block parse+pack threads (order-preserving); effective
    # with the native parser, which releases the GIL.  -1 = auto
    # (cores-1, capped at 6; sequential on single-core hosts);
    # 0/1 = sequential.
    parse_workers: int = -1

    # -- update path --
    # "dense": scatter-add gradients into a dense [T, D] buffer and apply
    #   the optimizer recurrence to the whole table each step.  No sort;
    #   pure elementwise math on HBM-resident arrays — the TPU-fast path.
    #   Correct because FTRL/SGD updates with g=0 are no-ops/idempotent
    #   (tests/test_ftrl.py::test_ftrl_zero_grad_is_idempotent).
    # "sparse": sort + segment-sum consolidation per unique key, then
    #   gather/update/scatter only touched rows.  O(batch nnz) work,
    #   preferable when the table vastly exceeds per-step HBM traffic
    #   budget or on CPU.
    # "sequential": the dense machinery, but the optimizer applies per
    #   microbatch SLICE inside the scan (tables ride the scan carry),
    #   so the effective update granularity is batch_size/microbatch
    #   while the host dispatches batch_size examples per call.  This
    #   composes the TPU dispatch rate with small-batch FTRL
    #   convergence (the reference's effective per-thread block is a
    #   few hundred rows, lr_worker.cc:116-118,190-196): gradients are
    #   divided by the SLICE's real count and each slice sees the
    #   tables as left by the previous slice — step-for-step the same
    #   training as batch_size/microbatch-sized dense steps.
    # dense ≡ sparse identically; sequential ≡ a sequence of dense
    # steps (tests/test_update_modes.py, tests/test_sequential.py).
    update_mode: str = "dense"

    # Per-slice update strategy under update_mode="sequential":
    # "dense" — full-table elementwise optimizer pass per slice
    #   (~7 [T,D]-arrays of HBM traffic; fine at T<=2^24).
    # "sparse" — consolidate the slice's keys and gather/update/scatter
    #   only touched rows; O(slice nnz) per slice, the ONLY viable form
    #   at north-star table sizes (a 2^28 FTRL triple is ~3 GiB —
    #   a full pass per 512-example slice would stream ~7 GiB).
    #   With the hot table on this runs the hybrid inner: cold keys
    #   touched-rows, hot section a dense [H, D] update with overflow
    #   spill folded in exactly once (step.py::_sparse_update).
    #   Equivalence: tests/test_sequential.py.
    # "hot" — hot-FINE / cold-COARSE: per slice the optimizer updates
    #   ONLY the dense hot head (on-chip, MXU one-hot traffic — no
    #   per-slice DMA at all); cold-section gradients accumulate
    #   per-occurrence and the cold tail takes ONE batched scatter +
    #   table pass per dispatch window.  Cold rows are read once at
    #   window start (one efficient batched gather) and are stale for
    #   at most one dispatch window — the async-parameter-server
    #   semantics of the reference itself, whose workers compute on
    #   weights pulled a minibatch ago (lr_worker.cc:95-143, ps-lite
    #   async Push/Pull), applied here only to the zipf TAIL while the
    #   head (most of the occurrence mass) updates at full B_eff
    #   granularity.  Requires hot_size_log2 > 0.  The per-slice cost
    #   is table-size-independent AND free of scatter/gather DMA
    #   latency — the form that turns sequential mode's convergence
    #   into device-rate wall-clock (docs/PERF.md "Sequential mode").
    sequential_inner: str = "dense"  # {"dense", "sparse", "hot"}

    # Window-end update form for sequential_inner='hot' (the cold-tail
    # pass that closes each dispatch window):
    # "dense" — accumulate cold grads into a [T, D] buffer and run ONE
    #   full-table optimizer pass (g=0 rows idempotent).  Simple, and
    #   fine at T<=2^24 — but the buffer + pass are a full-table
    #   transient per table per dispatch, multi-GB at T=2^28 for D>1
    #   (the ADVICE step.py:945 hazard; analysis rule XF010/XF014).
    # "sparse" — consolidate the window's cold keys (one argsort +
    #   segment-sum, ops/sparse.py) and gather/update/scatter ONLY
    #   touched rows: O(window nnz) work and transients, table-size-
    #   independent — the north-star form.  Same training: one summed-
    #   gradient update per touched row either way
    #   (tests/test_sequential.py).
    # "auto" (default): "sparse" from table_size_log2 >= 24 up (where
    #   the [T, D] transient would exceed ~any per-table budget),
    #   "dense" below.
    hot_windowend: str = "auto"  # {"auto", "dense", "sparse"}

    # Gradient-accumulation slices per train step (1 = off).  The batch
    # is split into `microbatch` equal slices scanned sequentially;
    # per-slice gradients accumulate into the dense per-table buffers
    # and ONE optimizer update runs at the end — numerically the same
    # step as microbatch=1 (scatter-add order aside), but every
    # [batch, nnz, D]-shaped intermediate shrinks by the slice count.
    # This is the memory lever for wide-row models (FFM's pair tensors,
    # docs/PERF.md layout section): big B on a small chip.  Under
    # update_mode="sequential" the same slicing instead sets the
    # effective optimizer batch (batch_size/microbatch).  Requires
    # update_mode="dense"/"sequential" and microbatch | batch_size.
    # Slices are interleaved (example i → slice i % microbatch) so each
    # slice stays evenly spread over the batch-sharded mesh axis — a
    # contiguous split would cut across device shards and force a
    # reshard per slice.
    microbatch: int = 1

    # Consolidate duplicate cold-section keys (one shared argsort +
    # per-table segment-sums) before the dense-mode scatter-add.  Zipf
    # batches duplicate heavily even after hot steering (measured 53%
    # duplicate cold occurrences at the FM flagship geometry, 90%
    # hot-off — docs/PERF.md "Cold consolidation"), and multi-lane
    # (D>1) scatter-add costs ~85-107 ns/slice, so collapsing
    # duplicates removes most of those slices.  Worth it for D>1
    # models (fm/mvm/wide_deep/ffm) at large batch; LR's scalar
    # scatters are too cheap for the sort to pay.  dense/sequential
    # modes only (sparse mode already consolidates).
    cold_consolidate: bool = False

    # -- hot table (frequency-partitioned head; docs/PERF.md "The win") --
    # log2 of the hot-table row count H (0 = off).  CTR key distributions
    # are zipfian; the top-H keys by frequency are permuted into table
    # rows [0, H) (io/freq.py) and their gather/scatter runs as two-level
    # one-hot MXU matmuls (ops/hot.py) instead of per-slice DMA —
    # measured ~2x (f32) to ~4x (bf16) on the hot fraction on v5e.
    # Requires update_mode="dense" or "sequential".
    hot_size_log2: int = 0
    # Static hot-key slots per sample (extra capacity on top of max_nnz;
    # per-row hot overflow spills to the cold/DMA path, which is always
    # correct).
    hot_nnz: int = 24
    # Bytes of training data sampled (from the front of the shard list,
    # deterministically — identical on every host) to estimate key
    # frequencies for the remap.
    freq_sample_mib: int = 64
    # Matmul input dtype for the hot path: "float32" = exact gather,
    # order-only scatter difference; "bfloat16" = ~2x faster, rounds
    # table/grad values to bf16 inside the hot path only.
    hot_dtype: str = "float32"

    # -- precision --
    # Parameter/optimizer state dtype. float32 default; bf16 is not used
    # for FTRL state (z accumulates small increments).
    param_dtype: str = "float32"

    # -- host->device wire format --
    # "full": ship keys/slots/vals/mask/labels/weights as-is.
    # "compact": ship sentinel-coded int32 keys (-1 = padding) + uint8
    #   labels/weights (~4x fewer bytes; slot-reading models — mvm,
    #   ffm, wide_deep — add a uint8 slots plane, ~3x) and reconstruct
    #   vals/mask (and slots where none shipped) inside the jitted
    #   step.  Valid only in hash mode (vals are identically 1,
    #   load_data_from_disk.cc:151); slot-reading models additionally
    #   need max_fields <= 255.  On links where host->device bandwidth
    #   bounds e2e throughput (measured ~150-250 MB/s here,
    #   docs/PERF.md) this is the main e2e lever.
    # "auto" (default): compact whenever valid, else full.
    wire_mode: str = "auto"  # {"auto", "full", "compact"}

    # Host-side batch compaction + dictionary wire (io/compact.py):
    # deduplicate each batch's cold keys on the host, ship a per-batch
    # dictionary of the most-duplicated keys (u16 occurrence indices,
    # consumed directly by the device's consolidation — no device
    # argsort) plus the near-unique tail as raw u24/u32, tiered hot
    # ids, flattened padding-free planes, and bitmap labels/weights —
    # measured ~70 wire bytes/example vs 130 for the plain compact
    # wire at the bench flagship (docs/PERF.md "Wire format and
    # compaction").  "auto" (default): on whenever eligible — hash
    # mode, single process + single-device mesh (the dictionary/stream
    # planes have no batch-axis sharding), max_nnz/hot_nnz <= 255, hot
    # table absent or hot_size_log2 <= 16, and the wire_mode compact
    # eligibility.  "on" raises when ineligible; "off" keeps the plain
    # compact/full wire.
    wire_dedup: str = "auto"  # {"auto", "off", "on"}

    # Hot-path gather/scatter implementation (ops/hot.py): "mxu" = the
    # two-level one-hot matmul path (the TPU win — ~2-4x over per-slice
    # DMA on v5e); "seg" = plain gather + segment-sum (the CPU-fast
    # form: one-hot matmuls are an MXU trick, measured 3.3x slower
    # than the gather on the CPU backend).  "auto" picks "mxu" on TPU
    # meshes and "seg" elsewhere.  Numerics: gather is exact either
    # way; scatter differs only in summation order.
    hot_impl: str = "auto"  # {"auto", "mxu", "seg"}

    # -- hierarchical parameter store (store/; docs/STORE.md) --
    # "dense": the whole [T, D] table lives in device HBM (every mode
    #   above) — the small-table form.
    # "tiered": HBM holds only a bounded HOT tier of
    #   2^hot_capacity_log2 rows (mesh-row-sharded, store/hot.py); the
    #   2^table_size_log2-row cold tail lives in HOST memory
    #   (store/cold.py, touched rows only — untouched rows materialize
    #   lazily from the per-row init, TableSpec.init_kind) and an async
    #   worker (store/promote.py) promotes/demotes rows by touch
    #   frequency.  Per-batch misses ride the wire as a packed row
    #   block and write back after the step, so every jitted transient
    #   scales with hot capacity, never T (analysis rules XF010/XF014)
    #   — the form that makes FM/MVM/FFM trainable at the north-star
    #   2^28 geometry, mirroring hierarchical parameter servers for
    #   massive ads models (arXiv:2003.05622).  Requires
    #   update_mode='dense' or 'sparse' (the optimizer applies once
    #   per dispatch either way), microbatch=1, hot_size_log2=0 (the
    #   tier subsumes the MXU frequency head), and a single process.
    store_mode: str = "dense"  # {"dense", "tiered"}
    # log2 rows of the HBM-resident hot tier under store_mode='tiered'.
    # Budget math at 2^28 lives in docs/STORE.md; must not exceed
    # table_size_log2 (a tier bigger than the table is a config bug).
    hot_capacity_log2: int = 18
    # Apply pending promotion/demotion plans every N train steps (the
    # async worker only PROPOSES; application is a between-steps device
    # fill/read so in-flight batches never see a moving key->slot map).
    store_promote_every: int = 1

    # Device staging ring depth: how many batches ahead the host->device
    # transfer (put_batch — compaction + h2d) runs on worker threads,
    # overlapping link round-trips and compaction with device compute
    # (trainer._transfer_ahead; single-host only — multi-host transfers
    # are collective).  >= 2 keeps the link busy while a transfer is in
    # flight (double buffering); deeper rings absorb link-latency jitter
    # and give the N-stream input fan-out (input_streams, io/fanout.py)
    # room to stay ahead of the device.  Worker count scales with the
    # depth (capped by the host's cores); batch order is preserved at
    # any depth (docs/PERF.md "Input fan-out").
    transfer_ahead_depth: int = 2

    # Parallel sharded input fan-out (io/fanout.py; docs/PERF.md "Input
    # fan-out"): number of concurrent shard-reader streams feeding the
    # training loop.  Stream s owns the epoch's shards with index
    # i % input_streams == s and runs its own read -> parse -> compact
    # worker, so per-shard host work no longer serializes behind one
    # stream; the merged batch order is the SERIAL shard order (stream
    # interleave keyed by shard index), so training is bitwise-identical
    # to input_streams=1.  1 = the serial path.  Most effective with
    # multi-shard epochs; a single-shard epoch degrades to one stream.
    # store_mode='tiered' requires 1 (see __post_init__).
    input_streams: int = 1

    def __post_init__(self) -> None:
        # registry-validated (models/__init__.py): new families become
        # config-valid by registering, not by editing this file.  Late
        # import — model modules import jax; config must stay
        # importable before backend selection.
        from xflow_tpu.models import model_names

        if self.model not in model_names():
            raise ValueError(
                f"unknown model {self.model!r} (registered families: "
                f"{', '.join(model_names())})"
            )
        if self.model == "two_tower" and not (
            0 < self.tower_split_field < self.max_fields
        ):
            raise ValueError(
                f"tower_split_field {self.tower_split_field} must be in "
                f"(0, max_fields={self.max_fields}): both towers need "
                "at least one field"
            )
        if self.tower_dim < 1:
            raise ValueError("tower_dim must be >= 1")
        if self.cross_layers < 1:
            raise ValueError("cross_layers must be >= 1")
        if self.optimizer not in ("ftrl", "sgd"):
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if self.update_mode not in ("dense", "sparse", "sequential"):
            raise ValueError(f"unknown update_mode {self.update_mode!r}")
        if not 10 <= self.table_size_log2 <= 30:
            raise ValueError("table_size_log2 must be in [10, 30]")
        if self.microbatch < 1:
            raise ValueError("microbatch must be >= 1")
        if self.microbatch > 1:
            if self.update_mode not in ("dense", "sequential"):
                raise ValueError(
                    "microbatch requires update_mode='dense' or 'sequential'"
                )
            if self.batch_size % self.microbatch:
                raise ValueError(
                    f"microbatch {self.microbatch} must divide "
                    f"batch_size {self.batch_size}"
                )
        if self.sequential_inner not in ("dense", "sparse", "hot"):
            raise ValueError(
                f"unknown sequential_inner {self.sequential_inner!r}"
            )
        if self.sequential_inner == "hot" and not self.hot_size_log2:
            raise ValueError(
                "sequential_inner='hot' needs a hot table "
                "(hot_size_log2 > 0) — the per-slice update IS the "
                "hot head"
            )
        if self.hot_windowend not in ("auto", "dense", "sparse"):
            raise ValueError(
                f"unknown hot_windowend {self.hot_windowend!r}"
            )
        if self.cold_consolidate and self.update_mode not in (
            "dense",
            "sequential",
        ):
            raise ValueError(
                "cold_consolidate requires update_mode='dense' or "
                "'sequential' (sparse mode already consolidates)"
            )
        if self.hot_size_log2:
            if self.update_mode not in ("dense", "sequential"):
                raise ValueError(
                    "hot table requires update_mode='dense' or 'sequential'"
                )
            if not 0 < self.hot_size_log2 < self.table_size_log2:
                raise ValueError(
                    "hot_size_log2 must be in (0, table_size_log2)"
                )
            if self.hot_nnz <= 0:
                raise ValueError("hot_nnz must be > 0 when hot table is on")
        if self.hot_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unknown hot_dtype {self.hot_dtype!r}")
        if self.pred_style not in ("single", "per_block"):
            raise ValueError(f"unknown pred_style {self.pred_style!r}")
        if self.wire_mode not in ("auto", "full", "compact"):
            raise ValueError(f"unknown wire_mode {self.wire_mode!r}")
        if self.wire_dedup not in ("auto", "off", "on"):
            raise ValueError(f"unknown wire_dedup {self.wire_dedup!r}")
        if self.hot_impl not in ("auto", "mxu", "seg"):
            raise ValueError(f"unknown hot_impl {self.hot_impl!r}")
        if self.store_mode not in ("dense", "tiered"):
            raise ValueError(f"unknown store_mode {self.store_mode!r}")
        if self.store_mode == "tiered":
            if self.hot_capacity_log2 > self.table_size_log2:
                raise ValueError(
                    f"hot_capacity_log2 {self.hot_capacity_log2} exceeds "
                    f"table_size_log2 {self.table_size_log2}: the hot "
                    "tier cannot hold more rows than the logical table "
                    "— lower --hot-capacity-log2 (or use "
                    "store_mode='dense', which fits the whole table in "
                    "HBM at this size)"
                )
            if self.hot_capacity_log2 < 1:
                raise ValueError(
                    "hot_capacity_log2 must be >= 1 under "
                    "store_mode='tiered'"
                )
            if self.update_mode == "sequential":
                raise ValueError(
                    "store_mode='tiered' does not compose with "
                    "update_mode='sequential': the sequential scan "
                    "carries full tables through the microbatch slices, "
                    "which is exactly the [T, D] residency the tiered "
                    "store removes — use update_mode='dense' (optimizer "
                    "over the hot+miss tier) or 'sparse' (touched rows "
                    "only), with microbatch for memory if needed"
                )
            if self.microbatch > 1:
                raise ValueError(
                    "store_mode='tiered' requires microbatch=1: the "
                    "tiered step already bounds every transient by hot "
                    "capacity, so gradient-accumulation slicing has "
                    "nothing left to shrink"
                )
            if self.hot_size_log2:
                raise ValueError(
                    "store_mode='tiered' subsumes the MXU frequency-hot "
                    "head (the hot tier IS the frequency head, kept "
                    "fresh by the promotion worker) — set "
                    "hot_size_log2=0"
                )
        if self.store_promote_every < 1:
            raise ValueError("store_promote_every must be >= 1")
        if self.chaos_spec:
            from xflow_tpu.chaos import parse_spec

            parse_spec(self.chaos_spec)  # fail at config time, not mid-run
        if self.io_retries < 0:
            raise ValueError("io_retries must be >= 0")
        if self.io_retry_backoff_s < 0:
            raise ValueError("io_retry_backoff_s must be >= 0")
        if not 0.0 <= self.max_quarantined_frac <= 1.0:
            raise ValueError("max_quarantined_frac must be in [0, 1]")
        for knob in (
            "serve_score_timeout_s",
            "serve_socket_timeout_s",
            "serve_client_timeout_s",
        ):
            if getattr(self, knob) <= 0:
                raise ValueError(
                    f"{knob} must be > 0 (an unbounded serve-path wait "
                    "is exactly what analysis rule XF017 forbids)"
                )
        if not (
            0.0
            < self.serve_qos_best_effort_frac
            <= self.serve_qos_normal_frac
            <= 1.0
        ):
            raise ValueError(
                "QoS budget fractions must satisfy 0 < "
                "serve_qos_best_effort_frac <= serve_qos_normal_frac "
                "<= 1 (best_effort sheds first, bidding last)"
            )
        if self.serve_cache_capacity < 0:
            raise ValueError(
                "serve_cache_capacity must be >= 0 (0 disables the "
                "score cache)"
            )
        if self.serve_pipeline_depth < 1:
            raise ValueError("serve_pipeline_depth must be >= 1")
        if self.checkpoint_keep < 0:
            raise ValueError("checkpoint_keep must be >= 0")
        if self.transfer_ahead_depth < 1:
            raise ValueError(
                "transfer_ahead_depth must be >= 1 (1 = a single staged "
                "batch; >= 2 overlaps transfer with device compute)"
            )
        if self.input_streams < 1:
            raise ValueError(
                "input_streams must be >= 1 (1 = the serial reader; "
                "N > 1 fans the shard list out over N concurrent "
                "streams — io/fanout.py)"
            )
        if self.input_streams > 1 and self.store_mode == "tiered":
            raise ValueError(
                "input_streams > 1 does not compose with "
                "store_mode='tiered' yet: the cold store's strict "
                "plan->dispatch->writeback ordering (read-your-writes, "
                "docs/STORE.md) already pins the transfer-ahead ring "
                "off, and concurrent shard streams would feed it no "
                "faster — set input_streams=1; the async-PS per-key-"
                "range version gate of ROADMAP item 2 is the relaxation "
                "that lifts this pin"
            )
        if self.obs_trace_capacity < 1:
            raise ValueError("obs_trace_capacity must be >= 1")
        if not 0.0 <= self.obs_reqtrace_sample <= 1.0:
            raise ValueError("obs_reqtrace_sample must be in [0, 1]")
        if self.obs_flight_events < 1:
            raise ValueError("obs_flight_events must be >= 1")
        if self.obs_watchdog:
            if min(
                self.obs_watchdog_input_s,
                self.obs_watchdog_device_s,
                self.obs_watchdog_serve_s,
            ) <= 0:
                raise ValueError("watchdog thresholds must be > 0")
            if self.obs_watchdog_poll_s < 0:
                raise ValueError("obs_watchdog_poll_s must be >= 0")
        if not 0 <= self.obs_export_port <= 65535:
            raise ValueError(
                "obs_export_port must be in [0, 65535] (0 = exporter "
                "off)"
            )
        if self.obs_resource_every_s < 0:
            raise ValueError(
                "obs_resource_every_s must be >= 0 (0 = sampler off)"
            )
        if self.obs_resource_every_s > 0 and not self.metrics_out:
            raise ValueError(
                "obs_resource_every_s requires metrics_out — the "
                "resource rows need a metrics stream to land in"
            )

    @property
    def table_size(self) -> int:
        return 1 << self.table_size_log2

    @property
    def hot_size(self) -> int:
        return (1 << self.hot_size_log2) if self.hot_size_log2 else 0

    @property
    def hot_capacity(self) -> int:
        """Hot-tier rows under store_mode='tiered' (shapeflow symbol
        Hc — analysis/shapeflow.py CONFIG_SYMS)."""
        return 1 << self.hot_capacity_log2

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    def digest(self) -> str:
        """12-hex-char sha256 of the config JSON — the run/artifact
        identity stamped into metrics ``run_start`` headers
        (trainer._run_header) and serving-artifact manifests
        (serve/artifact.py); PredictEngine refuses artifacts whose
        digest doesn't match an expected config."""
        import hashlib

        return hashlib.sha256(self.to_json().encode()).hexdigest()[:12]

    @classmethod
    def from_json(cls, text: str) -> "Config":
        raw: dict[str, Any] = json.loads(text)
        # legacy alias (docs/MIGRATION.md): checkpoint/artifact manifests
        # written before the input fan-out spelled the staging-ring depth
        # `transfer_ahead`
        if "transfer_ahead" in raw and "transfer_ahead_depth" not in raw:
            raw["transfer_ahead_depth"] = raw.pop("transfer_ahead")
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - fields
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        return cls(**raw)

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)
