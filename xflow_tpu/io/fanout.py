"""Parallel sharded input fan-out: N concurrent shard-reader streams
with a deterministic, serial-order merge (ROADMAP item 1).

One reader stream was the last measured input bottleneck (BENCH_r05:
compute far ahead of the packed e2e feed): read, parse and host
compaction all serialized behind a single thread while the device
waited.  Parallel sharded host feeds are table stakes for sparse CTR
training at scale — Parallax's sparsity-aware data parallelism
(arXiv:1808.02621) and the terabyte-scale ads-training wire discipline
(arXiv:2201.05500) both shard the input path first.

``ShardStreamPool`` partitions an epoch's shard list across N streams
by shard index (stream ``s`` owns shards ``i % N == s``).  Each stream
is a daemon producer thread (the ``_PrefetchIter`` fabric from
io/loader.py — bounded queue, explicit close(), backpressure
heartbeats, exception propagation) running its own
read -> parse -> [compact] loop over its shards, ``depth`` batches
ahead.  The consumer-side merge walks the GLOBAL shard order and pulls
each shard's batches from its owning stream, so the merged batch
sequence is exactly the serial reader's — training under the fan-out is
bitwise-identical to ``input_streams=1`` and steady-state shapes stay
on one compiled program (``e2e_recompiles: 0``).  The parallelism is in
the lookahead: while shard ``i`` drains to the device, the other
streams are already reading/parsing/compacting shards ``i+1..i+N-1``.

``transform`` runs on the producer thread per batch — the trainer
passes ``TrainStep.precompact`` so host dictionary compaction
(io/compact.py) rides the streams instead of the staging-ring workers.

Per-stream accounting (``stream_stats``) feeds the trainer's ``stream``
metrics rows (obs/schema.py): shards/batches/examples, producer wall
seconds, and backpressure stall seconds — `obs doctor` ranks a stream
whose throughput lags its peers as a straggler
(docs/OBSERVABILITY.md).

The tiered parameter store pins the pool to one stream at config time
(Config.input_streams validation): its cold tier's read-your-writes
ordering leaves nothing for concurrent readers to feed — ROADMAP item
2's async-PS relaxation lifts that.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator

from xflow_tpu.io.loader import _PrefetchIter
from xflow_tpu.obs import NULL_OBS

# Stream worker -> merger messages ride the _PrefetchIter queue:
# (_ITEM, shard_idx, batch, resume) per batch, (_DONE, shard_idx,
# stats) after each finished shard.  No other cross-thread state
# exists — stats travel with the message, so the pool needs no locks
# of its own.
_ITEM = 0
_DONE = 1


class ShardStreamPool:
    """N concurrent shard streams merged back into serial shard order.

    ``shards`` is the epoch's full (ordered) shard path list;
    ``loader_factory(path)`` builds the per-shard loader (the trainer's
    ``_loader``).  Yields ``(batch, shard_idx, resume_offset)`` with
    the exact contract and order of the serial reader.  ``close()``
    stops every stream (bounded join — the _PrefetchIter discipline);
    the pool is a context manager and registers cleanly with
    Trainer.close()'s reap set.
    """

    def __init__(
        self,
        shards: list[str],
        loader_factory: Callable[[str], object],
        num_streams: int,
        depth: int = 2,
        start_shard: int = 0,
        start_offset: int = 0,
        parse_workers: int = 0,
        transform: Callable | None = None,
        obs=None,
    ):
        if num_streams < 1:
            raise ValueError("num_streams must be >= 1")
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._shards = shards
        self._start_shard = start_shard
        self._obs = obs if obs is not None else NULL_OBS
        remaining = len(shards) - start_shard
        # never spawn empty streams: a 2-shard epoch at N=4 runs 2
        self.num_streams = max(1, min(num_streams, remaining))
        self._streams: list[_PrefetchIter] = []
        # consumer-side per-stream accumulators (single-thread: the
        # merging consumer alone touches these)
        self._stats: list[dict] = []
        self._stall_base: list[float] = []
        for s in range(self.num_streams):
            owned = [
                (i, shards[i])
                for i in range(start_shard, len(shards))
                if (i - start_shard) % self.num_streams == s
            ]
            it = _PrefetchIter(
                self._stream_source(
                    owned, loader_factory, start_shard, start_offset,
                    parse_workers, transform,
                ),
                depth,
                obs=self._obs,
            )
            self._streams.append(it)
            self._stats.append({
                "stream": s,
                "shards": 0,
                "batches": 0,
                "examples": 0,
                "seconds": 0.0,
                "read_seconds": 0.0,
                "stall_seconds": 0.0,
            })
            self._stall_base.append(0.0)

    @staticmethod
    def _stream_source(
        owned: list[tuple[int, str]],
        loader_factory: Callable[[str], object],
        start_shard: int,
        start_offset: int,
        parse_workers: int,
        transform: Callable | None,
    ) -> Iterator[tuple]:
        """One stream's producer generator: its owned shards in global
        order, each read through a fresh loader, batches optionally
        transformed (host compaction) BEFORE they hit the queue.  Runs
        entirely on the _PrefetchIter producer thread."""
        for shard_idx, path in owned:
            loader = loader_factory(path)
            offset = start_offset if shard_idx == start_shard else 0
            t0 = time.perf_counter()
            batches = 0
            examples = 0
            read_s = 0.0  # read+parse+compact, EXCLUDING queue waits:
            # measured directly (never wall minus stall — that
            # difference cancels catastrophically for fast readers)
            it = loader.iter_batches(offset, parse_workers)
            while True:
                t = time.perf_counter()
                try:
                    batch, resume = next(it)
                except StopIteration:
                    break
                if transform is not None:
                    batch = transform(batch)
                read_s += time.perf_counter() - t
                yield _ITEM, shard_idx, batch, resume
                batches += 1
                examples += batch.num_real()
            yield _DONE, shard_idx, {
                "batches": batches,
                "examples": examples,
                "seconds": time.perf_counter() - t0,
                "read_seconds": read_s,
            }

    def __iter__(self) -> Iterator[tuple]:
        """Merge: global shard order, each shard pulled from its owning
        stream.  A stream exception (quarantine budget, I/O failure)
        propagates here through its _PrefetchIter."""
        for si in range(self._start_shard, len(self._shards)):
            s = (si - self._start_shard) % self.num_streams
            stream = self._streams[s]
            for msg in stream:
                if msg[0] == _DONE:
                    self._book_done(s, msg[1], msg[2])
                    break
                _, shard_idx, batch, resume = msg
                if shard_idx != si:  # defensive: streams emit in order
                    raise RuntimeError(
                        f"stream {s} yielded shard {shard_idx} while "
                        f"the merge expected shard {si}"
                    )
                yield batch, shard_idx, resume

    def _book_done(self, s: int, shard_idx: int, stats: dict) -> None:
        acc = self._stats[s]
        acc["shards"] += 1
        acc["batches"] += stats["batches"]
        acc["examples"] += stats["examples"]
        acc["seconds"] += stats["seconds"]
        acc["read_seconds"] += stats["read_seconds"]
        # stall delta since the last finished shard: _PrefetchIter
        # accounts cumulatively across the stream's whole life
        total_stall = self._streams[s].stall_seconds()
        acc["stall_seconds"] += total_stall - self._stall_base[s]
        self._stall_base[s] = total_stall

    def stream_stats(self) -> list[dict]:
        """Per-stream accounting over the shards finished so far —
        the trainer's ``stream`` metrics rows.  ``examples_per_sec``
        divides by the DIRECTLY MEASURED read+parse+compact seconds
        (queue waits excluded), so a stream parked behind a saturated
        consumer doesn't read as a straggler and a fast reader's rate
        doesn't explode out of a wall-minus-stall cancellation."""
        out = []
        for acc in self._stats:
            row = dict(acc)
            row["seconds"] = round(acc["seconds"], 6)
            row["read_seconds"] = round(acc["read_seconds"], 6)
            row["stall_seconds"] = round(acc["stall_seconds"], 6)
            row["examples_per_sec"] = round(
                acc["examples"] / max(acc["read_seconds"], 1e-9), 1
            )
            out.append(row)
        return out

    def close(self, join_timeout: float = 5.0) -> None:
        """Stop every stream's producer (bounded join per stream; a
        wedged producer is surfaced by _PrefetchIter.close's leak
        counter + health row, never waited on forever).  Idempotent."""
        for stream in self._streams:
            stream.close(join_timeout)

    @property
    def alive(self) -> bool:
        return any(stream.alive for stream in self._streams)

    def __enter__(self) -> "ShardStreamPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
