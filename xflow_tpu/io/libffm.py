"""libffm text parsing with block streaming.

Behavioral spec is the reference's only production loader,
``load_minibatch_hash_data_fread`` (load_data_from_disk.cc:103-210):

* reads a fixed-size byte block per pass and carries the partial last
  line over to the next pass (:108-124);
* a line is ``label<SEP>fgid:fid:val ...`` — whitespace-separated
  feature tokens after the label;
* the label is binarized ``y > 1e-7 → 1`` (:131-134);
* ``fgid`` parses as an integer field/group id;
* in hash mode the ``fid`` token is hashed **as a string** and the
  value field is discarded — features are implicitly binary (:151);
* in numeric mode (reference loaders at :11-57) ``fid`` parses as an
  integer and ``val`` as a float and both are kept.

Differences from the reference, on purpose: the hash is MurmurHash64A,
not ``std::hash<string>`` (see hashing.py); malformed tokens are skipped
with a count rather than undefined behavior.
"""

from __future__ import annotations

import io as _stdio
from typing import BinaryIO, Iterator

import numpy as np

from xflow_tpu.io.batch import ParsedBlock
from xflow_tpu.io.hashing import murmur64_batch

LABEL_THRESHOLD = 1e-7  # reference: load_data_from_disk.cc:131-134


class BlockReader:
    """Streams a binary file in ~block_bytes chunks of whole lines,
    carrying the partial last line between reads (reference
    load_data_from_disk.cc:108-124)."""

    def __init__(self, f: BinaryIO, block_bytes: int):
        self._f = f
        self._block_bytes = max(int(block_bytes), 1)
        self._carry = b""

    def __iter__(self) -> Iterator[bytes]:
        while True:
            chunk = self._f.read(self._block_bytes)
            if not chunk:
                if self._carry:
                    carry, self._carry = self._carry, b""
                    yield carry
                return
            buf = self._carry + chunk
            cut = buf.rfind(b"\n")
            if cut == -1:
                self._carry = buf
                continue
            self._carry = buf[cut + 1 :]
            yield buf[: cut + 1]


def parse_block(
    data: bytes,
    table_size: int,
    hash_mode: bool = True,
    hash_seed: int = 0,
) -> ParsedBlock:
    """Parse one block of libffm lines into a CSR ParsedBlock.

    Keys are reduced modulo ``table_size`` (the TPU table is a dense
    array, unlike the reference's unbounded server-side hash map,
    ftrl.h:84).  ``table_size=0`` keeps FULL keys — the 64-bit hash
    (two's-complement int64 view) in hash mode, the raw fid in numeric
    mode — for the binary block cache (io/binary.py, table-size-
    independent) and collision accounting.
    """
    labels: list[float] = []
    row_ptr: list[int] = [0]
    slots: list[int] = []
    vals: list[float] = []
    tokens: list[bytes] = []  # fid tokens (hash mode)
    fids: list[int] = []  # numeric fids (no-hash mode)

    for line in data.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        parts = line.split()
        try:
            y = float(parts[0])
        except ValueError:
            continue
        labels.append(1.0 if y > LABEL_THRESHOLD else 0.0)
        for tok in parts[1:]:
            pieces = tok.split(b":")
            if len(pieces) != 3:
                continue
            try:
                fgid = int(pieces[0])
            except ValueError:
                continue
            if not -(2**31) <= fgid < 2**31:
                continue  # slot arrays are int32; reject, never wrap
            if hash_mode:
                tokens.append(pieces[1])
                vals.append(1.0)  # value field discarded: binary features
            else:
                try:
                    fid = int(pieces[1])
                    val = float(pieces[2])
                except ValueError:
                    continue
                if not -(2**63) <= fid < 2**63:
                    continue  # keys are int64; reject, never wrap
                # reject values not finite IN FLOAT32: inf/nan literals
                # and "1e999"/"1e39"-style overflows the float32 cast
                # would silently turn into inf (round-1 weak point 8).
                # (2-2^-24)*2^127 is the exact round-to-nearest overflow
                # boundary; `not <` also rejects nan.  Native parser
                # matches exactly (parser.cc isfinite after narrowing).
                if not abs(val) < 3.4028235677973366e38:
                    continue
                fids.append(fid)
                vals.append(val)
            slots.append(fgid)
        row_ptr.append(len(slots))

    if hash_mode:
        hashed = murmur64_batch(tokens, seed=hash_seed)
        if table_size:
            keys = (hashed % np.uint64(table_size)).astype(np.int64)
        else:
            keys = hashed.view(np.int64)
    else:
        keys = np.asarray(fids, dtype=np.int64)
        if table_size:
            keys = keys % table_size

    return ParsedBlock(
        labels=np.asarray(labels, dtype=np.float32),
        row_ptr=np.asarray(row_ptr, dtype=np.int64),
        keys=keys,
        slots=np.asarray(slots, dtype=np.int32),
        vals=np.asarray(vals, dtype=np.float32),
    )


def parse_file(
    path: str, table_size: int, hash_mode: bool = True, hash_seed: int = 0
) -> ParsedBlock:
    """Parse an entire file at once (reference ``load_all_*`` loaders,
    load_data_from_disk.cc:11-33,59-79)."""
    # whole-file test/tool helper — production streaming goes through
    # ShardLoader, which carries the loader.* sites (xf: ignore[XF018])
    with open(path, "rb") as f:
        return parse_block(f.read(), table_size, hash_mode, hash_seed)


def open_block_stream(path: str, block_mib: int) -> BlockReader:
    # bare-stream helper for tools/tests — ShardLoader.iter_batches is
    # the chaos-covered production opener (xf: ignore[XF018])
    f: BinaryIO = open(path, "rb", buffering=_stdio.DEFAULT_BUFFER_SIZE)
    return BlockReader(f, block_mib << 20)
