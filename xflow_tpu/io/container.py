"""Shared container framing for the on-disk cache formats (io/binary.py
CSR blocks, io/packed.py device-ready batches): an 8-byte magic, a u32
JSON-header length, the JSON header, then format-specific records.

Writers stream records after a placeholder header (totals pinned to
2^63 so the real values — which can only be shorter — rewrite in place
without moving the data), then call rewrite_header once the totals are
known.  Readers go through read_header, which also enforces the
format's version."""

from __future__ import annotations

import json
import struct
from typing import BinaryIO

_HLEN = struct.Struct("<I")


def sniff(path: str, magic: bytes) -> bool:
    # 4-byte magic peek for format dispatch — the actual read path it
    # dispatches to carries the loader.* sites (xf: ignore[XF018])
    with open(path, "rb") as f:
        return f.read(len(magic)) == magic


def read_header(
    f: BinaryIO, magic: bytes, what: str, version: int | tuple = 1
) -> tuple[dict, int]:
    """Returns (header dict, byte offset of the first record).
    ``version`` may be a tuple when a format spans several on-disk
    versions the caller knows how to read (io/packed.py v1/v2)."""
    got = f.read(len(magic))
    if got != magic:
        raise ValueError(f"not a {what} (bad magic)")
    raw = f.read(_HLEN.size)
    if len(raw) != _HLEN.size:
        raise ValueError(f"truncated {what} header")
    (hlen,) = _HLEN.unpack(raw)
    body = f.read(hlen)
    if len(body) != hlen:
        raise ValueError(f"truncated {what} header")
    meta = json.loads(body)
    versions = version if isinstance(version, tuple) else (version,)
    if meta.get("version") not in versions:
        raise ValueError(
            f"unsupported {what} version {meta.get('version')!r} "
            f"(expected {' or '.join(map(str, versions))})"
        )
    return meta, len(magic) + _HLEN.size + hlen


def write_placeholder_header(
    f: BinaryIO, magic: bytes, meta: dict, total_keys: tuple[str, ...]
) -> int:
    """Write ``meta`` with every key in ``total_keys`` pinned to 2^63
    (the widest value it can take); returns the header's byte length for
    the later rewrite."""
    padded = {**meta, **{k: 2**63 for k in total_keys}}
    raw = json.dumps(padded).encode()
    f.write(magic + _HLEN.pack(len(raw)) + raw)
    return f.tell()


def rewrite_header(
    f: BinaryIO, magic: bytes, meta: dict, hdr_len: int
) -> None:
    """Rewrite the header in place with final totals, space-padding the
    JSON to exactly the placeholder's length (json.loads ignores
    trailing whitespace)."""
    raw = json.dumps(meta).encode()
    pad = hdr_len - len(magic) - _HLEN.size - len(raw)
    if pad < 0:
        raise ValueError(
            "final header longer than placeholder — totals grew?"
        )
    raw += b" " * pad
    f.seek(0)
    f.write(magic + _HLEN.pack(len(raw)) + raw)
