"""Stable 64-bit feature hashing (MurmurHash64A).

The reference hashes feature-id string tokens with ``std::hash<string>``
(io.h:53, applied at load_data_from_disk.cc:151).  ``std::hash`` is
implementation-defined, so checkpoints/results would not be portable
across toolchains; we use MurmurHash64A (Austin Appleby, public domain)
instead — the same choice SURVEY §7 stage 2 calls for.  Golden vectors
from the canonical C implementation are pinned in tests/test_hashing.py
so any alternate implementation (e.g. a native parser) can be checked
for bit-exact parity.

Both a scalar reference implementation and a length-grouped vectorized
numpy implementation are provided; they agree bit-for-bit.
"""

from __future__ import annotations

import numpy as np

_M = 0xC6A4A7935BD1E995
_R = 47
_MASK = (1 << 64) - 1
DEFAULT_SEED = 0


def murmur64(data: bytes | str, seed: int = DEFAULT_SEED) -> int:
    """MurmurHash64A of ``data``; returns an unsigned 64-bit int."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    n = len(data)
    h = (seed ^ ((n * _M) & _MASK)) & _MASK
    nblocks = n // 8
    for i in range(nblocks):
        k = int.from_bytes(data[i * 8 : i * 8 + 8], "little")
        k = (k * _M) & _MASK
        k ^= k >> _R
        k = (k * _M) & _MASK
        h ^= k
        h = (h * _M) & _MASK
    tail = data[nblocks * 8 :]
    if tail:
        k = int.from_bytes(tail, "little")
        h ^= k
        h = (h * _M) & _MASK
    h ^= h >> _R
    h = (h * _M) & _MASK
    h ^= h >> _R
    return h


def _murmur64_fixed_len(buf: np.ndarray, seed: int) -> np.ndarray:
    """Vectorized MurmurHash64A for a [n, L] uint8 array of equal-length
    tokens (L = true byte length of every row)."""
    n, length = buf.shape
    m = np.uint64(_M)
    r = np.uint64(_R)
    h = np.full(n, (seed ^ ((length * _M) & _MASK)) & _MASK, dtype=np.uint64)
    nblocks = length // 8
    old = np.seterr(over="ignore")
    try:
        for i in range(nblocks):
            k = (
                buf[:, i * 8 : i * 8 + 8]
                .copy()
                .view(np.uint64)
                .reshape(n)
                .astype(np.uint64)
            )
            k *= m
            k ^= k >> r
            k *= m
            h ^= k
            h *= m
        tail_len = length - nblocks * 8
        if tail_len:
            k = np.zeros(n, dtype=np.uint64)
            for j in range(tail_len):
                k |= buf[:, nblocks * 8 + j].astype(np.uint64) << np.uint64(8 * j)
            h ^= k
            h *= m
        h ^= h >> r
        h *= m
        h ^= h >> r
    finally:
        np.seterr(**old)
    return h


def murmur64_batch(tokens: list[bytes], seed: int = DEFAULT_SEED) -> np.ndarray:
    """Vectorized MurmurHash64A over a list of byte tokens.

    Groups tokens by length and hashes each group with numpy; bit-exact
    with :func:`murmur64`.  Returns uint64 [len(tokens)].
    """
    out = np.empty(len(tokens), dtype=np.uint64)
    if not tokens:
        return out
    lengths = np.fromiter((len(t) for t in tokens), dtype=np.int64, count=len(tokens))
    for length in np.unique(lengths):
        idx = np.nonzero(lengths == length)[0]
        if length == 0:
            # h = seed ^ 0, then finalization mix.
            out[idx] = np.uint64(murmur64(b"", seed))
            continue
        buf = np.frombuffer(
            b"".join(tokens[i] for i in idx), dtype=np.uint8
        ).reshape(len(idx), int(length))
        out[idx] = _murmur64_fixed_len(buf, seed)
    return out
