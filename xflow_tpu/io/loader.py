"""Shard-aware streaming minibatch loader.

Reference behavior reproduced: each data-parallel worker reads its own
file shard named ``<prefix>-%05d`` by rank (lr_worker.cc:210); training
streams the shard in fixed-size byte blocks per epoch until the loader
returns no rows (lr_worker.cc:183-189).

New capabilities (gaps filled, SURVEY §5):

* batches are FULL across text-block boundaries — parsed blocks
  accumulate in a carry buffer and only a shard's final batch is
  zero-weight padded (the reference trains on whatever each 2 MiB block
  parses to, lr_worker.cc:184-189);
* a resume cursor per batch — the byte offset of the earliest block
  holding samples not yet emitted — so training can checkpoint and
  restart mid-shard.  Replay on resume is bounded by one block plus one
  carry (< batch_size samples); see iter_batches.
"""

from __future__ import annotations

import math
import queue
import threading
import time
import warnings
from typing import Callable, Iterator

from xflow_tpu.chaos import ChaosError, emit_health, failpoint, retry_call
from xflow_tpu.io.batch import Batch, ParsedBlock, pack_batch
from xflow_tpu.io.libffm import BlockReader, parse_block
from xflow_tpu.obs import NULL_OBS


class QuarantineExceeded(RuntimeError):
    """Quarantined blocks/records exceeded the budget
    (Config.max_quarantined_frac): the stream is corrupt beyond what
    skip-and-continue can responsibly absorb — training on the
    remainder would silently fit a different dataset."""


def shard_path(prefix: str, rank: int) -> str:
    return f"{prefix}-{rank:05d}"  # reference: lr_worker.cc:210


def _concat_blocks(a: ParsedBlock, b: ParsedBlock) -> ParsedBlock:
    """CSR concatenation (carry ∥ next block)."""
    import numpy as np

    return ParsedBlock(
        labels=np.concatenate([a.labels, b.labels]),
        row_ptr=np.concatenate([a.row_ptr, b.row_ptr[1:] + a.row_ptr[-1]]),
        keys=np.concatenate([a.keys, b.keys]),
        slots=np.concatenate([a.slots, b.slots]),
        vals=np.concatenate([a.vals, b.vals]),
    )


def _slice_block(block: ParsedBlock, start: int) -> ParsedBlock:
    """CSR tail slice: samples [start, n)."""
    lo = block.row_ptr[start]
    return ParsedBlock(
        labels=block.labels[start:],
        row_ptr=block.row_ptr[start:] - lo,
        keys=block.keys[lo:],
        slots=block.slots[lo:],
        vals=block.vals[lo:],
    )


ParseFn = Callable[[bytes], ParsedBlock]


def make_parse_fn(
    table_size: int,
    hash_mode: bool = True,
    hash_seed: int = 0,
    prefer_native: bool = True,
) -> ParseFn:
    """Native C++ parser when built/buildable, else the Python one.
    Both are behaviorally identical (tests/test_native.py)."""
    if prefer_native:
        from xflow_tpu import native

        if native.available():
            return lambda data: native.native_parse_block(
                data, table_size, hash_mode, hash_seed
            )
    return lambda data: parse_block(data, table_size, hash_mode, hash_seed)


class ShardLoader:
    """Streams one text shard as padded fixed-shape Batches."""

    def __init__(
        self,
        path: str,
        batch_size: int,
        max_nnz: int,
        table_size: int,
        block_mib: int = 2,
        hash_mode: bool = True,
        hash_seed: int = 0,
        parse_fn: ParseFn | None = None,
        remap=None,  # int32 [table_size] permutation (io/freq.py), or None
        hot_size: int = 0,
        hot_nnz: int = 0,
        obs=None,  # obs.Obs: parse/pack phase seconds + byte counters
        emit_compact: bool = False,  # v2 packed shards: yield CompactBatch
        io_retries: int = 2,  # transient read/parse retries per block
        io_retry_backoff_s: float = 0.05,
        max_quarantined_frac: float = 0.05,  # quarantine budget
    ):
        self.path = path
        self.batch_size = batch_size
        self.max_nnz = max_nnz
        self.table_size = table_size
        self.block_bytes = block_mib << 20
        self.hash_mode = hash_mode
        self.hash_seed = hash_seed
        if parse_fn is None:
            parse_fn = lambda data: parse_block(
                data, table_size, hash_mode, hash_seed
            )
        self.parse_fn = parse_fn
        self.remap = remap
        self.hot_size = hot_size
        self.hot_nnz = hot_nnz
        # With emit_compact, v2 packed shards (io/packed.py) yield
        # their records AS CompactBatch — the consumer (a dict-wire
        # TrainStep via put_batch) then pays ZERO per-batch host work;
        # other formats still yield padded Batches.
        self.emit_compact = emit_compact
        # Parse/pack run on worker threads under prefetch/parse_workers,
        # so their phase seconds OVERLAP the consumer's wall-clock — the
        # trainer reports them in the epoch record's "overlapped" dict,
        # never in the additive main-thread accounting.
        self.obs = obs if obs is not None else NULL_OBS
        # Native pack folds remap + hot steering + padding into one C
        # pass (xf_pack_batch); the numpy fallback applies the remap at
        # parse time and pads/steers with pack_batch.
        from xflow_tpu import native

        self._native_pack = native.available()
        # Self-healing (docs/ROBUSTNESS.md): transient read/parse
        # failures retry with backoff; a block that still fails is
        # quarantined (skipped + health row) until the budget trips.
        # Counters shared across parse workers — guarded (XF003/XF008).
        self.io_retries = io_retries
        self.io_retry_backoff_s = io_retry_backoff_s
        self.max_quarantined_frac = max_quarantined_frac
        self._q_lock = threading.Lock()
        self._blocks_seen = 0
        self._quarantined = 0

    # -- self-healing -------------------------------------------------------

    def _parse_block_healed(self, raw: bytes, offset: int) -> ParsedBlock | None:
        """One block through the failpoint + retry + quarantine fabric.
        Returns None when the block was quarantined (the stream skips
        it); raises :class:`QuarantineExceeded` past the budget.
        Failpoint sites: ``loader.read_block`` (arm as a transient —
        retries heal it with zero data loss) and ``loader.parse_record``
        (arm persistent — retries exhaust, the block quarantines)."""
        with self._q_lock:
            self._blocks_seen += 1

        def attempt() -> ParsedBlock:
            failpoint("loader.read_block")
            failpoint("loader.parse_record")
            return self._parse_remap(raw)

        try:
            return retry_call(
                attempt,
                attempts=self.io_retries,
                backoff_s=self.io_retry_backoff_s,
                channel="loader",
                site=f"{self.path}@{offset}",
                obs=self.obs,
                retry_on=(OSError, ValueError, ChaosError),
            )
        except (OSError, ValueError, ChaosError) as e:
            self._quarantine(offset, e)
            return None

    def _quarantine(self, offset: int, err: BaseException) -> None:
        """Skip one unhealable block/record: counter + ``health`` row,
        then the budget check — quarantine is for isolated corruption,
        not a license to train past a rotten stream."""
        self.obs.counter("loader.quarantined")
        with self._q_lock:
            self._quarantined += 1
            quarantined, seen = self._quarantined, self._blocks_seen
        emit_health(
            self.obs,
            cause="record_quarantined",
            channel="loader",
            detail=f"{self.path}@{offset}: skipped after "
            f"{self.io_retries} retries ({type(err).__name__}: {err})",
        )
        budget = max(1, math.ceil(self.max_quarantined_frac * seen))
        if quarantined > budget:
            emit_health(
                self.obs,
                cause="quarantine_budget_exceeded",
                channel="loader",
                detail=f"{self.path}: {quarantined} of {seen} blocks "
                f"quarantined (budget {budget})",
            )
            raise QuarantineExceeded(
                f"{self.path}: {quarantined} quarantined blocks exceed "
                f"the budget ({budget} of {seen} seen, "
                f"max_quarantined_frac={self.max_quarantined_frac}) — "
                f"last error: {type(err).__name__}: {err}"
            ) from err

    def _apply_remap(self, block: ParsedBlock) -> ParsedBlock:
        if (
            self.remap is not None
            and not self._native_pack
            and len(block.keys)
        ):
            # frequency remap: pure row-placement permutation (io/freq.py)
            block.keys = self.remap[block.keys]
        return block

    def _parse_remap(self, raw: bytes) -> ParsedBlock:
        with self.obs.phase("parse"):
            block = self._apply_remap(self.parse_fn(raw))
        self.obs.counter("loader.parse_bytes", len(raw))
        self.obs.counter("loader.blocks")
        return block

    def _pack(self, block: ParsedBlock, start: int, end: int) -> Batch:
        with self.obs.phase("pack"):
            if self._native_pack:
                from xflow_tpu.native import native_pack_batch

                return native_pack_batch(
                    block, start, end, self.batch_size, self.max_nnz,
                    self.hot_size, self.hot_nnz, self.remap,
                )
            return pack_batch(
                block, start, end, self.batch_size, self.max_nnz,
                self.hot_size, self.hot_nnz,
            )

    def iter_batches(
        self, start_offset: int = 0, parse_workers: int = 0
    ) -> Iterator[tuple[Batch, int]]:
        """Yield (batch, resume_offset) pairs for one pass over the shard.

        Batches are FULL (batch_size real examples) regardless of the
        text block size: parsed blocks accumulate in a carry buffer and
        only the shard's final batch is zero-weight padded.  (Without
        this, block_bytes ≪ batch_size lines would make every batch
        mostly padding — wasted device cycles.)

        ``resume_offset`` is the byte offset of the earliest block with
        samples not yet yielded — pass it back as ``start_offset`` to
        resume; up to one block plus one carry may replay (resume
        granularity is the block, as in the reference's block loader,
        load_data_from_disk.cc:103-124).

        With parse_workers > 1, whole blocks parse+remap concurrently on
        a thread pool, order-preserving (the native parser and numpy
        release the GIL for the heavy part) — the TPU-era replacement
        for the reference's per-minibatch ThreadPool fan-out
        (lr_worker.cc:190-196).

        Binary block-cache shards (io/binary.py, sniffed by magic) skip
        parsing entirely — records stream at memory speed; parse_workers
        is irrelevant there.  Packed-batch shards (io/packed.py) skip
        batch assembly too: records ARE finished device-ready batches.
        The (batch, resume_offset) contract is identical for all three
        formats.
        """
        from xflow_tpu.io import binary, packed

        # chaos site: shard open/sniff fault — distinct from the
        # per-record sites so open-time failures are injectable (XF018)
        failpoint("loader.open_shard")
        with open(self.path, "rb") as f:
            magic = f.read(len(binary.MAGIC))
            if magic == binary.MAGIC:
                yield from self._iter_binary(f, start_offset)
                return
            if magic == packed.MAGIC:
                yield from self._iter_packed(f, start_offset)
                return
            f.seek(start_offset)

            def parsed_blocks() -> Iterator[tuple[ParsedBlock, int, int]]:
                # every block rides _parse_block_healed (retry +
                # quarantine); a None result is a quarantined block —
                # skipped, never yielded (resume offsets stay
                # consistent: the skip consumes the block's bytes)
                offset = start_offset
                if parse_workers <= 1:
                    for raw in BlockReader(f, self.block_bytes):
                        next_offset = offset + len(raw)
                        block = self._parse_block_healed(raw, offset)
                        if block is not None:
                            yield block, offset, next_offset
                        offset = next_offset
                    return
                from collections import deque
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=parse_workers) as ex:
                    pending: deque = deque()
                    for raw in BlockReader(f, self.block_bytes):
                        next_offset = offset + len(raw)
                        pending.append(
                            (
                                ex.submit(
                                    self._parse_block_healed, raw, offset
                                ),
                                offset,
                                next_offset,
                            )
                        )
                        offset = next_offset
                        while len(pending) > parse_workers + 1:
                            fut, off, noff = pending.popleft()
                            block = fut.result()
                            if block is not None:
                                yield block, off, noff
                    while pending:
                        fut, off, noff = pending.popleft()
                        block = fut.result()
                        if block is not None:
                            yield block, off, noff

            yield from self._batches_from_blocks(parsed_blocks(), start_offset)

    def _iter_binary(
        self, f, start_offset: int
    ) -> Iterator[tuple[Batch, int]]:
        """Batch stream over a binary block-cache shard (io/binary.py):
        records already hold parsed CSR; reduction to [0, table_size)
        and the remap happen at load."""
        from xflow_tpu.io import binary

        blocks = (
            (self._apply_remap(b), off, noff)
            for b, off, noff in binary.iter_blocks(
                f,
                self.table_size,
                start_offset,
                expect_hash_mode=self.hash_mode,
                expect_hash_seed=self.hash_seed,
            )
        )
        yield from self._batches_from_blocks(blocks, start_offset)

    def _iter_packed(
        self, f, start_offset: int
    ) -> Iterator[tuple[Batch, int]]:
        """Batch stream over a packed-batch shard (io/packed.py): each
        record is a finished Batch — no parse, no assembly.  The cache's
        baked-in batch geometry must match this loader exactly."""
        from xflow_tpu.io import packed

        f.seek(0)
        meta, _ = packed.read_header(f)
        packed.check_compat(
            meta,
            batch_size=self.batch_size,
            cold_nnz=self.max_nnz,
            hot_nnz=self.hot_nnz if self.hot_size else 0,
            hot_size=self.hot_size,
            table_size=self.table_size,
            hash_mode=self.hash_mode,
            hash_seed=self.hash_seed,
            remap=self.remap,
        )
        flight = self.obs.flight
        if self.emit_compact and meta.get("version", 1) == 2:
            records = packed.iter_compact_batches(f, start_offset)
        else:
            records = packed.iter_batches(f, start_offset)
        for batch, offset, next_offset in records:
            with self._q_lock:
                self._blocks_seen += 1
            try:
                # the packed-record corruption site: a fire here
                # quarantines THIS record (skip + health row + budget
                # check) and the stream continues at the next one
                failpoint("loader.packed_record")
            except ChaosError as e:
                self._quarantine(offset, e)
                continue
            if flight is not None:
                flight.note_loader("packed_batch")
            yield batch, next_offset

    def _batches_from_blocks(
        self,
        blocks: Iterator[tuple[ParsedBlock, int, int]],
        start_offset: int,
    ) -> Iterator[tuple[Batch, int]]:
        """Shared carry/batch assembly over any (block, offset,
        next_offset) source (text parser or binary cache)."""
        carry: ParsedBlock | None = None
        end_offset = start_offset
        flight = self.obs.flight
        for block, raw_offset, next_offset in blocks:
            # watchdog heartbeat (obs/flight.py): the input pipeline is
            # alive.  A starving trainer with a BEATING loader points
            # at transfer/backpressure, not at parsing.
            if flight is not None:
                flight.note_loader("block")
            end_offset = next_offset
            if carry is not None and carry.num_samples:
                block = _concat_blocks(carry, block)
            carry = None
            n = block.num_samples
            start = 0
            while n - start >= self.batch_size:
                end = start + self.batch_size
                # resume = earliest block holding a not-yet-yielded
                # sample.  The carry is always < batch_size samples,
                # so the first batch of this loop consumes it whole:
                # unyielded samples start in this raw block (or past
                # it entirely when end == n).
                resume = next_offset if end == n else raw_offset
                yield self._pack(block, start, end), resume
                start = end
            if start < n:
                carry = _slice_block(block, start)
        if carry is not None and carry.num_samples:
            # the stream's final (partial) batch consumes everything
            yield self._pack(carry, 0, carry.num_samples), end_offset

    def prefetch(
        self, depth: int, start_offset: int = 0, parse_workers: int = 0
    ) -> Iterator[tuple[Batch, int]]:
        """iter_batches with parse/pack running on a background thread,
        ``depth`` batches ahead of the consumer."""
        return _prefetch_iter(
            self.iter_batches(start_offset, parse_workers), depth,
            obs=self.obs,
        )

    def count_examples(self) -> int:
        from xflow_tpu.io import binary, packed

        if binary.is_binary_shard(self.path):
            return binary.shard_example_count(self.path)
        if packed.is_packed_shard(self.path):
            return packed.shard_example_count(self.path)
        n = 0
        # metadata sizing pass for planners, not the streamed training
        # path — the read path carries loader.* sites (xf: ignore[XF018])
        with open(self.path, "rb") as f:
            for line in f:
                if line.strip():
                    n += 1
        return n


_SENTINEL = object()


class _PrefetchIter:
    """``it`` running on a daemon producer thread, buffering up to
    ``depth`` items.  Exceptions propagate to the consumer.

    The round-4 design relied on a queue-put timeout plus GC to stop
    the producer when a consumer abandoned the iterator — which LEAKS
    the thread (and its open shard file) until the garbage collector
    happens to run the generator's finally block.  This object makes
    shutdown explicit: ``close()`` signals the producer, drains the
    queue so a blocked put wakes immediately, and joins the thread.
    Trainer.close() closes every live prefetch it spawned; use the
    iterator as a context manager elsewhere.  ``depth <= 0`` degrades
    to a synchronous passthrough with the same close() surface."""

    def __init__(self, it: Iterator, depth: int, obs=None):
        self._source = it
        self._closed = False
        self._close_done = False
        self._close_lock = threading.Lock()
        self._obs = obs if obs is not None else NULL_OBS
        self._thread: threading.Thread | None = None
        # Producer backpressure accounting: wall seconds the producer
        # spent blocked on a FULL queue (the consumer wasn't ready).
        # The fan-out pool (io/fanout.py) reads this per stream to
        # separate a slow reader (straggler) from a saturated consumer.
        self._stats_lock = threading.Lock()
        self._stall_seconds = 0.0
        if depth <= 0:
            return
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _put_or_abort(self, item) -> bool:
        flight = self._obs.flight
        t0 = time.perf_counter()
        try:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    # XF009 heartbeat: the producer is alive but
                    # blocked on a full queue — a 'backpressure' beat
                    # lets the watchdog tell a wedged CONSUMER (loader
                    # beating, no consumption) from a dead input
                    # pipeline (no beats)
                    if flight is not None:
                        flight.note_loader("backpressure")
                    continue
            return False
        finally:
            # anything past the free-slot fast path (microseconds) was
            # the producer waiting on the consumer; the 1ms floor keeps
            # per-batch noise out of the stall ledger
            dt = time.perf_counter() - t0
            if dt > 1e-3:
                self._note_stall(dt)

    def _note_stall(self, dt: float) -> None:
        with self._stats_lock:
            self._stall_seconds += dt

    def stall_seconds(self) -> float:
        """Cumulative producer-side backpressure (blocked-on-full-queue)
        wall seconds so far.  Safe from any thread."""
        with self._stats_lock:
            return self._stall_seconds

    def _produce(self) -> None:
        try:
            for item in self._source:
                if not self._put_or_abort(item):
                    return
            self._put_or_abort(_SENTINEL)
        except BaseException as e:  # propagate to consumer
            self._put_or_abort(e)

    def __iter__(self) -> "_PrefetchIter":
        return self

    def __next__(self):
        if self._thread is None:  # synchronous passthrough
            if self._closed:
                raise StopIteration
            return next(self._source)
        if self._closed:
            raise StopIteration
        item = self._q.get()
        if item is _SENTINEL:
            self._closed = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._closed = True
            raise item
        return item

    def close(self, join_timeout: float = 5.0) -> None:
        """Stop the producer thread and release its resources.
        Idempotent; safe from any thread.  A producer that OUTLIVES
        the join (wedged in parse/read, not on the queue) is surfaced
        — warning, ``loader.leaked_threads`` counter, and a ``health``
        row — instead of silently leaking with its open shard file."""
        self._closed = True
        if self._thread is None:
            return
        with self._close_lock:
            # a second close() — sequential (consumer closed directly,
            # then Trainer.close() reaps _live_prefetch) or concurrent
            # (the "safe from any thread" contract) — must not pay
            # another join_timeout or double-report a wedged producer
            if self._close_done:
                return
            self._close_done = True
        self._stop.set()
        # drain so a producer blocked on a full queue observes the
        # stop event on its next timeout tick at the latest
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=join_timeout)
        if self._thread.is_alive():
            warnings.warn(
                "prefetch producer thread outlived its close() join "
                f"({join_timeout:.1f}s) — it is wedged in parse/read "
                "and still holds the shard file open",
                RuntimeWarning,
                stacklevel=2,
            )
            self._obs.counter("loader.leaked_threads")
            flight = self._obs.flight
            if flight is not None and flight.metrics_logger is not None:
                from xflow_tpu.obs.schema import health_row

                flight.metrics_logger.log("health", health_row(
                    cause="prefetch_thread_leak",
                    channel="loader",
                    silence_seconds=join_timeout,
                    threshold_seconds=join_timeout,
                    detail="producer outlived close() join",
                    channels=flight.snapshot()["channels"],
                ))

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def __enter__(self) -> "_PrefetchIter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _prefetch_iter(it: Iterator, depth: int, obs=None) -> _PrefetchIter:
    return _PrefetchIter(it, depth, obs=obs)
