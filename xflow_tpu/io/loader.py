"""Shard-aware streaming minibatch loader.

Reference behavior reproduced: each data-parallel worker reads its own
file shard named ``<prefix>-%05d`` by rank (lr_worker.cc:210); training
streams the shard in fixed-size byte blocks per epoch until the loader
returns no rows (lr_worker.cc:183-189).

New capability (gap filled, SURVEY §5): the loader exposes a resume
cursor — the byte offset of the next unparsed block — so training can
checkpoint-and-restart mid-shard.  Resume granularity is one block.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

from xflow_tpu.io.batch import Batch, ParsedBlock, pack_batch
from xflow_tpu.io.libffm import BlockReader, parse_block


def shard_path(prefix: str, rank: int) -> str:
    return f"{prefix}-{rank:05d}"  # reference: lr_worker.cc:210


ParseFn = Callable[[bytes], ParsedBlock]


def make_parse_fn(
    table_size: int,
    hash_mode: bool = True,
    hash_seed: int = 0,
    prefer_native: bool = True,
) -> ParseFn:
    """Native C++ parser when built/buildable, else the Python one.
    Both are behaviorally identical (tests/test_native.py)."""
    if prefer_native:
        from xflow_tpu import native

        if native.available():
            return lambda data: native.native_parse_block(
                data, table_size, hash_mode, hash_seed
            )
    return lambda data: parse_block(data, table_size, hash_mode, hash_seed)


class ShardLoader:
    """Streams one text shard as padded fixed-shape Batches."""

    def __init__(
        self,
        path: str,
        batch_size: int,
        max_nnz: int,
        table_size: int,
        block_mib: int = 2,
        hash_mode: bool = True,
        hash_seed: int = 0,
        parse_fn: ParseFn | None = None,
        remap=None,  # int32 [table_size] permutation (io/freq.py), or None
        hot_size: int = 0,
        hot_nnz: int = 0,
    ):
        self.path = path
        self.batch_size = batch_size
        self.max_nnz = max_nnz
        self.table_size = table_size
        self.block_bytes = block_mib << 20
        if parse_fn is None:
            parse_fn = lambda data: parse_block(
                data, table_size, hash_mode, hash_seed
            )
        self.parse_fn = parse_fn
        self.remap = remap
        self.hot_size = hot_size
        self.hot_nnz = hot_nnz

    def _block_to_batches(
        self, raw: bytes, offset: int, next_offset: int
    ) -> list[tuple[Batch, int]]:
        block = self.parse_fn(raw)
        if self.remap is not None and len(block.keys):
            # frequency remap: pure row-placement permutation (io/freq.py)
            block.keys = self.remap[block.keys]
        out = []
        n = block.num_samples
        for start in range(0, n, self.batch_size):
            end = min(start + self.batch_size, n)
            out.append(
                (
                    pack_batch(
                        block, start, end, self.batch_size, self.max_nnz,
                        self.hot_size, self.hot_nnz,
                    ),
                    offset if end < n else next_offset,
                )
            )
        return out

    def iter_batches(
        self, start_offset: int = 0, parse_workers: int = 0
    ) -> Iterator[tuple[Batch, int]]:
        """Yield (batch, resume_offset) pairs for one pass over the shard.

        ``resume_offset`` is the byte offset of the first block not yet
        fully consumed — pass it back as ``start_offset`` to resume.

        With parse_workers > 1, whole blocks parse+pack concurrently on a
        thread pool, order-preserving (the native parser and numpy both
        release the GIL for the heavy part) — the TPU-era replacement for
        the reference's per-minibatch ThreadPool fan-out
        (lr_worker.cc:190-196).
        """
        with open(self.path, "rb") as f:
            f.seek(start_offset)
            offset = start_offset
            if parse_workers <= 1:
                for raw in BlockReader(f, self.block_bytes):
                    next_offset = offset + len(raw)
                    yield from self._block_to_batches(raw, offset, next_offset)
                    offset = next_offset
                return

            from collections import deque
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=parse_workers) as ex:
                pending: deque = deque()
                for raw in BlockReader(f, self.block_bytes):
                    next_offset = offset + len(raw)
                    pending.append(
                        ex.submit(self._block_to_batches, raw, offset, next_offset)
                    )
                    offset = next_offset
                    while len(pending) > parse_workers + 1:
                        yield from pending.popleft().result()
                while pending:
                    yield from pending.popleft().result()

    def prefetch(
        self, depth: int, start_offset: int = 0, parse_workers: int = 0
    ) -> Iterator[tuple[Batch, int]]:
        """iter_batches with parse/pack running on a background thread,
        ``depth`` batches ahead of the consumer."""
        return _prefetch_iter(
            self.iter_batches(start_offset, parse_workers), depth
        )

    def count_examples(self) -> int:
        n = 0
        with open(self.path, "rb") as f:
            for line in f:
                if line.strip():
                    n += 1
        return n


_SENTINEL = object()


def _prefetch_iter(it: Iterator, depth: int) -> Iterator:
    """Run ``it`` on a daemon thread, buffering up to ``depth`` items.
    Exceptions propagate to the consumer; the thread stops early if the
    consumer abandons the iterator (queue slot freed on GC via timeout)."""
    if depth <= 0:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_or_abort(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in it:
                if not put_or_abort(item):
                    return
            put_or_abort(_SENTINEL)
        except BaseException as e:  # propagate to consumer
            put_or_abort(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
