"""Shard-aware streaming minibatch loader.

Reference behavior reproduced: each data-parallel worker reads its own
file shard named ``<prefix>-%05d`` by rank (lr_worker.cc:210); training
streams the shard in fixed-size byte blocks per epoch until the loader
returns no rows (lr_worker.cc:183-189).

New capability (gap filled, SURVEY §5): the loader exposes a resume
cursor — the byte offset of the next unparsed block — so training can
checkpoint-and-restart mid-shard.  Resume granularity is one block.
"""

from __future__ import annotations

from typing import Callable, Iterator

from xflow_tpu.io.batch import Batch, ParsedBlock, pack_batch
from xflow_tpu.io.libffm import BlockReader, parse_block


def shard_path(prefix: str, rank: int) -> str:
    return f"{prefix}-{rank:05d}"  # reference: lr_worker.cc:210


ParseFn = Callable[[bytes], ParsedBlock]


class ShardLoader:
    """Streams one text shard as padded fixed-shape Batches."""

    def __init__(
        self,
        path: str,
        batch_size: int,
        max_nnz: int,
        table_size: int,
        block_mib: int = 2,
        hash_mode: bool = True,
        hash_seed: int = 0,
        parse_fn: ParseFn | None = None,
    ):
        self.path = path
        self.batch_size = batch_size
        self.max_nnz = max_nnz
        self.table_size = table_size
        self.block_bytes = block_mib << 20
        if parse_fn is None:
            parse_fn = lambda data: parse_block(
                data, table_size, hash_mode, hash_seed
            )
        self.parse_fn = parse_fn

    def iter_batches(self, start_offset: int = 0) -> Iterator[tuple[Batch, int]]:
        """Yield (batch, resume_offset) pairs for one pass over the shard.

        ``resume_offset`` is the byte offset of the first block not yet
        fully consumed — pass it back as ``start_offset`` to resume.
        """
        with open(self.path, "rb") as f:
            f.seek(start_offset)
            offset = start_offset
            for raw in BlockReader(f, self.block_bytes):
                next_offset = offset + len(raw)
                block = self.parse_fn(raw)
                n = block.num_samples
                for start in range(0, n, self.batch_size):
                    end = min(start + self.batch_size, n)
                    yield (
                        pack_batch(block, start, end, self.batch_size, self.max_nnz),
                        offset if end < n else next_offset,
                    )
                offset = next_offset

    def count_examples(self) -> int:
        n = 0
        with open(self.path, "rb") as f:
            for line in f:
                if line.strip():
                    n += 1
        return n
