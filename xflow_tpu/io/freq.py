"""Key-frequency statistics and the hot-head remap.

The hot-table MXU path (ops/hot.py) only pays off if the frequent keys
actually live in table rows [0, H).  Feature hashing spreads keys
uniformly, so we measure: sample the head of the training data, count
key frequencies, and build a *permutation* of the hash space that maps
the top-H keys to rows [0, H) and everything else to [H, T) — a bijection,
so collision behavior is unchanged; only row placement moves.

The remap is computed from a deterministic sample (the first
``sample_bytes`` of the global shard list, block-aligned), so every
host of a multi-host job derives the identical permutation with no
communication.  It is part of the model: rows are addressed through it,
so it is persisted next to checkpoints (trainer.save) and restored
before any prediction.

The reference has no analogue — its unordered_map server store
(ftrl.h:84) is frequency-oblivious; this is a TPU-specific placement
optimization with no numeric effect (tests/test_hot_train.py).
"""

from __future__ import annotations

import os

import numpy as np

from xflow_tpu.io.libffm import BlockReader


def count_keys(
    paths: list[str],
    parse_fn,
    table_size: int,
    sample_bytes: int,
    block_bytes: int = 2 << 20,
) -> np.ndarray:
    """Count key occurrences over up to ``sample_bytes`` of data taken
    from the front of ``paths`` in order.  Returns int64 [table_size]."""
    from xflow_tpu.io import binary

    counts = np.zeros(table_size, dtype=np.int64)
    remaining = sample_bytes
    for path in paths:
        if remaining <= 0:
            break
        # offline remap-building sampler (run before training), not the
        # streamed training/serving fault fabric (xf: ignore[XF018])
        with open(path, "rb") as f:
            magic = f.read(len(binary.MAGIC))
            if magic == binary.MAGIC:
                # binary block cache: records already hold keys
                for block, off, noff in binary.iter_blocks(f, table_size):
                    if len(block.keys):
                        np.add.at(counts, block.keys, 1)
                    remaining -= noff - off
                    if remaining <= 0:
                        break
                continue
            from xflow_tpu.io import packed

            if magic == packed.MAGIC:
                # packed caches hold POST-remap keys — counting them
                # cannot build a remap; parsing them as text would
                # silently produce garbage counts
                raise ValueError(
                    f"{path} is a packed-batch cache: key frequencies "
                    "must be counted from text or CSR-binary shards "
                    "(the remap is baked in at pack time — point "
                    "hot-table runs at the remap.npy used to build it)"
                )
            f.seek(0)
            for raw in BlockReader(f, block_bytes):
                block = parse_fn(raw)
                if len(block.keys):
                    # in-place accumulate: no O(table_size) temporary per
                    # block (bincount would allocate [T] each time)
                    np.add.at(counts, block.keys, 1)
                remaining -= len(raw)
                if remaining <= 0:
                    break
    return counts


def build_remap(counts: np.ndarray, hot_size: int) -> np.ndarray:
    """Permutation of [0, T): the hot_size most frequent keys map to
    [0, hot_size) in descending-frequency order; the rest keep their
    relative order in [hot_size, T).  Returns int32 [T]."""
    t = counts.shape[0]
    if not 0 < hot_size < t:
        raise ValueError(f"hot_size {hot_size} must be in (0, {t})")
    top = np.argpartition(counts, t - hot_size)[t - hot_size :]
    top = top[np.argsort(counts[top])[::-1]]  # descending frequency
    perm = np.empty(t, dtype=np.int32)
    perm[top] = np.arange(hot_size, dtype=np.int32)
    rest = np.ones(t, dtype=bool)
    rest[top] = False
    perm[rest] = np.arange(hot_size, t, dtype=np.int32)
    return perm


def hot_mass(counts: np.ndarray, remap: np.ndarray, hot_size: int) -> float:
    """Fraction of sampled occurrences the hot table captures."""
    total = counts.sum()
    if total == 0:
        return 0.0
    hot = counts[remap < hot_size].sum()
    return float(hot) / float(total)


def save_remap(path: str, remap: np.ndarray) -> None:
    tmp = path + ".tmp.npy"  # np.save appends .npy unless present
    np.save(tmp, remap)
    # offline remap tool (atomic tmp+rename; run before training), not
    # the runtime fault fabric (xf: ignore[XF018])
    os.replace(tmp, path)


def load_remap(path: str) -> np.ndarray | None:
    if not os.path.exists(path):
        return None
    # offline remap tool companion of save_remap (xf: ignore[XF018])
    return np.load(path)
