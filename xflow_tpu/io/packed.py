"""Packed-batch cache: device-ready batches on disk.

The CSR binary cache (io/binary.py) removes text parsing but still pays
CSR→padded assembly (~15 ns/entry in native pack — the host bottleneck
once parsing is gone; docs/PERF.md).  For steady-state multi-epoch
training at a FIXED batch configuration — the reference's workload is
60 epochs over the same shards (lr_worker.h:63) — even that can be
precomputed: this cache stores finished ``Batch`` arrays; reading one
is a header-driven buffer slice (zero copy, no per-entry work), so the
host side runs at memory speed and the device step becomes the
bottleneck.

The trade against io/binary.py: a packed cache bakes in batch_size,
max_nnz, table_size, hot geometry, and the hot remap (keys are stored
POST-remap, steered into hot/cold sections).  Change any of those and
the cache must be rebuilt — the loader validates every one of them
(including a hash of the remap) and refuses silently-wrong reads.

Format (little-endian):

    magic   8 bytes  b"XFPB0001"
    hlen    u32, header JSON:
      {"version": 1, "batch_size": B, "cold_nnz": K, "hot_nnz": Kh,
       "hot_size": H, "table_size": T, "hash_mode": bool,
       "hash_seed": int, "remap_sha256": hex|null, "batches": n,
       "examples": n}
    then ``batches`` fixed-size records, each the concatenation of
      keys i32[B,K] | slots i32[B,K] | vals f32[B,K] | mask f32[B,K]
      | hot_keys i32[B,Kh] | hot_slots i32[B,Kh] | hot_vals f32[B,Kh]
      | hot_mask f32[B,Kh] | labels f32[B] | weights f32[B]

Records have constant size, so a resume offset is plain arithmetic and
random access is free.  The final (partial) batch of a shard is stored
as-is — weights already encode padding.

Tail safety: every writer streams into a ``<dst>.tmp.<pid>`` scratch
name, fsyncs, and ``os.replace``s on finalize — a reader (including
the continuous-training ShardFollower tailing a growing directory,
stream/follower.py) can NEVER observe a half-written shard at the
final name; a mid-write kill leaves only the scratch file, which every
consumer skips by its ``.tmp`` infix.

Convert via the CLI (from text or CSR-binary shards):

    python -m xflow_tpu.io.packed --train PREFIX --out PREFIX.pk \
        --batch-size N --max-nnz K --table-size-log2 T \
        [--hot-size-log2 H --hot-nnz Kh --remap remap.npy] [...]
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import BinaryIO, Iterator

import numpy as np

from xflow_tpu.chaos import failpoint
from xflow_tpu.io import container
from xflow_tpu.io.batch import Batch

MAGIC = b"XFPB0001"

# v2 (format version in the JSON header; MIGRATION.md "Packed cache
# v2"): records hold CompactBatch planes (io/compact.py) instead of the
# padded [B, K] arrays — ~7x smaller on disk at the flagship geometry,
# and the steady-state reader hands the trainer PRE-COMPACTED batches,
# so epochs 2..N pay zero per-batch compaction or wire-packing work.
# Records are variable-size (content-sized planes under plane_cap
# bucketing), each prefixed by a fixed binary counts header; resume
# offsets are validated by walking the record chain (a packed shard
# holds ~examples/B records — double digits — so the walk is free).
_REC_HEADER = struct.Struct("<8q")  # n_real n_cold n_dict n_dict_occ
#                                     n_hot n_h8 slots_code rec_bytes


def remap_digest(remap: np.ndarray | None) -> str | None:
    if remap is None:
        return None
    return hashlib.sha256(
        np.ascontiguousarray(remap, np.int32).tobytes()
    ).hexdigest()


def is_packed_shard(path: str) -> bool:
    return container.sniff(path, MAGIC)


def read_header(f: BinaryIO) -> tuple[dict, int]:
    return container.read_header(f, MAGIC, "packed shard", version=(1, 2))


def _layout(meta: dict) -> tuple[list[tuple[str, tuple, np.dtype]], int]:
    """(field, shape, dtype) per record section, and the record size."""
    b = meta["batch_size"]
    k = meta["cold_nnz"]
    kh = meta["hot_nnz"]
    fields = [
        ("keys", (b, k), np.dtype(np.int32)),
        ("slots", (b, k), np.dtype(np.int32)),
        ("vals", (b, k), np.dtype(np.float32)),
        ("mask", (b, k), np.dtype(np.float32)),
        ("hot_keys", (b, kh), np.dtype(np.int32)),
        ("hot_slots", (b, kh), np.dtype(np.int32)),
        ("hot_vals", (b, kh), np.dtype(np.float32)),
        ("hot_mask", (b, kh), np.dtype(np.float32)),
        ("labels", (b,), np.dtype(np.float32)),
        ("weights", (b,), np.dtype(np.float32)),
    ]
    size = sum(int(np.prod(s)) * d.itemsize for _, s, d in fields)
    return fields, size


def check_compat(
    meta: dict,
    *,
    batch_size: int,
    cold_nnz: int,
    hot_nnz: int,
    hot_size: int,
    table_size: int,
    hash_mode: bool,
    hash_seed: int,
    remap: np.ndarray | None,
) -> None:
    """Raise unless the cache was built for exactly this batch config."""
    want = {
        "batch_size": batch_size,
        "cold_nnz": cold_nnz,
        "hot_nnz": hot_nnz,
        "hot_size": hot_size,
        "table_size": table_size,
        "hash_mode": bool(hash_mode),
        "remap_sha256": remap_digest(remap),
    }
    for key, val in want.items():
        if meta.get(key) != val:
            raise ValueError(
                f"packed shard built with {key}={meta.get(key)!r}, "
                f"loader expects {val!r} — rebuild the cache "
                "(python -m xflow_tpu.io.packed)"
            )
    if meta["hash_mode"] and int(meta["hash_seed"]) != int(hash_seed):
        raise ValueError(
            f"packed shard hashed with seed {meta['hash_seed']}, "
            f"loader expects {hash_seed}"
        )


def write_shard(
    dst: str, meta: dict, batches: Iterator[Batch]
) -> dict:
    """Stream ``batches`` into a packed shard (atomic temp + rename).
    ``meta`` must hold the config keys of check_compat; totals are
    filled in here."""
    fields, _ = _layout(meta)
    # chaos site: a transient writer fault mid-shard — the tmp+fsync+
    # os.replace tail-safety below is what it exercises (XF018)
    failpoint("packed.write")
    tmp = f"{dst}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(dst)), exist_ok=True)
    n_batches = 0
    examples = 0
    try:
        with open(tmp, "wb") as f:
            header = {"version": 1, **meta}
            hdr_len = container.write_placeholder_header(
                f, MAGIC, header, ("batches", "examples")
            )
            for batch in batches:
                for name, shape, dtype in fields:
                    arr = getattr(batch, name)
                    if arr.shape != shape or arr.dtype != dtype:
                        raise ValueError(
                            f"batch field {name}: {arr.shape}/{arr.dtype} "
                            f"!= cache layout {shape}/{dtype}"
                        )
                    f.write(np.ascontiguousarray(arr).tobytes())
                n_batches += 1
                examples += batch.num_real()
            header.update({"batches": n_batches, "examples": examples})
            container.rewrite_header(f, MAGIC, header, hdr_len)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dst)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    header.pop("version")
    return header


def write_shard_v2(
    dst: str, meta: dict, batches: Iterator[Batch]
) -> dict:
    """Stream ``batches`` through host compaction (io/compact.py) into
    a v2 packed shard of CompactBatch records (atomic temp + rename).
    ``meta`` must hold the config keys of check_compat; wire parameters
    and totals are filled in here."""
    from xflow_tpu.io import compact as C

    failpoint("packed.write")
    tmp = f"{dst}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(dst)), exist_ok=True)
    key_bytes = 3 if meta["table_size"] <= 1 << 24 else 4
    hx16 = meta["hot_size"] > 1 << 12
    header = {
        "version": 2,
        **meta,
        "dict_cap": C.DICT_CAP,
        "granule_div": C.GRANULE_DIV,
        "granule_min": C.GRANULE_MIN,
        "key_bytes": key_bytes,
        "hx16": hx16,
    }
    n_batches = 0
    examples = 0
    try:
        with open(tmp, "wb") as f:
            hdr_len = container.write_placeholder_header(
                f, MAGIC, header, ("batches", "examples")
            )
            for batch in batches:
                cb = C.CompactBatch.from_batch(
                    batch,
                    meta["table_size"],
                    meta["hot_size"],
                    check=n_batches == 0,
                    strict_layout=True,
                )
                specs = C.plane_specs(
                    batch_size=cb.batch_size,
                    cold_nnz=cb.cold_nnz,
                    hot_nnz_cap=cb.hot_nnz_cap,
                    key_bytes=cb.key_bytes,
                    hx16=cb.hx16,
                    slots_code=cb.slots_code,
                    n_cold=cb.n_cold,
                    n_dict=cb.n_dict,
                    n_dict_occ=cb.n_dict_occ,
                    n_hot=cb.n_hot,
                    n_h8=cb.n_h8,
                )
                if cb.key_bytes != key_bytes or cb.hx16 != hx16:
                    raise ValueError(
                        "compact batch wire parameters drifted from "
                        "the shard header — geometry mismatch?"
                    )
                blobs = []
                for name, shape, dtype in specs:
                    arr = getattr(cb, name)
                    if arr.shape != shape or arr.dtype != dtype:
                        raise ValueError(
                            f"record plane {name}: {arr.shape}/"
                            f"{arr.dtype} != spec {shape}/{dtype}"
                        )
                    blobs.append(np.ascontiguousarray(arr).tobytes())
                body = b"".join(blobs)
                f.write(_REC_HEADER.pack(
                    cb.n_real, cb.n_cold, cb.n_dict, cb.n_dict_occ,
                    cb.n_hot, cb.n_h8, cb.slots_code,
                    _REC_HEADER.size + len(body),
                ))
                f.write(body)
                n_batches += 1
                examples += cb.n_real
            header.update({"batches": n_batches, "examples": examples})
            container.rewrite_header(f, MAGIC, header, hdr_len)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dst)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    header.pop("version")
    return header


def _iter_records_v2(f: BinaryIO, meta: dict, start_offset: int):
    """Yield (CompactBatch, offset, next_offset) over a v2 shard.
    Record planes are read-only zero-copy views of the mmap; the mmap
    outlives ``f`` (numpy views hold it via .base)."""
    import mmap

    from xflow_tpu.io import compact as C

    f.seek(0)
    _, data_start = read_header(f)
    # schema-check the JSON meta BEFORE any arithmetic consumes it: a
    # corrupt header (fuzzed/bit-rotted JSON values of the wrong type)
    # must be a typed refusal, not a TypeError deep in plane sizing
    try:
        b = int(meta["batch_size"])
        kc = int(meta["cold_nnz"])
        kh = int(meta["hot_nnz"])
        dict_cap = int(meta["dict_cap"])
        key_bytes = int(meta["key_bytes"])
        hx16 = bool(meta["hx16"])
        gdiv = int(meta["granule_div"])
        gmin = int(meta["granule_min"])
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(
            f"packed shard header meta malformed: {e!r}"
        ) from e
    if b <= 0 or kc < 0 or kh < 0 or dict_cap < 0 or gdiv <= 0 \
            or gmin < 0 or key_bytes not in (3, 4):
        raise ValueError(
            "packed shard header meta out of range "
            f"(batch_size={b} cold_nnz={kc} hot_nnz={kh} "
            f"dict_cap={dict_cap} key_bytes={key_bytes} "
            f"granule_div={gdiv} granule_min={gmin})"
        )
    try:
        mm: memoryview | bytes | mmap.mmap = mmap.mmap(
            f.fileno(), 0, access=mmap.ACCESS_READ
        )
        if hasattr(mmap, "MADV_SEQUENTIAL"):
            mm.madvise(mmap.MADV_SEQUENTIAL)
    except (ValueError, OSError):
        f.seek(0)
        mm = f.read()  # unmmapable stream: buffer it
    end = len(mm)
    offset = data_start
    start_offset = max(int(start_offset), data_start)
    if start_offset > end:
        raise ValueError(
            f"resume offset {start_offset} is past the packed shard "
            f"end {end} — was the cache rebuilt since the checkpoint?"
        )
    boundary_ok = start_offset == data_start
    while offset < end:
        if offset + _REC_HEADER.size > end:
            raise ValueError("truncated packed shard record")
        (
            n_real, n_cold, n_dict, n_dict_occ, n_hot, n_h8,
            slots_code, rec_bytes,
        ) = _REC_HEADER.unpack_from(mm, offset)
        if rec_bytes <= 0 or offset + rec_bytes > end:
            raise ValueError("truncated packed shard record")
        next_offset = offset + rec_bytes
        if offset == start_offset:
            boundary_ok = True
        if offset >= start_offset:
            if not boundary_ok:
                raise ValueError(
                    f"start_offset {start_offset} is not a record "
                    "boundary"
                )
            # range-check every header count against the shard meta
            # BEFORE sizing planes: a corrupt/adversarial header must
            # raise here, not address planes out of bounds or hand the
            # model a silently-wrong batch (wirefuzz pins this)
            ok = (
                0 <= n_real <= b
                and 0 <= n_cold <= b * kc
                and 0 <= n_dict_occ <= n_cold
                and 0 <= n_dict <= n_dict_occ
                and n_dict <= dict_cap
                and 0 <= n_hot <= b * kh
                and 0 <= n_h8 <= n_hot
                and 0 <= slots_code < len(C._SLOT_DTYPES)
            )
            if not ok:
                raise ValueError(
                    "packed shard record header counts out of range "
                    f"(n_real={n_real} n_cold={n_cold} n_dict={n_dict} "
                    f"n_dict_occ={n_dict_occ} n_hot={n_hot} n_h8={n_h8} "
                    f"slots_code={slots_code} vs batch_size={b} "
                    f"cold_nnz={kc} hot_nnz={kh}) — corrupt record"
                )
            counts = {
                "n_real": n_real, "n_cold": n_cold, "n_dict": n_dict,
                "n_dict_occ": n_dict_occ, "n_hot": n_hot,
                "n_h8": n_h8, "slots_code": slots_code,
            }
            specs = C.plane_specs(
                batch_size=b,
                cold_nnz=kc,
                hot_nnz_cap=kh,
                key_bytes=key_bytes,
                hx16=hx16,
                slots_code=slots_code,
                dict_cap=dict_cap,
                granule_div=gdiv,
                granule_min=gmin,
                **{k: counts[k] for k in (
                    "n_cold", "n_dict", "n_dict_occ", "n_hot", "n_h8"
                )},
            )
            pos = offset + _REC_HEADER.size
            planes = {}
            for name, shape, dtype in specs:
                count = int(np.prod(shape))
                planes[name] = np.frombuffer(
                    mm, dtype, count=count, offset=pos
                ).reshape(shape)
                pos += count * dtype.itemsize
            if pos > next_offset:
                raise ValueError("packed shard record size mismatch")
            yield C.from_planes(meta, counts, planes), offset, next_offset
        offset = next_offset
    if not boundary_ok and start_offset != offset:
        raise ValueError(
            f"start_offset {start_offset} is not a record boundary"
        )


def iter_compact_batches(
    f: BinaryIO, start_offset: int = 0
):
    """Yield (CompactBatch, offset, next_offset) from a v2 shard (raises
    on v1 — those records hold padded arrays, not compact planes)."""
    f.seek(0)
    meta, _ = read_header(f)
    if meta.get("version", 1) != 2:
        raise ValueError("iter_compact_batches requires a v2 packed shard")
    yield from _iter_records_v2(f, meta, start_offset)


def iter_batches(
    f: BinaryIO, start_offset: int = 0
) -> Iterator[tuple[Batch, int, int]]:
    """Yield (batch, offset, next_offset).  Batch arrays are read-only
    zero-copy views of each record's buffer — the whole point of this
    format; copy before mutating.

    Records are mmap-backed: a consumer that only touches some fields
    (the compact wire reads keys/mask/labels and skips vals/slots —
    half the record) never pages the rest in, which roughly doubles the
    measured host feed rate over the old read()-a-record path.  The
    mmap outlives ``f`` (numpy views hold it via .base), so batches may
    be used after the file is closed.

    v2 shards hold CompactBatch records; this interface expands them
    to padded Batches (byte-exact — io/compact.py) so every consumer
    of the v1 contract keeps working.  Consumers that can feed the
    dict wire directly use ``iter_compact_batches`` and skip both the
    expansion and the re-compaction (ShardLoader emit_compact)."""
    import mmap

    f.seek(0)
    meta, data_start = read_header(f)
    if meta.get("version", 1) == 2:
        for cb, off, noff in _iter_records_v2(f, meta, start_offset):
            yield cb.expand(), off, noff
        return
    fields, rec_size = _layout(meta)
    offset = max(int(start_offset), data_start)
    if (offset - data_start) % rec_size:
        raise ValueError(
            f"start_offset {start_offset} is not a record boundary"
        )
    try:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        if hasattr(mmap, "MADV_SEQUENTIAL"):
            mm.madvise(mmap.MADV_SEQUENTIAL)
    except (ValueError, OSError):
        mm = None  # unmmapable stream (pipe, empty file): read() path

    def record(buf, base):
        pos = base
        kw = {}
        for name, shape, dtype in fields:
            kw[name] = np.frombuffer(
                buf, dtype, count=int(np.prod(shape)), offset=pos
            ).reshape(shape)
            pos += int(np.prod(shape)) * dtype.itemsize
        return Batch(**kw)

    if mm is not None:
        end = len(mm)
        if offset > end:
            # A resume cursor past EOF means the cache was rebuilt
            # shorter since the checkpoint — distinguish it from a
            # partial trailing record, and fail the same way the CSR
            # cache does (binary.py 'start_offset ... past the shard
            # end') rather than silently dropping the shard remainder.
            raise ValueError(
                f"resume offset {offset} is past the packed shard end "
                f"{end} — was the cache rebuilt since the checkpoint?"
            )
        while offset + rec_size <= end:
            yield record(mm, offset), offset, offset + rec_size
            offset += rec_size
        if offset < end:
            raise ValueError("truncated packed shard record")
        return
    f.seek(offset)
    while True:
        buf = f.read(rec_size)
        if not buf:
            return
        if len(buf) != rec_size:
            raise ValueError("truncated packed shard record")
        yield record(buf, 0), offset, offset + rec_size
        offset += rec_size


def shard_example_count(path: str) -> int:
    # metadata peek (header totals), not a streamed I/O boundary — the
    # record-walk readers carry the loader.* sites (xf: ignore[XF018])
    with open(path, "rb") as f:
        meta, _ = read_header(f)
        return int(meta["examples"])


def split_shard_v2(
    src: str, dst_prefix: str, num_shards: int
) -> list[str]:
    """Split one packed-v2 shard into up to ``num_shards`` contiguous
    sub-shards ``<dst_prefix>-%05d`` — the corpus shape the input
    fan-out (io/fanout.py) distributes across reader streams.

    Records are self-contained (each carries its counts header and its
    planes), so the split is a raw byte copy over the validated record
    walk: no decode, no re-encode, and the concatenation of the
    sub-shards' record streams is byte-identical to the source's.  Each
    sub-shard gets the source header with its own batches/examples
    totals; writers use the shared tail-safe tmp+fsync+os.replace
    protocol.  Returns the written paths (fewer than ``num_shards``
    when the source has fewer records)."""
    import mmap

    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    failpoint("packed.write")
    with open(src, "rb") as f:
        meta, data_start = read_header(f)
        if meta.get("version", 1) != 2:
            raise ValueError("split_shard_v2 requires a v2 packed shard")
        try:
            # O(record) resident memory at any shard size (the same
            # mmap discipline as the readers); only unmmapable streams
            # pay a full buffer
            blob: mmap.mmap | bytes = mmap.mmap(
                f.fileno(), 0, access=mmap.ACCESS_READ
            )
        except (ValueError, OSError):
            f.seek(0)
            blob = f.read()
        # record spans via the same walk _iter_records_v2 validates
        spans: list[tuple[int, int, int]] = []  # (offset, next, n_real)
        offset = data_start
        end = len(blob)
        while offset < end:
            if offset + _REC_HEADER.size > end:
                raise ValueError("truncated packed shard record")
            fields = _REC_HEADER.unpack_from(blob, offset)
            n_real, rec_bytes = fields[0], fields[7]
            if rec_bytes <= 0 or offset + rec_bytes > end:
                raise ValueError("truncated packed shard record")
            spans.append((offset, offset + rec_bytes, n_real))
            offset += rec_bytes
        n_out = max(1, min(num_shards, len(spans)))
        per = -(-len(spans) // n_out) if spans else 0
        paths = []
        for i in range(n_out):
            chunk = spans[i * per: (i + 1) * per]
            if not chunk:
                break
            dst = f"{dst_prefix}-{i:05d}"
            tmp = f"{dst}.tmp.{os.getpid()}"
            header = dict(meta)
            try:
                with open(tmp, "wb") as out:
                    hdr_len = container.write_placeholder_header(
                        out, MAGIC, header, ("batches", "examples")
                    )
                    for lo, hi, _ in chunk:
                        out.write(blob[lo:hi])
                    header.update({
                        "batches": len(chunk),
                        "examples": int(sum(r for _, _, r in chunk)),
                    })
                    container.rewrite_header(out, MAGIC, header, hdr_len)
                    out.flush()
                    os.fsync(out.fileno())
                os.replace(tmp, dst)
            finally:
                if os.path.exists(tmp):
                    os.remove(tmp)
            paths.append(dst)
    return paths


def convert_shard(
    src: str,
    dst: str,
    *,
    batch_size: int,
    max_nnz: int,
    table_size: int,
    hot_size: int = 0,
    hot_nnz: int = 0,
    hash_mode: bool = True,
    hash_seed: int = 0,
    block_mib: float = 8,
    remap: np.ndarray | None = None,
    parse_fn=None,
    fmt: str = "auto",
) -> dict:
    """Pack one shard (text or CSR-binary — ShardLoader sniffs) into
    device-ready batches.  ``fmt``: "v1" = padded-array records, "v2" =
    compacted records (io/compact.py — smaller and pre-compacted for
    the dict wire), "auto" = v2 whenever the compaction invariants hold
    (hash mode; u8 per-row counts; hot ids fit the tiered encoding)."""
    from xflow_tpu.io.loader import ShardLoader

    loader = ShardLoader(
        src,
        batch_size=batch_size,
        max_nnz=max_nnz,
        table_size=table_size,
        block_mib=max(1, int(block_mib)),
        hash_mode=hash_mode,
        hash_seed=hash_seed,
        parse_fn=parse_fn,
        remap=remap,
        hot_size=hot_size,
        hot_nnz=hot_nnz,
    )
    loader.block_bytes = max(1, int(block_mib * (1 << 20)))
    meta = {
        "batch_size": batch_size,
        "cold_nnz": max_nnz,
        "hot_nnz": hot_nnz if hot_size else 0,
        "hot_size": hot_size,
        "table_size": table_size,
        "hash_mode": bool(hash_mode),
        "hash_seed": int(hash_seed),
        "remap_sha256": remap_digest(remap),
    }
    if fmt not in ("auto", "v1", "v2"):
        raise ValueError(f"unknown packed format {fmt!r}")
    v2_ok = (
        bool(hash_mode)
        and max_nnz <= 255
        and (hot_nnz if hot_size else 0) <= 255
        and (not hot_size or hot_size <= 1 << 16)
    )
    if fmt == "v2" and not v2_ok:
        raise ValueError(
            "packed v2 requires hash_mode, max_nnz/hot_nnz <= 255 "
            "and hot_size <= 2^16"
        )
    writer = write_shard_v2 if (fmt == "v2" or (fmt == "auto" and v2_ok)) \
        else write_shard
    return writer(
        dst, meta, (b for b, _ in loader.iter_batches())
    )


def main(argv=None) -> int:
    import argparse

    from xflow_tpu.io import freq
    from xflow_tpu.trainer import find_shards

    p = argparse.ArgumentParser(
        prog="xflow_tpu.io.packed",
        description="pack shards into device-ready batch caches",
    )
    p.add_argument("--train", required=True, help="text/CSR shard prefix")
    p.add_argument("--out", required=True, help="output shard prefix")
    p.add_argument("--batch-size", type=int, required=True)
    p.add_argument("--max-nnz", type=int, required=True)
    p.add_argument("--table-size-log2", type=int, required=True)
    p.add_argument("--hot-size-log2", type=int, default=0)
    p.add_argument("--hot-nnz", type=int, default=0)
    p.add_argument("--remap", help=".npy hot remap (trainer's remap.npy)")
    p.add_argument("--no-hash", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--block-mib", type=float, default=8)
    p.add_argument(
        "--format", choices=("auto", "v1", "v2"), default="auto",
        help="record format: v2 = compacted records (default when "
        "eligible; docs/MIGRATION.md)",
    )
    a = p.parse_args(argv)
    remap = freq.load_remap(a.remap) if a.remap else None
    if a.hot_size_log2 and remap is None:
        p.error("--hot-size-log2 requires --remap (trainer's remap.npy)")
    for i, src in enumerate(find_shards(a.train)):
        dst = f"{a.out}-{i:05d}" if src != a.train else a.out
        meta = convert_shard(
            src,
            dst,
            batch_size=a.batch_size,
            max_nnz=a.max_nnz,
            table_size=1 << a.table_size_log2,
            hot_size=(1 << a.hot_size_log2) if a.hot_size_log2 else 0,
            hot_nnz=a.hot_nnz,
            hash_mode=not a.no_hash,
            hash_seed=a.seed,
            block_mib=a.block_mib,
            remap=remap,
            fmt=a.format,
        )
        print(
            f"{src} -> {dst}: {meta['examples']} examples in "
            f"{meta['batches']} batches"
        )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
